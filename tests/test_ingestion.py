"""Ingestion readers + record transformer chain tests.

Parity: core/data/readers/ (CSV/JSON/GenericRow/PinotSegment readers) and
core/data/recordtransformer/ (CompoundTransformer ordering: expression →
time → data-type → null → sanitation). End state: a segment built from a
CSV file answers queries identically to the same rows built in-memory.
"""
import json
import os
import tempfile

import numpy as np
import pytest

from fixtures import make_schema, make_table_config

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.schema import (Schema, TimeUnit, dimension, metric,
                                     time_field)
from pinot_tpu.engine import QueryEngine
from pinot_tpu.ingestion import (CompoundTransformer, CSVRecordReader,
                                 DataTypeTransformer, GenericRowRecordReader,
                                 JSONRecordReader, NullValueTransformer,
                                 SanitationTransformer, SegmentRecordReader)
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.tools.create_segment import create_segment_from_file

ROWS = [
    {"teamID": "BOS", "league": "AL", "playerName": "ted",
     "position": ["LF", "RF"], "runs": 10, "hits": 20, "average": 0.34,
     "salary": 100.5, "yearID": 1999},
    {"teamID": "NYA", "league": "AL", "playerName": "babe",
     "position": ["P"], "runs": 15, "hits": 25, "average": 0.39,
     "salary": 200.25, "yearID": 2001},
    {"teamID": "BOS", "league": "AL", "playerName": "carl",
     "position": ["CF"], "runs": 5, "hits": 8, "average": 0.28,
     "salary": 50.0, "yearID": 2005},
]


def _write_csv(path):
    with open(path, "w") as fh:
        fh.write("teamID,league,playerName,position,runs,hits,average,"
                 "salary,yearID\n")
        for r in ROWS:
            fh.write(",".join([
                r["teamID"], r["league"], r["playerName"],
                ";".join(r["position"]), str(r["runs"]), str(r["hits"]),
                str(r["average"]), str(r["salary"]), str(r["yearID"]),
            ]) + "\n")


def _check_segment_queries(seg_dir):
    eng = QueryEngine.from_dirs([seg_dir])
    resp = eng.query("SELECT COUNT(*), SUM(runs) FROM baseballStats")
    assert int(resp.aggregation_results[0].value) == 3
    assert float(resp.aggregation_results[1].value) == 30.0
    resp = eng.query("SELECT SUM(hits) FROM baseballStats "
                     "WHERE teamID = 'BOS'")
    assert float(resp.aggregation_results[0].value) == 28.0
    resp = eng.query("SELECT COUNT(*) FROM baseballStats "
                     "WHERE position = 'RF'")
    assert int(resp.aggregation_results[0].value) == 1


def test_csv_reader_to_segment_to_query():
    base = tempfile.mkdtemp()
    csv_path = os.path.join(base, "in.csv")
    _write_csv(csv_path)
    seg_dir = os.path.join(base, "seg")
    meta = create_segment_from_file(csv_path, "csv", make_schema(), seg_dir,
                                    make_table_config(),
                                    segment_name="csv_seg_0")
    assert meta.total_docs == 3
    assert meta.start_time == 1999 and meta.end_time == 2005
    _check_segment_queries(seg_dir)


def test_json_reader_to_segment_to_query():
    base = tempfile.mkdtemp()
    json_path = os.path.join(base, "in.json")
    with open(json_path, "w") as fh:
        for r in ROWS:
            fh.write(json.dumps(r) + "\n")
    seg_dir = os.path.join(base, "seg")
    create_segment_from_file(json_path, "json", make_schema(), seg_dir,
                             make_table_config())
    _check_segment_queries(seg_dir)


def test_json_array_format():
    base = tempfile.mkdtemp()
    json_path = os.path.join(base, "arr.json")
    with open(json_path, "w") as fh:
        json.dump(ROWS, fh)
    rows = list(JSONRecordReader(json_path))
    assert len(rows) == 3 and rows[1]["playerName"] == "babe"


def test_csv_reader_mv_and_nulls():
    base = tempfile.mkdtemp()
    p = os.path.join(base, "x.csv")
    with open(p, "w") as fh:
        fh.write("teamID,position,runs\nBOS,LF;RF,5\nNYA,,\n")
    rows = list(CSVRecordReader(p, make_schema()))
    assert rows[0]["position"] == ["LF", "RF"]
    assert rows[1]["position"] is None and rows[1]["runs"] is None


def test_transformer_chain():
    schema = make_schema()
    t = CompoundTransformer(schema)
    # strings coerced, MV normalized, nulls filled, NULs stripped
    row = t.transform({"teamID": "B\x00OS", "league": "AL",
                       "playerName": "x" * 600, "position": "LF",
                       "runs": "7", "hits": 3.0, "average": "0.5",
                       "yearID": "1998"})
    assert row["teamID"] == "BOS"
    assert len(row["playerName"]) == 512
    assert row["position"] == ["LF"]
    assert row["runs"] == 7 and isinstance(row["runs"], int)
    assert row["salary"] == 0.0          # missing metric → default fill
    assert row["yearID"] == 1998


def test_expression_transformer_derives_column():
    schema = Schema("t", [dimension("a", DataType.INT),
                          metric("b", DataType.LONG),
                          time_field("days", DataType.INT, TimeUnit.DAYS)])
    t = CompoundTransformer(schema,
                            expressions={"days": "time_convert(hours,"
                                                 "'HOURS','DAYS')"})
    row = t.transform({"a": 1, "b": 2, "hours": 48})
    assert row["days"] == 2


def test_time_transformer_incoming_unit():
    schema = make_schema()        # yearID in DAYS
    t = CompoundTransformer(schema, incoming_time_unit=TimeUnit.HOURS)
    row = t.transform({"teamID": "BOS", "yearID": 48})   # 48h → 2 days
    assert row["yearID"] == 2


def test_segment_record_reader_roundtrip():
    base = tempfile.mkdtemp()
    csv_path = os.path.join(base, "in.csv")
    _write_csv(csv_path)
    seg_dir = os.path.join(base, "seg")
    create_segment_from_file(csv_path, "csv", make_schema(), seg_dir,
                             make_table_config())
    seg = ImmutableSegmentLoader.load(seg_dir)
    rows = list(SegmentRecordReader(seg))
    assert len(rows) == 3
    by_player = {r["playerName"]: r for r in rows}
    assert by_player["ted"]["runs"] == 10
    assert sorted(by_player["ted"]["position"]) == ["LF", "RF"]
    # rebuild a segment from the re-read rows: same answers
    seg2_dir = os.path.join(base, "seg2")
    from pinot_tpu.segment.creator import SegmentCreator
    SegmentCreator(make_schema(), make_table_config(),
                   segment_name="rebuilt").build(
        GenericRowRecordReader(rows), seg2_dir)
    _check_segment_queries(seg2_dir)


def test_batch_ingest_to_cluster():
    """Parity: SegmentCreationJob + SegmentTarPushJob — one segment per
    input file, pushed to the controller, queryable via the cluster."""
    from pinot_tpu.tools.batch_ingest import batch_ingest
    from pinot_tpu.tools.cluster import EmbeddedCluster

    base = tempfile.mkdtemp()
    paths = []
    for i in range(3):
        p = os.path.join(base, f"in_{i}.csv")
        _write_csv(p)
        paths.append(p)
    cluster = EmbeddedCluster(os.path.join(base, "cluster"), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        names = batch_ingest(paths, "csv", make_schema(),
                             os.path.join(base, "segs"),
                             "baseballStats_OFFLINE",
                             cluster.controller.manager,
                             make_table_config())
        assert len(names) == 3
        resp = cluster.query("SELECT COUNT(*), SUM(runs) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == 9
        assert float(resp.aggregation_results[1].value) == 90.0
    finally:
        cluster.stop()


def test_parallel_batch_ingest_rest_push():
    """Parity: SegmentCreationJob runs one MAPPER PROCESS per input file
    in parallel and SegmentTarPushJob POSTs the artifacts — 4 input
    files build concurrently on a process pool and push over the
    controller's REST upload endpoint."""
    from pinot_tpu.client import ControllerClient
    from pinot_tpu.tools.batch_ingest import (batch_build_segments,
                                              push_segments)
    from pinot_tpu.tools.cluster import EmbeddedCluster

    base = tempfile.mkdtemp()
    paths = []
    for i in range(4):
        p = os.path.join(base, f"in_{i}.csv")
        _write_csv(p)
        paths.append(p)
    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=2, http=True)
    ctl = ControllerClient("127.0.0.1", cluster.controller_port)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        dirs = batch_build_segments(
            paths, "csv", make_schema(), os.path.join(base, "segs"),
            make_table_config(), max_workers=4, use_processes=True)
        assert len(dirs) == 4
        push_segments(dirs, lambda d: ctl.upload_segment_dir(
            "baseballStats_OFFLINE", d))
        resp = cluster.query("SELECT COUNT(*), SUM(runs) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == 12
        assert float(resp.aggregation_results[1].value) == 120.0
    finally:
        ctl.close()
        cluster.stop()


def test_poison_record_does_not_kill_realtime_consumer():
    """A record that decodes but fails type coercion must be dropped, not
    kill the partition consumer."""
    import time as _time

    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.common.table_config import (IndexingConfig,
                                               SegmentsConfig, TableConfig,
                                               TableType)

    base = tempfile.mkdtemp()
    stream = MemoryStream("poison", num_partitions=1)
    registry.register_stream_factory(
        "mem_poison", MemoryStreamConsumerFactory(stream, batch_size=8))
    cluster = EmbeddedCluster(base, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(TableConfig(
            "baseballStats", table_type=TableType.REALTIME,
            indexing_config=IndexingConfig(stream_configs={
                "stream.factory.name": "mem_poison",
                "stream.topic.name": "poison"}),
            segments_config=SegmentsConfig(replication=1)))
        good = dict(ROWS[0])
        bad = dict(ROWS[1])
        bad["runs"] = "not_a_number"
        stream.publish(good, partition=0)
        stream.publish(bad, partition=0)       # poison: dropped, not fatal
        stream.publish(dict(ROWS[2]), partition=0)
        deadline = _time.monotonic() + 10
        cnt = -1
        while _time.monotonic() < deadline:
            resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
            if not resp.exceptions:
                cnt = int(resp.aggregation_results[0].value)
                if cnt == 2:
                    break
            _time.sleep(0.05)
        assert cnt == 2
        rt = cluster.participants["Server_0"].realtime
        assert rt.consuming_state("baseballStats__0__0") == "CONSUMING"
    finally:
        cluster.stop()


def test_expression_transformer_scalar_literals():
    schema = Schema("t", [dimension("region", DataType.STRING),
                          metric("b", DataType.LONG)])
    from pinot_tpu.ingestion import ExpressionTransformer
    t = ExpressionTransformer({"region": "'west'"})
    assert t.transform({"b": 1})["region"] == "west"


def _arrow_rows_table():
    import pyarrow as pa
    return pa.table({
        "teamID": [r["teamID"] for r in ROWS],
        "league": [r["league"] for r in ROWS],
        "playerName": [r["playerName"] for r in ROWS],
        "position": [r["position"] for r in ROWS],
        "runs": [r["runs"] for r in ROWS],
        "hits": [r["hits"] for r in ROWS],
        "average": [r["average"] for r in ROWS],
        "salary": [r["salary"] for r in ROWS],
        "yearID": [r["yearID"] for r in ROWS],
    })


def test_parquet_reader_to_segment_to_query():
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq
    base = tempfile.mkdtemp()
    path = os.path.join(base, "in.parquet")
    pq.write_table(_arrow_rows_table(), path)
    seg_dir = os.path.join(base, "seg")
    meta = create_segment_from_file(path, "parquet", make_schema(), seg_dir,
                                    make_table_config(),
                                    segment_name="pq_seg_0")
    assert meta.total_docs == 3
    _check_segment_queries(seg_dir)


def test_orc_reader_to_segment_to_query():
    pa = pytest.importorskip("pyarrow")
    from pyarrow import orc as pa_orc
    base = tempfile.mkdtemp()
    path = os.path.join(base, "in.orc")
    pa_orc.write_table(_arrow_rows_table(), path)
    seg_dir = os.path.join(base, "seg")
    meta = create_segment_from_file(path, "orc", make_schema(), seg_dir,
                                    make_table_config(),
                                    segment_name="orc_seg_0")
    assert meta.total_docs == 3
    _check_segment_queries(seg_dir)


# ---------------------------------------------------------------------------
# Avro reader (hand-rolled writer here so the decoder is tested against an
# independent encoding of the spec, not against itself)
# ---------------------------------------------------------------------------

def _zz(n):
    out, u = b"", (n << 1) ^ (n >> 63) if n < 0 else n << 1
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _avro_str(s):
    b = s.encode("utf-8")
    return _zz(len(b)) + b


def _write_avro(path, codec="null"):
    import struct as _struct
    import zlib as _zlib
    schema = {
        "type": "record", "name": "Stat", "fields": [
            {"name": "teamID", "type": "string"},
            {"name": "league", "type": {"type": "enum", "name": "League",
                                        "symbols": ["AL", "NL"]}},
            {"name": "playerName", "type": ["null", "string"]},
            {"name": "position", "type": {"type": "array",
                                          "items": "string"}},
            {"name": "runs", "type": "int"},
            {"name": "hits", "type": "long"},
            {"name": "average", "type": "double"},
            {"name": "salary", "type": "float"},
            {"name": "yearID", "type": "int"},
        ]}
    body = b""
    for r in ROWS:
        body += _avro_str(r["teamID"])
        body += _zz(["AL", "NL"].index(r["league"]))
        body += _zz(1) + _avro_str(r["playerName"])  # union branch 1
        body += _zz(len(r["position"]))
        for p in r["position"]:
            body += _avro_str(p)
        body += _zz(0)  # array terminator
        body += _zz(r["runs"]) + _zz(r["hits"])
        body += _struct.pack("<d", r["average"])
        body += _struct.pack("<f", r["salary"])
        body += _zz(r["yearID"])
    if codec == "deflate":
        co = _zlib.compressobj(9, _zlib.DEFLATED, -15)
        body = co.compress(body) + co.flush()
    sync = b"S" * 16
    meta = (_zz(2) +
            _avro_str("avro.schema") + _avro_str(json.dumps(schema)) +
            _avro_str("avro.codec") + _avro_str(codec) +
            _zz(0))
    with open(path, "wb") as fh:
        fh.write(b"Obj\x01" + meta + sync)
        fh.write(_zz(len(ROWS)) + _zz(len(body)) + body + sync)


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_reader_to_segment_to_query(codec):
    base = tempfile.mkdtemp()
    path = os.path.join(base, "in.avro")
    _write_avro(path, codec)
    from pinot_tpu.ingestion import AvroRecordReader
    rows = list(AvroRecordReader(path))
    assert rows[0]["teamID"] == "BOS" and rows[0]["league"] == "AL"
    assert rows[1]["position"] == ["P"]
    assert rows[2]["hits"] == 8
    seg_dir = os.path.join(base, "seg")
    meta = create_segment_from_file(path, "avro", make_schema(), seg_dir,
                                    make_table_config(),
                                    segment_name="avro_seg_0")
    assert meta.total_docs == 3
    _check_segment_queries(seg_dir)


def test_avro_reader_rejects_garbage():
    base = tempfile.mkdtemp()
    path = os.path.join(base, "bad.avro")
    with open(path, "wb") as fh:
        fh.write(b"not avro at all")
    from pinot_tpu.ingestion import AvroRecordReader
    with pytest.raises(ValueError, match="not an Avro"):
        AvroRecordReader(path)


def test_thrift_reader_to_segment_to_query():
    """Parity: core/data/readers/ThriftRecordReader.java — TBinaryProtocol
    struct stream -> rows -> segment -> queries; unknown wire fields
    skipped, unset optionals -> None, field ids from config order."""
    from pinot_tpu.ingestion.thrift import (ThriftRecordReader,
                                            ThriftRecordReaderConfig,
                                            write_thrift_records)
    base = tempfile.mkdtemp()
    path = os.path.join(base, "in.thrift")
    # float() the average/salary so the writer emits DOUBLE fields, and
    # add an extra field NOT in the reader config (skipped on read)
    rows = [dict(r, average=float(r["average"]),
                 salary=float(r["salary"]), _extra="ignored") for r in ROWS]
    names = ["teamID", "league", "playerName", "position", "runs", "hits",
             "average", "salary", "yearID", "_extra"]
    write_thrift_records(path, rows,
                         {n: i + 1 for i, n in enumerate(names)})
    cfg = ThriftRecordReaderConfig(names[:-1])     # _extra unprojected
    got = list(ThriftRecordReader(path, cfg))
    assert len(got) == 3
    assert got[0]["teamID"] == "BOS" and got[0]["position"] == ["LF", "RF"]
    assert got[2]["hits"] == 8 and "_extra" not in got[0]
    # unset optional field -> None
    path2 = os.path.join(base, "opt.thrift")
    write_thrift_records(path2, [{"teamID": "BOS"}], {"teamID": 1,
                                                      "playerName": 2})
    r0 = list(ThriftRecordReader(
        path2, ThriftRecordReaderConfig(["teamID", "playerName"])))[0]
    assert r0["teamID"] == "BOS" and r0["playerName"] is None
    # full path through the factory + segment build + queries
    seg_dir = os.path.join(base, "seg")
    meta = create_segment_from_file(
        path, "thrift", make_schema(), seg_dir, make_table_config(),
        segment_name="thrift_seg_0", fields=names[:-1])
    assert meta.total_docs == 3
    _check_segment_queries(seg_dir)


def test_thrift_declared_bytes_fields_skip_utf8_decode():
    """A BINARY thrift field whose payload happens to be valid UTF-8
    must stay `bytes` when declared — via the reader config or the
    target schema's BYTES column type (ADVICE.md)."""
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import Schema, dimension
    from pinot_tpu.ingestion.thrift import (ThriftRecordReader,
                                            ThriftRecordReaderConfig,
                                            write_thrift_records)
    base = tempfile.mkdtemp()
    path = os.path.join(base, "b.thrift")
    payload = b"looks-like-text"            # valid UTF-8 on purpose
    rows = [{"name": "a", "blob": payload},
            {"name": "b", "blob": b"\xff\xfe raw"}]
    write_thrift_records(path, rows, {"name": 1, "blob": 2})
    # undeclared: the valid-UTF-8 payload silently becomes str (the
    # wire cannot distinguish) — per-row type instability
    got = list(ThriftRecordReader(
        path, ThriftRecordReaderConfig(["name", "blob"])))
    assert isinstance(got[0]["blob"], str)
    assert isinstance(got[1]["blob"], bytes)
    # declared on the config: both rows stay bytes
    got = list(ThriftRecordReader(
        path, ThriftRecordReaderConfig(["name", "blob"],
                                       bytes_fields=["blob"])))
    assert got[0]["blob"] == payload and isinstance(got[0]["blob"], bytes)
    assert got[1]["blob"] == b"\xff\xfe raw"
    # declared through the schema's BYTES column type
    schema = Schema("t", [dimension("name", DataType.STRING),
                          dimension("blob", DataType.BYTES)])
    got = list(ThriftRecordReader(
        path, ThriftRecordReaderConfig(["name", "blob"]), schema=schema))
    assert isinstance(got[0]["blob"], bytes)
    assert isinstance(got[0]["name"], str)


def test_thrift_nested_struct_and_map_round_trip():
    from pinot_tpu.ingestion.thrift import (_BinaryProtocolReader,
                                            write_thrift_records)
    import struct as _struct
    base = tempfile.mkdtemp()
    path = os.path.join(base, "m.thrift")
    write_thrift_records(path, [{"m": {"a": 1, "b": 2}, "l": [True, False]}],
                         {"m": 1, "l": 2})
    with open(path, "rb") as fh:
        rec = _BinaryProtocolReader(fh.read()).read_struct()
    assert rec[1] == {"a": 1, "b": 2} and rec[2] == [True, False]
    # nested struct value (type 12) decodes recursively
    inner = b"\x0b" + _struct.pack(">h", 1) + _struct.pack(">i", 2) + \
        b"hi" + b"\x00"
    outer = b"\x0c" + _struct.pack(">h", 5) + inner + b"\x00"
    rec = _BinaryProtocolReader(outer).read_struct()
    assert rec[5] == {1: "hi"}


def test_preprocessing_job_partitions_and_sorts():
    """Parity: SegmentPreprocessingJob.java:59 — rows are shuffled into
    one output file per partition (and sorted within it) before the
    segment build, so each built segment carries exactly ONE partition
    id and the broker prunes whole segments on EQ filters."""
    import json as _json

    from pinot_tpu.common.partition import make_partition_function
    from pinot_tpu.tools.batch_ingest import (batch_build_segments,
                                              preprocess_inputs)
    from pinot_tpu.tools.cluster import EmbeddedCluster

    base = tempfile.mkdtemp()
    # 3 unpartitioned input files
    paths = []
    for i in range(3):
        p = os.path.join(base, f"in_{i}.csv")
        _write_csv(p)
        paths.append(p)

    n_part = 2
    outs = preprocess_inputs(paths, "csv", make_schema(),
                             os.path.join(base, "shuffled"),
                             partition_column="teamID",
                             num_partitions=n_part,
                             partition_function="murmur",
                             sort_column="yearID")
    assert len(outs) == n_part
    fn = make_partition_function("murmur", n_part)
    total = 0
    for p, path in enumerate(outs):
        years = []
        with open(path) as fh:
            for line in fh:
                row = _json.loads(line)
                assert fn.get_partition(row["teamID"]) == p
                years.append(int(row["yearID"]))
                total += 1
        assert years == sorted(years)        # sorted within partition
    assert total == 9                        # nothing lost in the shuffle

    # build from the shuffled files with a partition-aware table config;
    # each segment's recorded partition metadata is a single id
    cfg = make_table_config()
    cfg.indexing_config.segment_partition_config = {
        "teamID": {"functionName": "murmur", "numPartitions": n_part}}
    dirs = batch_build_segments(outs, "json", make_schema(),
                                os.path.join(base, "segs"), cfg,
                                use_processes=False)
    from pinot_tpu.segment.metadata import SegmentMetadata
    part_sets = []
    for d in dirs:
        cm = SegmentMetadata.load(d).columns["teamID"]
        assert cm.partition_function.lower() == "murmur"
        part_sets.append(tuple(cm.partitions))
    assert all(len(s) == 1 for s in part_sets), part_sets
    assert set(part_sets) == {(0,), (1,)}

    # the broker prunes the other partition's segment before scatter
    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(cfg)
        for d in dirs:
            cluster.upload_segment("baseballStats_OFFLINE", d)
        team = "BOS"
        resp = cluster.query("SELECT COUNT(*) FROM baseballStats "
                             f"WHERE teamID = '{team}'")
        # partition pruning cut the fan-out to one segment's worth of
        # processing (the other partition's segment is eliminated
        # broker-side before scatter)
        assert resp.num_segments_processed <= 1, resp.to_json()
        rows = 0
        for path in outs:
            with open(path) as fh:
                rows += sum(1 for line in fh
                            if _json.loads(line)["teamID"] == team)
        assert int(resp.aggregation_results[0].value) == rows
    finally:
        cluster.stop()
