"""Randomized query-generator correctness harness: engine vs numpy oracle.

Parity: the reference's randomized integration-test tier —
pinot-integration-tests/.../QueryGenerator.java:48-65,318-332 generates
random PQL (COMPARISON/IN/BETWEEN predicates joined by AND/OR;
SUM/MIN/MAX/AVG/COUNT/DISTINCTCOUNT aggregations; group-by; selection
with ORDER BY/LIMIT) and compares every result against H2 loaded from the
same rows (ClusterIntegrationTestUtils).  Here the oracle is the
independent numpy implementation in tests/oracle.py, the engine runs the
real plan maker + kernels + combine + reduce over two real segments, and
every query is checked on BOTH the device path and the host fallback.

Seeded, so failures are reproducible; on failure the PQL is in the assert
message.
"""
import math
import random
import tempfile

import numpy as np
import pytest

from fixtures import TEAMS, build_segment
from oracle import Oracle

from pinot_tpu.engine import QueryEngine

N_PER_SEG = 2_500
SEED = 20260730
N_AGG, N_GROUP, N_SEL = 14, 12, 12


@pytest.fixture(scope="module")
def setup():
    tmp1, tmp2 = tempfile.mkdtemp(), tempfile.mkdtemp()
    seg1, cols1 = build_segment(tmp1, n=N_PER_SEG, seed=11)
    seg2, cols2 = build_segment(tmp2, n=N_PER_SEG, seed=12)
    cols = {}
    for k in cols1:
        if isinstance(cols1[k], list):  # MV list-of-lists
            cols[k] = cols1[k] + cols2[k]
        else:
            cols[k] = np.concatenate([cols1[k], cols2[k]])
    engine = QueryEngine([seg1, seg2])
    host_engine = QueryEngine([seg1, seg2], use_device=False)
    # mesh engine: the two segments have DIFFERENT per-segment
    # dictionaries (independent seeds), so this sweeps the randomized
    # suite over the union-dictionary sharded device combine
    from pinot_tpu.parallel import make_mesh
    mesh_engine = QueryEngine([seg1, seg2], mesh=make_mesh())
    return engine, host_engine, mesh_engine, Oracle(cols)


# ---------------------------------------------------------------------------
# Generator: every draw yields (pql_fragment, oracle_equivalent)
# ---------------------------------------------------------------------------

class Gen:
    def __init__(self, rng: random.Random, oracle: Oracle):
        self.rng = rng
        self.oracle = oracle

    # -- predicates --------------------------------------------------------
    def predicate(self):
        r = self.rng
        kind = r.choice(["eq_team", "neq_league", "in_team", "not_in_team",
                         "between_year", "range_year", "range_runs",
                         "range_hits", "range_salary", "eq_player",
                         "eq_position_mv"])
        if kind == "eq_team":
            v = r.choice(TEAMS)
            return f"teamID = '{v}'", lambda row: row["teamID"] == v
        if kind == "neq_league":
            v = r.choice(["AL", "NL"])
            return f"league <> '{v}'", lambda row: row["league"] != v
        if kind == "in_team":
            vs = r.sample(TEAMS, r.randint(2, 5))
            lst = ", ".join(f"'{v}'" for v in vs)
            s = set(vs)
            return f"teamID IN ({lst})", lambda row: row["teamID"] in s
        if kind == "not_in_team":
            vs = r.sample(TEAMS, r.randint(2, 4))
            lst = ", ".join(f"'{v}'" for v in vs)
            s = set(vs)
            return f"teamID NOT IN ({lst})", lambda row: row["teamID"] not in s
        if kind == "between_year":
            a = r.randint(1990, 2015)
            b = a + r.randint(0, 10)
            return (f"yearID BETWEEN {a} AND {b}",
                    lambda row: a <= row["yearID"] <= b)
        if kind == "range_year":
            v = r.randint(1992, 2018)
            op = r.choice([">", ">=", "<", "<="])
            return (f"yearID {op} {v}",
                    lambda row, op=op, v=v: _cmp(row["yearID"], op, v))
        if kind == "range_runs":
            v = r.randint(5, 140)
            op = r.choice([">", ">=", "<", "<="])
            return (f"runs {op} {v}",
                    lambda row, op=op, v=v: _cmp(row["runs"], op, v))
        if kind == "range_hits":
            v = r.randint(10, 240)
            op = r.choice([">", "<"])
            return (f"hits {op} {v}",
                    lambda row, op=op, v=v: _cmp(row["hits"], op, v))
        if kind == "range_salary":
            v = round(r.uniform(1e4, 9e5), 2)
            op = r.choice([">", "<"])
            return (f"salary {op} {v}",
                    lambda row, op=op, v=v: _cmp(row["salary"], op, v))
        if kind == "eq_player":
            v = f"player_{r.randint(0, 996):03d}"
            return f"playerName = '{v}'", lambda row: row["playerName"] == v
        # MV membership
        v = r.choice(["P", "C", "1B", "SS", "CF"])
        return f"position = '{v}'", lambda row: v in row["position"]

    def where(self):
        """0-3 predicates joined by AND or OR; returns (sql, mask)."""
        r = self.rng
        k = r.randint(0, 3)
        if k == 0:
            return "", self.oracle.mask(lambda row: True)
        preds = [self.predicate() for _ in range(k)]
        joiner = r.choice([" AND ", " OR "])
        sql = " WHERE " + joiner.join(p[0] for p in preds)
        fns = [p[1] for p in preds]
        if joiner == " AND ":
            fn = lambda row: all(f(row) for f in fns)
        else:
            fn = lambda row: any(f(row) for f in fns)
        return sql, self.oracle.mask(fn)

    # -- aggregations ------------------------------------------------------
    AGGS = [
        ("COUNT(*)", "count", None, "exact"),
        ("SUM(runs)", "sum", "runs", "exact"),
        ("SUM(hits)", "sum", "hits", "exact"),
        ("SUM(salary)", "sum", "salary", "approx"),
        ("MIN(runs)", "min", "runs", "exact"),
        ("MIN(average)", "min", "average", "approx"),
        ("MAX(hits)", "max", "hits", "exact"),
        ("MAX(salary)", "max", "salary", "approx"),
        ("AVG(runs)", "avg", "runs", "approx"),
        ("AVG(hits)", "avg", "hits", "approx"),
        ("MINMAXRANGE(runs)", "minmaxrange", "runs", "exact"),
        ("DISTINCTCOUNT(teamID)", "distinctcount", "teamID", "exact"),
        ("DISTINCTCOUNT(yearID)", "distinctcount", "yearID", "exact"),
        ("DISTINCTCOUNT(playerName)", "distinctcount", "playerName", "exact"),
    ]

    def aggs(self):
        return self.rng.sample(self.AGGS, self.rng.randint(1, 3))


def _cmp(x, op, v):
    if op == ">":
        return x > v
    if op == ">=":
        return x >= v
    if op == "<":
        return x < v
    return x <= v


def _check_agg(resp, i, oracle, name, col, mode, m, pql, label):
    got = resp.aggregation_results[i].value
    if name == "count":
        assert int(got) == oracle.count(m), (pql, label)
        return
    if int(m.sum()) == 0:
        return  # empty-result sentinel conventions covered by golden tests
    if name == "distinctcount":
        assert int(got) == oracle.distinctcount(col, m), (pql, label)
        return
    exp = getattr(oracle, name)(col, m)
    if mode == "exact":
        assert float(got) == pytest.approx(exp, rel=1e-9), (pql, label)
    else:
        assert float(got) == pytest.approx(exp, rel=1e-3, abs=1e-6), \
            (pql, label)


# ---------------------------------------------------------------------------


def test_random_aggregation_queries(setup):
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED), oracle)
    for qi in range(N_AGG):
        where, m = gen.where()
        aggs = gen.aggs()
        pql = ("SELECT " + ", ".join(a[0] for a in aggs) +
               " FROM baseballStats" + where)
        for e, label in [(engine, "device"), (host_engine, "host"),
                 (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            for i, (_, name, col, mode) in enumerate(aggs):
                _check_agg(resp, i, oracle, name, col, mode, m, pql, label)


def test_random_group_by_queries(setup):
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED + 1), oracle)
    dims_pool = ["teamID", "league", "yearID"]
    for qi in range(N_GROUP):
        where, m = gen.where()
        aggs = gen.aggs()
        dims = gen.rng.sample(dims_pool, gen.rng.randint(1, 2))
        pql = ("SELECT " + ", ".join(a[0] for a in aggs) +
               " FROM baseballStats" + where +
               " GROUP BY " + ", ".join(dims) + " TOP 2000")
        for e, label in [(engine, "device"), (host_engine, "host"),
                 (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            for i, (_, name, col, mode) in enumerate(aggs):
                expected = oracle.group_by(
                    dims, m, (name, col) if name != "count" else
                    ("count", None))
                got = {tuple(str(k) for k in g["group"]): g["value"]
                       for g in resp.aggregation_results[i].group_by_result}
                # group keys come back as strings over the wire
                exp_norm = {tuple(str(k) for k in key): v
                            for key, v in expected.items()}
                assert set(got) == set(exp_norm), (pql, label, i)
                for key, v in exp_norm.items():
                    if name in ("count", "distinctcount"):
                        assert int(float(got[key])) == int(v), \
                            (pql, label, key)
                    elif mode == "exact":
                        assert float(got[key]) == pytest.approx(
                            v, rel=1e-9), (pql, label, key)
                    else:
                        assert float(got[key]) == pytest.approx(
                            v, rel=1e-3, abs=1e-6), (pql, label, key)


def test_random_group_by_having_queries(setup):
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED + 3), oracle)
    for qi in range(6):
        where, m = gen.where()
        dims = gen.rng.sample(["teamID", "league"], 1)
        thresh = gen.rng.randint(5, 200)
        op = gen.rng.choice([">", "<="])
        pql = ("SELECT COUNT(*) FROM baseballStats" + where +
               " GROUP BY " + dims[0] +
               f" HAVING COUNT(*) {op} {thresh} TOP 2000")
        counts = oracle.group_by(dims, m, ("count", None))
        keep = {tuple(str(k) for k in key): v for key, v in counts.items()
                if (v > thresh if op == ">" else v <= thresh)}
        for e, label in [(engine, "device"), (host_engine, "host"),
                 (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            got = {tuple(str(k) for k in g["group"]): int(float(g["value"]))
                   for g in resp.aggregation_results[0].group_by_result}
            assert got == keep, (pql, label)


def test_random_selection_queries(setup):
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED + 2), oracle)
    exact_cols = ["teamID", "runs", "hits", "yearID"]
    for qi in range(N_SEL):
        where, m = gen.where()
        cols = gen.rng.sample(exact_cols, gen.rng.randint(1, 3))
        limit = gen.rng.randint(5, 20)
        order = gen.rng.random() < 0.5
        pql = "SELECT " + ", ".join(cols) + " FROM baseballStats" + where
        if order:
            ocol = gen.rng.choice([c for c in ["runs", "hits", "yearID"]])
            desc = gen.rng.random() < 0.5
            if ocol not in cols:
                cols = cols + [ocol]
                pql = ("SELECT " + ", ".join(cols) +
                       " FROM baseballStats" + where)
            pql += f" ORDER BY {ocol} {'DESC' if desc else 'ASC'}"
        pql += f" LIMIT {limit}"
        matched = int(m.sum())
        # matched-row multiset for membership checks
        idx = np.nonzero(m)[0]
        rowset = {}
        for i in idx:
            key = tuple(str(oracle.cols[c][i]) for c in cols)
            rowset[key] = rowset.get(key, 0) + 1
        for e, label in [(engine, "device"), (host_engine, "host"),
                 (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            rows = resp.selection_results.results
            assert len(rows) == min(limit, matched), (pql, label)
            seen = {}
            for row in rows:
                key = tuple(str(v) for v in row)
                seen[key] = seen.get(key, 0) + 1
                assert key in rowset, (pql, label, row)
            for key, cnt in seen.items():
                assert cnt <= rowset[key], (pql, label, key)
            if order and rows:
                oi = cols.index(ocol)
                vals = [float(r[oi]) for r in rows]
                svals = sorted(vals, reverse=desc)
                assert vals == svals, (pql, label)
                # returned extreme matches the oracle extreme of matched rows
                ovals = np.sort(oracle.vals(ocol, m).astype(np.float64))
                exp_top = ovals[::-1][:limit] if desc else ovals[:limit]
                assert vals == [float(v) for v in exp_top], (pql, label)


def test_random_mv_group_by_queries(setup):
    """MV group keys and valuein under random filters — engine (device +
    host) vs an inline expansion oracle (aggregateGroupByMV semantics)."""
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED + 7), oracle)
    all_pos = sorted({v for lst in oracle.cols["position"] for v in lst})
    for qi in range(8):
        where, m = gen.where()
        if gen.rng.random() < 0.5:
            picks = gen.rng.sample(all_pos, gen.rng.randint(2, 5))
            mvkey = "valuein(position, %s)" % \
                ", ".join("'%s'" % p for p in picks)
            allowed = set(picks)
        else:
            mvkey, allowed = "position", None
        extra_sv = gen.rng.choice([None, "league"])
        dims = [mvkey] + ([extra_sv] if extra_sv else [])
        pql = ("SELECT COUNT(*), SUM(hits) FROM baseballStats" + where +
               " GROUP BY " + ", ".join(dims) + " TOP 5000")
        exp = {}
        for i, lst in enumerate(oracle.cols["position"]):
            if not m[i]:
                continue
            for v in lst:
                if allowed is not None and v not in allowed:
                    continue
                key = (v,) + ((str(oracle.cols["league"][i]),)
                              if extra_sv else ())
                e2 = exp.setdefault(key, [0, 0.0])
                e2[0] += 1
                e2[1] += float(oracle.cols["hits"][i])
        for e, label in [(engine, "device"), (host_engine, "host"),
                 (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            got_cnt = {tuple(str(k) for k in g["group"]):
                       int(float(g["value"]))
                       for g in resp.aggregation_results[0].group_by_result}
            got_sum = {tuple(str(k) for k in g["group"]): float(g["value"])
                       for g in resp.aggregation_results[1].group_by_result}
            assert got_cnt == {k: v[0] for k, v in exp.items()}, (pql, label)
            for k, v in exp.items():
                assert got_sum[k] == pytest.approx(v[1], rel=1e-9), \
                    (pql, label, k)


def test_random_star_tree_agreement():
    """Randomized sweep over star-tree-enabled segments: every generated
    aggregation/group-by answer must be IDENTICAL with and without cubes
    (StarTreeClusterIntegrationTest's property, randomized) — this
    stresses the sorted-prefix descent with arbitrary conjunctions,
    IN-fanouts, ranges, and OR fallbacks."""
    import os

    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    base = tempfile.mkdtemp()
    st_cfg = make_table_config()
    st_cfg.indexing_config.star_tree_configs = [
        {"dimensionsSplitOrder": ["teamID", "league", "yearID"],
         "functionColumnPairs": ["SUM__runs", "SUM__hits",
                                 "MAX__average"]},
        {"dimensionsSplitOrder": ["league", "yearID"],
         "functionColumnPairs": ["SUM__runs"]},
    ]
    st_segs, pl_segs, all_cols = [], [], {}
    for i in range(2):
        cols = make_columns(2_000, seed=90 + i)
        d_st = os.path.join(base, f"st{i}")
        d_pl = os.path.join(base, f"pl{i}")
        SegmentCreator(make_schema(), st_cfg, f"st{i}").build(dict(cols),
                                                              d_st)
        SegmentCreator(make_schema(), make_table_config(),
                       f"pl{i}").build(dict(cols), d_pl)
        st_segs.append(ImmutableSegmentLoader.load(d_st))
        pl_segs.append(ImmutableSegmentLoader.load(d_pl))
        for k, v in cols.items():
            if isinstance(v, list):
                all_cols.setdefault(k, []).extend(v)
            else:
                all_cols[k] = np.concatenate([all_cols[k], v]) \
                    if k in all_cols else v
    oracle = Oracle(all_cols)
    eng_st = QueryEngine(st_segs, use_device=False)
    eng_pl = QueryEngine(pl_segs, use_device=False)

    gen = Gen(random.Random(SEED + 7), oracle)
    covered_aggs = [a for a in Gen.AGGS
                    if a[1] in ("count", "sum", "min", "max", "avg",
                                "minmaxrange") and
                    a[2] in (None, "runs", "hits", "average")]
    def canon(resp):
        out = []
        for ar in resp.aggregation_results:
            if ar.group_by_result is not None:
                out.append(sorted(
                    (tuple(str(x) for x in g["group"]),
                     round(float(g["value"]), 6))
                    for g in ar.group_by_result))
            else:
                v = ar.value
                out.append(round(float(v), 6)
                           if v not in (None, "null") else v)
        return out

    for qi in range(24):
        where, _m = gen.where()
        aggs = gen.rng.sample(covered_aggs, gen.rng.randint(1, 2))
        if gen.rng.random() < 0.5:
            dims = gen.rng.sample(["teamID", "league", "yearID"],
                                  gen.rng.randint(1, 2))
            pql = ("SELECT " + ", ".join(a[0] for a in aggs) +
                   " FROM baseballStats" + where +
                   " GROUP BY " + ", ".join(dims) + " TOP 5000")
        else:
            pql = ("SELECT " + ", ".join(a[0] for a in aggs) +
                   " FROM baseballStats" + where)
        r_st, r_pl = eng_st.query(pql), eng_pl.query(pql)
        assert not r_st.exceptions and not r_pl.exceptions, pql
        assert canon(r_st) == canon(r_pl), pql


def test_random_multi_column_order_by(setup):
    """Randomized two-key ORDER BY (mixed ASC/DESC): returned rows must
    be sorted by the composite key and the prefix must match the oracle
    top-k exactly (SelectionOperatorService order-by comparator parity)."""
    engine, host_engine, mesh_engine, oracle = setup
    gen = Gen(random.Random(SEED + 9), oracle)
    num_cols = ["runs", "hits", "yearID"]
    for qi in range(8):
        where, m = gen.where()
        o1, o2 = gen.rng.sample(num_cols, 2)
        d1 = gen.rng.random() < 0.5
        d2 = gen.rng.random() < 0.5
        limit = gen.rng.randint(5, 15)
        pql = (f"SELECT {o1}, {o2} FROM baseballStats{where} "
               f"ORDER BY {o1} {'DESC' if d1 else 'ASC'}, "
               f"{o2} {'DESC' if d2 else 'ASC'} LIMIT {limit}")
        idx = np.nonzero(m)[0]
        keys = sorted(
            ((float(oracle.cols[o1][i]), float(oracle.cols[o2][i]))
             for i in idx),
            key=lambda t: (-t[0] if d1 else t[0],
                           -t[1] if d2 else t[1]))[:limit]
        for e, label in [(engine, "device"), (host_engine, "host"),
                         (mesh_engine, "mesh-union")]:
            resp = e.query(pql)
            assert not resp.exceptions, (pql, label, resp.exceptions)
            got = [(float(r[0]), float(r[1]))
                   for r in resp.selection_results.results]
            assert got == keys, (pql, label, got[:3], keys[:3])
