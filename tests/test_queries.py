"""Golden-value query tests: engine vs independent numpy oracle.

The BaseQueriesTest pattern (reference:
pinot-core/src/test/.../queries/BaseQueriesTest.java) — real segments, real
plan maker + executor + broker reduce, no cluster machinery; results checked
against an oracle computed from the raw input arrays.
"""
import math
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, make_columns
from oracle import Oracle

from pinot_tpu.engine import QueryEngine

N = 10_000


@pytest.fixture(scope="module")
def setup():
    tmp = tempfile.mkdtemp()
    segment, cols = build_segment(tmp, n=N, seed=7)
    engine = QueryEngine([segment])
    host_engine = QueryEngine([segment], use_device=False)
    return engine, host_engine, Oracle(cols)


def agg_value(resp, i=0):
    return resp.aggregation_results[i].value


def both_engines(setup):
    engine, host_engine, oracle = setup
    return [(engine, "device"), (host_engine, "host")], oracle


# ---------------------------------------------------------------------------


def test_count_star_no_filter(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats")
        assert agg_value(resp) == str(N), label
        assert resp.total_docs == N


def test_count_with_range_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["yearID"] > 2000)
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*) FROM baseballStats WHERE yearID > 2000")
        assert agg_value(resp) == str(oracle.count(m)), label
        assert resp.num_docs_scanned == oracle.count(m)


def test_sum_min_max_avg_with_eq_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "BOS")
    for e, label in engines:
        resp = e.query("SELECT SUM(runs), MIN(runs), MAX(runs), AVG(runs)"
                       " FROM baseballStats WHERE teamID = 'BOS'")
        assert float(agg_value(resp, 0)) == pytest.approx(
            oracle.sum("runs", m)), label
        assert float(agg_value(resp, 1)) == oracle.min("runs", m), label
        assert float(agg_value(resp, 2)) == oracle.max("runs", m), label
        assert float(agg_value(resp, 3)) == pytest.approx(
            oracle.avg("runs", m)), label


def test_compound_and_or_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: (r["yearID"] >= 1995 and r["yearID"] < 2005 and
                               (r["teamID"] == "NYA" or r["teamID"] == "BOS"
                                or r["league"] == "NL")))
    q = ("SELECT COUNT(*), SUM(hits) FROM baseballStats WHERE "
         "yearID >= 1995 AND yearID < 2005 AND "
         "(teamID = 'NYA' OR teamID = 'BOS' OR league = 'NL')")
    for e, label in engines:
        resp = e.query(q)
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("hits", m)), label


def test_in_and_not_in(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] in ("NYA", "BOS", "DET"))
    m2 = oracle.mask(lambda r: r["teamID"] not in ("NYA", "BOS", "DET"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE teamID IN "
                       "('NYA', 'BOS', 'DET')")
        assert agg_value(resp) == str(oracle.count(m)), label
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE teamID "
                       "NOT IN ('NYA', 'BOS', 'DET')")
        assert agg_value(resp) == str(oracle.count(m2)), label


def test_between_and_float_range(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: 0.2 <= r["average"] <= 0.35)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), AVG(average) FROM baseballStats "
                       "WHERE average BETWEEN 0.2 AND 0.35")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.avg("average", m), rel=1e-9), label


def test_no_dictionary_column_filter_and_agg(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["salary"] > 500_000)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), SUM(salary), MAX(salary) FROM "
                       "baseballStats WHERE salary > 500000")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("salary", m), rel=1e-6), label
        assert float(agg_value(resp, 2)) == pytest.approx(
            oracle.max("salary", m), rel=1e-6), label


def test_eq_absent_value_empty_result(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats WHERE "
            "teamID = 'ZZZ'")
        assert agg_value(resp, 0) == "0", label
        assert resp.num_docs_scanned == 0


def test_neq_and_regexp(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] != "NYA")
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*) FROM baseballStats WHERE teamID <> 'NYA'")
        assert agg_value(resp) == str(oracle.count(m)), label
    m2 = oracle.mask(lambda r: r["playerName"].endswith("7"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE "
                       "REGEXP_LIKE(playerName, '7$')")
        assert agg_value(resp) == str(oracle.count(m2)), label


def test_distinctcount_and_percentile(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["league"] == "AL")
    for e, label in engines:
        resp = e.query("SELECT DISTINCTCOUNT(playerName), PERCENTILE50(runs),"
                       " PERCENTILE95(hits) FROM baseballStats WHERE "
                       "league = 'AL'")
        assert int(agg_value(resp, 0)) == oracle.distinctcount(
            "playerName", m), label
        assert float(agg_value(resp, 1)) == oracle.percentile(
            "runs", m, 50), label
        assert float(agg_value(resp, 2)) == oracle.percentile(
            "hits", m, 95), label


def test_minmaxrange(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "SEA")
    for e, label in engines:
        resp = e.query("SELECT MINMAXRANGE(hits) FROM baseballStats WHERE "
                       "teamID = 'SEA'")
        assert float(agg_value(resp)) == oracle.minmaxrange("hits", m), label


def test_mv_filter_and_aggs(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: "SS" in r["position"])
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), SUM(runs) FROM baseballStats "
                       "WHERE position = 'SS'")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("runs", m)), label
    # distinct positions among AL docs
    m2 = oracle.mask(lambda r: r["league"] == "AL")
    for e, label in engines:
        resp = e.query("SELECT DISTINCTCOUNT(position) FROM baseballStats "
                       "WHERE league = 'AL'")
        assert int(agg_value(resp)) == oracle.distinctcount(
            "position", m2), label


def test_group_by_sum(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["yearID"] >= 2010)
    expected = oracle.group_by(["teamID"], m, ("sum", "runs"))
    for e, label in engines:
        resp = e.query("SELECT SUM(runs) FROM baseballStats WHERE "
                       "yearID >= 2010 GROUP BY teamID TOP 1000")
        got = {tuple(g["group"]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert set(got.keys()) == {(k[0],) for k in expected}, label
        for k, v in expected.items():
            assert got[(k[0],)] == pytest.approx(v), (label, k)


def test_group_by_two_dims_multiple_aggs(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    exp_count = oracle.group_by(["teamID", "league"], m, ("count", None))
    exp_avg = oracle.group_by(["teamID", "league"], m, ("avg", "hits"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), AVG(hits) FROM baseballStats "
                       "GROUP BY teamID, league TOP 1000")
        got_count = {tuple(g["group"]): int(g["value"])
                     for g in resp.aggregation_results[0].group_by_result}
        got_avg = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_count == {k: v for k, v in exp_count.items()}, label
        for k, v in exp_avg.items():
            assert got_avg[k] == pytest.approx(v), (label, k)


def test_group_by_top_n_ordering(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    expected = oracle.group_by(["teamID"], m, ("sum", "hits"))
    top3 = sorted(expected.items(), key=lambda kv: -kv[1])[:3]
    for e, label in engines:
        resp = e.query(
            "SELECT SUM(hits) FROM baseballStats GROUP BY teamID TOP 3")
        got = resp.aggregation_results[0].group_by_result
        assert len(got) == 3, label
        for (key, val), g in zip(top3, got):
            assert g["group"] == [key[0]], label
            assert float(g["value"]) == pytest.approx(val), label


def test_group_by_having(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    counts = oracle.group_by(["teamID"], m, ("count", None))
    keep = {k for k, v in counts.items() if v > 640}
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats GROUP BY teamID "
                       "HAVING COUNT(*) > 640 TOP 100")
        got = {tuple(g["group"]) for g in
               resp.aggregation_results[0].group_by_result}
        assert got == keep, label


def test_selection_limit(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT teamID, runs, yearID FROM baseballStats "
                       "WHERE teamID = 'NYA' LIMIT 7")
        rows = resp.selection_results.results
        assert len(rows) == 7, label
        for row in rows:
            assert row[0] == "NYA", label
        assert resp.selection_results.columns == ["teamID", "runs", "yearID"]


def test_selection_order_by(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "OAK")
    hits = np.sort(oracle.vals("hits", m))[::-1][:5]
    for e, label in engines:
        resp = e.query("SELECT hits FROM baseballStats WHERE teamID = 'OAK' "
                       "ORDER BY hits DESC LIMIT 5")
        got = [int(r[0]) for r in resp.selection_results.results]
        assert got == [int(h) for h in hits], label


def test_selection_star_and_mv_decode(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT * FROM baseballStats LIMIT 3")
        rows = resp.selection_results.results
        assert len(rows) == 3, label
        cols = resp.selection_results.columns
        pos_idx = cols.index("position")
        team_idx = cols.index("teamID")
        for i, row in enumerate(rows):
            assert row[team_idx] == setup[2].cols["teamID"][i], label
            assert row[pos_idx] == setup[2].cols["position"][i], label


def test_empty_segment_level_results_merge(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT MIN(runs), MAX(runs) FROM baseballStats "
                       "WHERE yearID > 9999")
        assert agg_value(resp, 0) == "Infinity", label
        assert agg_value(resp, 1) == "-Infinity", label
