"""Controller admin REST API.

Parity: pinot-controller/.../api/resources/ — the admin surface a Pinot
operator drives: PinotSchemaRestletResource (schema CRUD),
PinotTableRestletResource (table CRUD + rebalance),
PinotSegmentUploadRestletResource (segment upload as a packed artifact),
PinotSegmentRestletResource (list/delete segments), TableViews.java
(idealstate / externalview). Segment upload bodies are gzipped tars of the
segment directory — the same "push a built artifact" contract as the
reference's SegmentCompletionUtils tar.gz push.
"""
from __future__ import annotations

import asyncio
import os
import tempfile

from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.manager import InvalidTableConfigError
from pinot_tpu.controller.quota import StorageQuotaExceededError
from pinot_tpu.transport.http import (ApiServer, HttpRequest, HttpResponse,
                                      metrics_response)


# canonical home is common/segment_tar.py; re-exported here because the
# upload/download endpoints are where most callers first meet the format
from pinot_tpu.common.segment_tar import (pack_segment_dir,   # noqa: F401
                                          unpack_segment_tar)


def _read_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


class ControllerApiServer(ApiServer):
    """HTTP admin surface for one Controller."""

    def __init__(self, controller: Controller):
        super().__init__()
        self.controller = controller
        self.manager = controller.manager
        router = self.router
        router.add("GET", "/", self._console)
        router.add("GET", "/ui", self._cluster_ui)
        router.add("GET", "/health", self._health)
        router.add("GET", "/debug/health", self._debug_health)
        router.add("GET", "/metrics", self._metrics)
        router.add("GET", "/schemas", self._list_schemas)
        router.add("POST", "/schemas", self._add_schema)
        router.add("GET", "/schemas/{name}", self._get_schema)
        # tenant CRUD (parity: PinotTenantRestletResource.java:80)
        router.add("GET", "/tenants", self._list_tenants)
        router.add("POST", "/tenants", self._create_tenant)
        router.add("GET", "/tenants/{name}", self._tenant_instances)
        router.add("DELETE", "/tenants/{name}", self._delete_tenant)
        router.add("GET", "/instances", self._list_instances)
        router.add("PUT", "/instances/{name}/tags", self._update_tags)
        router.add("GET", "/tables", self._list_tables)
        router.add("POST", "/tables", self._add_table)
        router.add("PUT", "/tables/{name}", self._update_table)
        router.add("GET", "/tables/{name}", self._get_table)
        router.add("DELETE", "/tables/{name}", self._delete_table)
        router.add("GET", "/tables/{name}/size", self._table_size)
        router.add("GET", "/tables/{name}/schema", self._table_schema)
        # query passthrough (parity: PqlQueryResource — the controller
        # proxies ad-hoc queries to a live broker)
        router.add("POST", "/pql", self._pql_passthrough)
        router.add("GET", "/pql", self._pql_passthrough)
        router.add("GET", "/tables/{name}/idealstate", self._ideal_state)
        router.add("GET", "/tables/{name}/externalview",
                   self._external_view)
        router.add("POST", "/tables/{name}/rebalance", self._rebalance)
        router.add("GET", "/tables/{name}/segments", self._list_segments)
        router.add("POST", "/segments/{table}", self._upload_segment)
        router.add("GET", "/segments/{table}/{segment}/metadata",
                   self._segment_metadata)
        router.add("DELETE", "/segments/{table}/{segment}",
                   self._delete_segment)
        router.add("POST", "/segments/{table}/{segment}/reload",
                   self._reload_segment)
        router.add("POST", "/tables/{name}/reload", self._reload_table)
        # minion task plane (parity: PinotTaskRestletResource —
        # list task states per type, schedule generators)
        router.add("GET", "/tasks/{taskType}/state", self._task_states)
        router.add("POST", "/tasks/schedule", self._schedule_tasks)
        # LLC segment-completion protocol (parity:
        # controller/api/resources/LLCSegmentCompletionHandlers.java —
        # segmentConsumed / segmentStoppedConsuming / segmentCommitStart /
        # segmentCommitEnd{WithMetadata})
        router.add("POST", "/segmentConsumed", self._segment_consumed)
        router.add("POST", "/segmentStoppedConsuming",
                   self._stopped_consuming)
        router.add("POST", "/segmentCommitStart", self._commit_start)
        router.add("POST", "/segmentExtendBuildTime",
                   self._extend_build_time)
        router.add("POST", "/segmentCommitEnd", self._commit_end)
        # deep-store access for servers without a shared filesystem
        # (parity: common/segment/fetcher HTTP segment fetchers + the
        # controller serving segment downloads): segment dirs travel as
        # the same tar format the upload endpoint accepts
        router.add("GET", "/deepstore/download", self._deepstore_download)
        router.add("GET", "/deepstore/stat", self._deepstore_stat)
        router.add("GET", "/deepstore/list", self._deepstore_list)

    # -- handlers ----------------------------------------------------------
    async def _console(self, request: HttpRequest) -> HttpResponse:
        """Minimal query console (parity: the controller's web UI query
        console). Pass ?broker=host:port to point it at a broker."""
        import html as _html
        broker = request.query.get("broker", "127.0.0.1:8099")
        html = _CONSOLE_HTML.replace("__BROKER__", _html.escape(broker))
        return HttpResponse(200, html.encode("utf-8"),
                            content_type="text/html; charset=utf-8")

    async def _cluster_ui(self, request: HttpRequest) -> HttpResponse:
        """Cluster manager UI (parity: the controller web app's cluster
        views — tables / instances / tenants / schemas / segments),
        driven entirely by the same-origin REST endpoints."""
        return HttpResponse(200, _CLUSTER_UI_HTML.encode("utf-8"),
                            content_type="text/html; charset=utf-8")

    async def _health(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse(200, b"OK", content_type="text/plain")

    async def _debug_health(self, request: HttpRequest) -> HttpResponse:
        """Leak-gate rollup (obs/health.py) — RSS + residency + the
        controller's replication-deficit gauge in one scrape."""
        from pinot_tpu.obs.health import health_rollup
        return HttpResponse.of_json(
            health_rollup("controller", self.controller.metrics))

    async def _metrics(self, request: HttpRequest) -> HttpResponse:
        return metrics_response(self.controller.metrics, request)

    async def _list_schemas(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json(
            self.manager.store.children("/CONFIGS/SCHEMA"))

    async def _add_schema(self, request: HttpRequest) -> HttpResponse:
        schema = Schema.from_json(request.json())
        self.manager.add_schema(schema)
        return HttpResponse.of_json({"status": f"{schema.schema_name} "
                                     "successfully added"})

    async def _get_schema(self, request: HttpRequest) -> HttpResponse:
        schema = self.manager.get_schema(request.path_params["name"])
        if schema is None:
            return HttpResponse.error(404, "schema not found")
        return HttpResponse.of_json(schema.to_json())

    # -- tenants -----------------------------------------------------------
    async def _list_tenants(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json(self.manager.tenants.tenants())

    async def _create_tenant(self, request: HttpRequest) -> HttpResponse:
        from pinot_tpu.controller.tenants import TenantError
        body = request.json()
        name = body.get("tenantName") or body.get("name")
        role = (body.get("tenantRole") or body.get("role") or
                "SERVER").upper()
        instances = body.get("instances") or []
        if not name:
            return HttpResponse.error(400, "tenantName required")
        try:
            if role == "BROKER":
                insts = self.manager.tenants.create_broker_tenant(
                    name, instances)
            else:
                insts = self.manager.tenants.create_server_tenant(
                    name, instances)
        except TenantError as e:
            return HttpResponse.error(400, str(e))
        # (broker-resource records refresh via the manager's
        # live-instance watch — tag writes land on /LIVEINSTANCES)
        return HttpResponse.of_json(
            {"status": f"tenant {name} ({role}) tagged on "
             f"{len(insts)} instances"})

    async def _tenant_instances(self, request: HttpRequest) -> HttpResponse:
        name = request.path_params["name"]
        role = request.query.get("type", "server").upper()
        insts = self.manager.tenants.tenant_instances(name, role)
        return HttpResponse.of_json(
            {"tenantName": name, "type": role,
             "ServerInstances" if role != "BROKER" else "BrokerInstances":
                 insts})

    async def _delete_tenant(self, request: HttpRequest) -> HttpResponse:
        from pinot_tpu.controller.tenants import TenantError
        name = request.path_params["name"]
        role = request.query.get("type", "server").upper()
        tables = [self.manager.get_table_config(t)
                  for t in self.manager.table_names()]
        try:
            self.manager.tenants.delete_tenant(
                name, role, [t for t in tables if t is not None])
        except TenantError as e:
            return HttpResponse.error(409, str(e))
        return HttpResponse.of_json({"status": f"tenant {name} deleted"})

    async def _list_instances(self, request: HttpRequest) -> HttpResponse:
        tenants = self.manager.tenants
        return HttpResponse.of_json(
            {"instances": {i: tenants.instance_tags(i)
                           for i in tenants.live_instances()}})

    async def _update_tags(self, request: HttpRequest) -> HttpResponse:
        from pinot_tpu.controller.tenants import TenantError
        body = request.json()
        try:
            tags = self.manager.tenants.update_instance_tags(
                request.path_params["name"], add=body.get("add", []),
                remove=body.get("remove", []))
        except TenantError as e:
            return HttpResponse.error(404, str(e))
        return HttpResponse.of_json({"tags": tags})

    # -- minion tasks ------------------------------------------------------
    async def _task_states(self, request: HttpRequest) -> HttpResponse:
        from pinot_tpu.minion.tasks import TaskQueue
        states = TaskQueue(self.manager.store).task_states(
            request.path_params["taskType"])
        return HttpResponse.of_json(states)

    async def _schedule_tasks(self, request: HttpRequest) -> HttpResponse:
        """Run the registered task generators over all tables (parity:
        POST /tasks/schedule running PinotTaskManager.scheduleTasks).
        Serialized through one shared manager + lock: the generators'
        dedup check (tasks_for_segment) and submit are not atomic, so
        concurrent schedules would double-submit per segment."""
        import asyncio as _asyncio
        if not hasattr(self, "_task_manager"):
            # share the controller's task manager (its queue carries
            # the requeue meters and the per-sweep throttle; its
            # schedule_tasks serializes internally, covering the
            # periodic sweep AND this endpoint) — build a private one
            # only for bare managers in tests
            tm = getattr(self.controller, "task_manager", None)
            if tm is None:
                from pinot_tpu.minion.task_manager import \
                    PinotTaskManager
                tm = PinotTaskManager(self.manager)
            self._task_manager = tm

        submitted = await _asyncio.get_running_loop().run_in_executor(
            None, self._task_manager.schedule_tasks)
        return HttpResponse.of_json({"submitted": submitted})

    async def _table_size(self, request: HttpRequest) -> HttpResponse:
        """Aggregate + per-segment reported sizes from the durable
        segment records (parity: the controller TableSize API feeding
        quota/ops tooling)."""
        table = request.path_params["name"]
        if self.manager.get_table_config(table) is None:
            return HttpResponse.error(404, f"table {table} not found")
        segs = {}
        total = 0
        for seg in self.manager.segment_names(table):
            rec = self.manager.segment_metadata(table, seg) or {}
            size = int(rec.get("sizeBytes") or 0)
            segs[seg] = size
            total += size
        return HttpResponse.of_json(
            {"tableName": table, "reportedSizeInBytes": total,
             "segments": segs})

    async def _table_schema(self, request: HttpRequest) -> HttpResponse:
        """The schema backing a table (parity: GET /tables/{t}/schema)."""
        from pinot_tpu.common.table_name import raw_table
        table = request.path_params["name"]
        schema = self.manager.get_schema(raw_table(table))
        if schema is None:
            return HttpResponse.error(404,
                                      f"no schema for table {table}")
        return HttpResponse.of_json(schema.to_json())

    async def _pql_passthrough(self, request: HttpRequest) -> HttpResponse:
        """Proxy a query to a live broker (parity: PqlQueryResource).

        Broker discovery: any live instance with a _BROKER tag carrying
        an HTTP endpoint (the same records the dynamic client selector
        uses)."""
        import asyncio as _asyncio
        import json as _json
        import urllib.request as _req

        fwd_body = {}
        if request.method == "GET":
            pql = request.query.get("pql") or request.query.get("sql")
            if request.query.get("trace", "").lower() == "true":
                fwd_body["trace"] = True
        else:
            try:
                fwd_body = dict(request.json() or {})
            except ValueError:
                return HttpResponse.error(400, "invalid JSON body")
            pql = fwd_body.get("pql") or fwd_body.get("sql")
        if not pql:
            return HttpResponse.error(400, "missing pql")
        fwd_body["pql"] = pql
        from pinot_tpu.controller.state_machine import LIVE
        import random as _random
        brokers = []
        for inst in self.manager.store.children(LIVE):
            rec = self.manager.store.get(f"{LIVE}/{inst}") or {}
            if "host" in rec and any(t.endswith("_BROKER")
                                     for t in rec.get("tags", [])):
                brokers.append((rec["host"], int(rec["port"])))
        if not brokers:
            return HttpResponse.error(
                503, "no live broker registered in the cluster")
        broker = _random.choice(brokers)   # spread proxied load

        headers = {"Content-Type": "application/json"}
        auth = request.headers.get("authorization")
        if auth:
            # forward the caller's identity so the broker's ACL sees it
            headers["Authorization"] = auth

        def forward():
            req = _req.Request(
                f"http://{broker[0]}:{broker[1]}/query",
                data=_json.dumps(fwd_body).encode(), headers=headers)
            with _req.urlopen(req, timeout=60) as r:
                return r.read()

        payload = await _asyncio.get_running_loop().run_in_executor(
            None, forward)
        return HttpResponse(200, payload,
                            content_type="application/json")

    async def _list_tables(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json({"tables": self.manager.table_names()})

    async def _add_table(self, request: HttpRequest) -> HttpResponse:
        config = TableConfig.from_json(request.json())
        try:
            if config.table_type == TableType.REALTIME:
                table = self.controller.realtime.setup_table(config)
            else:
                table = self.manager.add_table(config)
        except InvalidTableConfigError as e:
            return HttpResponse.error(400, str(e))
        return HttpResponse.of_json({"status": f"{table} successfully "
                                     "added"})

    async def _update_table(self, request: HttpRequest) -> HttpResponse:
        config = TableConfig.from_json(request.json())
        if config.table_name_with_type != request.path_params["name"]:
            return HttpResponse.error(
                400, f"table name mismatch: path addresses "
                f"{request.path_params['name']!r} but body names "
                f"{config.table_name_with_type!r}")
        try:
            table = self.manager.update_table_config(config)
        except InvalidTableConfigError as e:
            return HttpResponse.error(400, str(e))
        except ValueError as e:
            return HttpResponse.error(404, str(e))
        return HttpResponse.of_json({"status": f"{table} updated"})

    async def _get_table(self, request: HttpRequest) -> HttpResponse:
        config = self.manager.get_table_config(
            request.path_params["name"])
        if config is None:
            return HttpResponse.error(404, "table not found")
        return HttpResponse.of_json(config.to_json())

    async def _delete_table(self, request: HttpRequest) -> HttpResponse:
        table = request.path_params["name"]
        if self.manager.get_table_config(table) is None:
            return HttpResponse.error(404, "table not found")
        self.manager.delete_table(table)
        return HttpResponse.of_json({"status": f"{table} deleted"})

    async def _ideal_state(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json(self.controller.coordinator.ideal_state(
            request.path_params["name"]))

    async def _external_view(self, request: HttpRequest) -> HttpResponse:
        view = self.controller.coordinator.external_view(
            request.path_params["name"])
        return HttpResponse.of_json(view.segment_states)

    async def _rebalance(self, request: HttpRequest) -> HttpResponse:
        import asyncio as _asyncio
        dry = request.query.get("dryRun", "false").lower() == "true"
        downtime = request.query.get("downtime",
                                     "false").lower() == "true"
        # the stepping path blocks on external-view convergence — run it
        # off the event loop so uploads and realtime commit traffic keep
        # flowing during a rebalance
        target = await _asyncio.get_running_loop().run_in_executor(
            None, lambda: self.manager.rebalance_table(
                request.path_params["name"], dry_run=dry,
                downtime=downtime))
        return HttpResponse.of_json({"dryRun": dry, "downtime": downtime,
                                     "targetState": target})

    async def _list_segments(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json(self.manager.segment_names(
            request.path_params["name"]))

    async def _upload_segment(self, request: HttpRequest) -> HttpResponse:
        table = request.path_params["table"]
        if self.manager.get_table_config(table) is None:
            return HttpResponse.error(404, f"table {table} not found")
        if not request.body:
            return HttpResponse.error(400, "empty segment payload")
        with tempfile.TemporaryDirectory() as tmp:
            seg_dir = os.path.join(tmp, "segment")
            os.makedirs(seg_dir)
            unpack_segment_tar(request.body, seg_dir)
            try:
                name = self.manager.add_segment(table, seg_dir)
            except StorageQuotaExceededError as e:
                return HttpResponse.error(403, str(e))
        return HttpResponse.of_json({"status": f"segment {name} uploaded"})

    async def _reload_segment(self, request: HttpRequest) -> HttpResponse:
        try:
            self.manager.reload_segment(request.path_params["table"],
                                        request.path_params["segment"])
        except ValueError as e:
            return HttpResponse.error(404, str(e))
        return HttpResponse.of_json({"status": "reload triggered"})

    async def _reload_table(self, request: HttpRequest) -> HttpResponse:
        try:
            n = self.manager.reload_table(request.path_params["name"])
        except ValueError as e:
            return HttpResponse.error(404, str(e))
        return HttpResponse.of_json({"status": f"{n} segments reloaded"})

    # -- LLC completion protocol ------------------------------------------
    def _completion_params(self, request: HttpRequest):
        q = request.query
        return (q["table"], q["name"], q["instance"],
                int(q.get("offset", "-1")))

    async def _segment_consumed(self, request: HttpRequest) -> HttpResponse:
        table, name, instance, offset = self._completion_params(request)
        resp = self.controller.realtime.segment_consumed(
            table, name, instance, offset)
        return HttpResponse.of_json(resp.to_json())

    async def _stopped_consuming(self, request: HttpRequest) -> HttpResponse:
        table, name, instance, _ = self._completion_params(request)
        self.controller.realtime.stopped_consuming(
            table, name, instance, request.query.get("reason", ""))
        return HttpResponse.of_json({"status": "PROCESSED"})

    async def _extend_build_time(self, request: HttpRequest
                                 ) -> HttpResponse:
        table, name, instance, _ = self._completion_params(request)
        extra = float(request.query.get("extraTimeMs", "60000"))
        resp = self.controller.realtime.extend_build_time(
            table, name, instance, extra)
        return HttpResponse.of_json(resp.to_json())

    async def _commit_start(self, request: HttpRequest) -> HttpResponse:
        table, name, instance, offset = self._completion_params(request)
        resp = self.controller.realtime.commit_start(
            table, name, instance, offset)
        return HttpResponse.of_json(resp.to_json())

    async def _commit_end(self, request: HttpRequest) -> HttpResponse:
        """Split-commit end: the winner uploads its built segment as the
        request body (tar.gz), the controller deep-stores it and steps
        the cluster (commitSegmentMetadata parity)."""
        table, name, instance, offset = self._completion_params(request)
        if not request.body:
            return HttpResponse.error(400, "empty segment payload")
        with tempfile.TemporaryDirectory() as tmp:
            seg_dir = os.path.join(tmp, "segment")
            try:
                unpack_segment_tar(request.body, seg_dir)
            except Exception as e:  # noqa: BLE001 — bad upload payload
                return HttpResponse.error(400, f"bad segment tar: {e}")
            resp = self.controller.realtime.commit_end(
                table, name, instance, offset, seg_dir)
        return HttpResponse.of_json(resp.to_json())

    def _deepstore_path(self, request: HttpRequest):
        """Resolve ?path= strictly INSIDE the deep-store root (path
        traversal outside it is refused)."""
        root = os.path.realpath(self.manager.deep_store_dir)
        rel = request.query.get("path", "")
        full = os.path.realpath(os.path.join(root, rel))
        if full != root and not full.startswith(root + os.sep):
            return None
        return full

    async def _deepstore_download(self, request: HttpRequest
                                  ) -> HttpResponse:
        full = self._deepstore_path(request)
        if full is None:
            return HttpResponse.error(403, "path outside deep store")
        # segment artifacts run to hundreds of MB: reading (or packing)
        # them on the event loop would stall every other controller API
        # call for the duration — do the IO on the default executor
        loop = asyncio.get_running_loop()
        if os.path.isdir(full):
            data = await loop.run_in_executor(None, pack_segment_dir,
                                              full)
            return HttpResponse(200, data,
                                content_type="application/octet-stream")
        if os.path.isfile(full):
            data = await loop.run_in_executor(None, _read_file, full)
            return HttpResponse(200, data,
                                content_type="application/octet-stream")
        return HttpResponse.error(404, "not found")

    async def _deepstore_stat(self, request: HttpRequest) -> HttpResponse:
        full = self._deepstore_path(request)
        if full is None:
            return HttpResponse.error(403, "path outside deep store")
        return HttpResponse.of_json({
            "exists": os.path.exists(full),
            "isDirectory": os.path.isdir(full)})

    async def _deepstore_list(self, request: HttpRequest) -> HttpResponse:
        full = self._deepstore_path(request)
        if full is None:
            return HttpResponse.error(403, "path outside deep store")
        if not os.path.isdir(full):
            return HttpResponse.error(404, "not a directory")
        return HttpResponse.of_json({"files": sorted(os.listdir(full))})

    async def _segment_metadata(self, request: HttpRequest) -> HttpResponse:
        meta = self.manager.segment_metadata(
            request.path_params["table"], request.path_params["segment"])
        if meta is None:
            return HttpResponse.error(404, "segment not found")
        return HttpResponse.of_json(meta)

    async def _delete_segment(self, request: HttpRequest) -> HttpResponse:
        table = request.path_params["table"]
        segment = request.path_params["segment"]
        if self.manager.segment_metadata(table, segment) is None:
            return HttpResponse.error(404, "segment not found")
        self.manager.delete_segment(table, segment)
        return HttpResponse.of_json({"status": f"{segment} deleted"})


_CONSOLE_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>pinot_tpu query console</title>
<style>
 body { font-family: monospace; margin: 2rem; background: #101418;
        color: #d8dee6; }
 h1 { font-size: 1.1rem; }
 textarea { width: 100%; height: 6rem; background: #181e24;
            color: #d8dee6; border: 1px solid #2c343c; padding: .5rem;
            font-family: inherit; }
 input { background: #181e24; color: #d8dee6; border: 1px solid #2c343c;
         padding: .3rem; width: 16rem; font-family: inherit; }
 button { padding: .4rem 1rem; margin-top: .5rem; cursor: pointer; }
 pre { background: #181e24; border: 1px solid #2c343c; padding: .7rem;
       overflow: auto; max-height: 32rem; }
 table { border-collapse: collapse; margin-top: .6rem; }
 td, th { border: 1px solid #2c343c; padding: .25rem .6rem; }
</style></head><body>
<h1>pinot_tpu query console</h1>
<div>broker <input id="broker" value="__BROKER__"></div>
<textarea id="pql">SELECT COUNT(*) FROM baseballStats</textarea><br>
<button onclick="run()">Run (Ctrl-Enter)</button>
<div id="stats"></div><div id="table"></div><pre id="out"></pre>
<script>
async function run() {
  const pql = document.getElementById('pql').value;
  const broker = document.getElementById('broker').value;
  const t0 = performance.now();
  try {
    const r = await fetch('http://' + broker + '/query', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({pql})});
    const j = await r.json();
    const ms = (performance.now() - t0).toFixed(1);
    document.getElementById('stats').textContent =
      ms + ' ms | docs scanned: ' + (j.numDocsScanned ?? '?') +
      ' | segments: ' + (j.numSegmentsProcessed ?? '?');
    render(j);
    document.getElementById('out').textContent =
      JSON.stringify(j, null, 2);
  } catch (e) {
    document.getElementById('out').textContent = 'error: ' + e;
  }
}
function esc(v) {
  return String(v).replace(/&/g, '&amp;').replace(/</g, '&lt;')
    .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
}
function render(j) {
  const el = document.getElementById('table');
  el.innerHTML = '';
  const mk = (rows, header) => {
    const t = document.createElement('table');
    t.innerHTML = '<tr>' + header.map(h => '<th>' + esc(h) + '</th>')
      .join('') + '</tr>' + rows.map(r => '<tr>' +
        r.map(c => '<td>' + esc(c) + '</td>').join('') + '</tr>').join('');
    el.appendChild(t);
  };
  if (j.selectionResults)
    mk(j.selectionResults.results, j.selectionResults.columns);
  for (const a of (j.aggregationResults || [])) {
    if (a.groupByResult)
      mk(a.groupByResult.map(g => [...g.group, g.value]),
         [...(a.groupByColumns || []), a.function]);
    else if (a.function) mk([[a.value]], [a.function]);
  }
}
document.getElementById('pql').addEventListener('keydown', e => {
  if (e.ctrlKey && e.key === 'Enter') run();
});
</script></body></html>
"""


_CLUSTER_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>pinot_tpu cluster manager</title>
<style>
 body { font-family: monospace; margin: 2rem; background: #101418;
        color: #d8dee6; }
 h1 { font-size: 1.1rem; } h2 { font-size: 1rem; margin-top: 1.4rem; }
 a { color: #7aa2f7; } pre { background: #181e24; padding: .7rem;
     border: 1px solid #2c343c; overflow: auto; max-height: 24rem; }
 table { border-collapse: collapse; margin-top: .6rem; }
 td, th { border: 1px solid #2c343c; padding: .25rem .6rem;
          text-align: left; }
 .tag { background: #1f2a38; border-radius: 3px; padding: 0 .4rem;
        margin-right: .3rem; }
 button { cursor: pointer; padding: .2rem .6rem; }
</style></head><body>
<h1>pinot_tpu cluster manager
  <small>(<a href="/">query console</a>)</small></h1>
<h2>instances</h2><div id="instances">loading...</div>
<h2>tenants</h2><div id="tenants">loading...</div>
<h2>schemas</h2><div id="schemas">loading...</div>
<h2>tables</h2><div id="tables">loading...</div>
<h2>detail</h2><pre id="detail">click a table / schema for detail</pre>
<script>
const J = async p => (await fetch(p)).json();
const esc = v => String(v).replace(/&/g,'&amp;').replace(/</g,'&lt;');
async function detail(path) {
  document.getElementById('detail').textContent =
    JSON.stringify(await J(path), null, 2);
}
async function load() {
  const inst = await J('/instances');
  document.getElementById('instances').innerHTML =
    '<table><tr><th>instance</th><th>tags</th></tr>' +
    inst.map(i => '<tr><td>' + esc(i.name ?? i) + '</td><td>' +
      ((i.tags ?? []).map(t => '<span class="tag">' + esc(t) +
      '</span>').join('')) + '</td></tr>').join('') + '</table>';
  const tenants = await J('/tenants');
  document.getElementById('tenants').innerHTML =
    (tenants.length ? tenants : ['(default only)']).map(esc).join(', ');
  const schemas = await J('/schemas');
  document.getElementById('schemas').innerHTML = schemas.map(s =>
    '<a href="#" onclick="detail(\'/schemas/' + esc(s) +
    '\');return false">' + esc(s) + '</a>').join(', ') || '(none)';
  const tables = await J('/tables');
  const names = tables.tables ?? tables;
  const rows = [];
  for (const t of names) {
    let size = '?', segs = '?';
    try {
      const sz = await J('/tables/' + t + '/size');
      size = (sz.reportedSizeInBytes ?? sz.sizeBytes ?? 0);
      const sg = await J('/tables/' + t + '/segments');
      segs = (sg.segments ?? sg).length;
    } catch (e) {}
    rows.push('<tr><td><a href="#" onclick="detail(\'/tables/' + esc(t) +
      '\');return false">' + esc(t) + '</a></td><td>' + segs +
      '</td><td>' + size + '</td>' +
      '<td><a href="#" onclick="detail(\'/tables/' + esc(t) +
      '/externalview\');return false">view</a></td>' +
      '<td><a href="#" onclick="detail(\'/tables/' + esc(t) +
      '/idealstate\');return false">ideal</a></td></tr>');
  }
  document.getElementById('tables').innerHTML =
    '<table><tr><th>table</th><th>segments</th><th>bytes</th>' +
    '<th>external view</th><th>ideal state</th></tr>' +
    rows.join('') + '</table>';
}
load();
</script></body></html>
"""
