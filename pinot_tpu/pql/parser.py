"""PQL recursive-descent parser → BrokerRequest.

Parity: org.apache.pinot.pql.parsers.Pql2Compiler.compileToBrokerRequest
(pinot-common/.../pql/parsers/Pql2Compiler.java:63-102) and the PQL2.g4
grammar: SELECT output list (columns or aggregation calls), FROM, WHERE
predicate tree (comparison / BETWEEN / IN / NOT IN / REGEXP_LIKE / IS NULL
with AND/OR nesting), GROUP BY, HAVING, ORDER BY, TOP, LIMIT.

Comparison predicates compile to the same FilterOperator encoding the
reference uses (Pql2AstNode → FilterQueryTree): ``=`` → EQUALITY, ``<>/!=`` →
NOT, ``< <= > >=`` → one-sided RANGE, BETWEEN → two-sided inclusive RANGE.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import (AggregationInfo, BrokerRequest,
                                      FilterOperator, FilterQueryTree, GroupBy,
                                      HavingNode, JoinSpec, QueryOptions,
                                      Selection, SelectionSort,
                                      VectorSimilarity, WindowSpec)
from pinot_tpu.pql.lexer import PqlSyntaxError, TokType, Token, tokenize

# Aggregation function names the engine recognizes (PERCENTILE variants are
# matched by prefix, e.g. PERCENTILE95 / PERCENTILETDIGEST99).
AGG_PREFIXES = (
    "COUNT", "SUM", "MIN", "MAX", "AVG", "MINMAXRANGE", "DISTINCTCOUNTHLL",
    "DISTINCTCOUNTRAWHLL", "DISTINCTCOUNT", "FASTHLL", "PERCENTILEEST",
    "PERCENTILETDIGEST", "PERCENTILE",
)
_MV_SUFFIX = "MV"


def is_aggregation_function(name: str) -> bool:
    up = name.upper()
    if up.endswith(_MV_SUFFIX):
        up = up[: -len(_MV_SUFFIX)]
    for p in sorted(AGG_PREFIXES, key=len, reverse=True):
        if up.startswith(p):
            rest = up[len(p):]
            return rest == "" or rest.isdigit()
    return False


class Pql2Compiler:
    """compile(pql) -> BrokerRequest."""

    def compile(self, pql: str) -> BrokerRequest:
        return _Parser(tokenize(pql), pql).parse_query()


def compile_pql(pql: str) -> BrokerRequest:
    return Pql2Compiler().compile(pql)


class _Parser:
    def __init__(self, toks: List[Token], text: str):
        self.toks = toks
        self.text = text
        self.i = 0

    # -- token plumbing ----------------------------------------------------
    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> bool:
        t = self.peek()
        if t.type == TokType.KEYWORD and t.upper == words[0]:
            # multi-word keyword like GROUP BY
            for k, w in enumerate(words):
                tk = self.toks[self.i + k]
                if not (tk.type == TokType.KEYWORD and tk.upper == w):
                    return False
            self.i += len(words)
            return True
        return False

    def expect_kw(self, *words: str):
        if not self.accept_kw(*words):
            raise PqlSyntaxError(
                f"expected {' '.join(words)} at {self.peek().pos} "
                f"(got {self.peek().value!r})")

    def expect(self, ttype: TokType) -> Token:
        t = self.next()
        if t.type != ttype:
            raise PqlSyntaxError(f"expected {ttype.value} at {t.pos}, "
                                 f"got {t.value!r}")
        return t

    # -- grammar -----------------------------------------------------------
    def parse_query(self) -> BrokerRequest:
        self.expect_kw("SELECT")
        select_items = self.parse_select_list()
        self.expect_kw("FROM")
        table = self.expect(TokType.IDENT).value

        join = None
        if self.accept_kw("JOIN"):
            join = self.parse_join_clause(table)

        filt = None
        if self.accept_kw("WHERE"):
            filt = self.parse_predicate()

        group_by_cols: List[str] = []
        if self.accept_kw("GROUP", "BY"):
            group_by_cols = self.parse_ident_list()

        having = None
        if self.accept_kw("HAVING"):
            having = self.parse_having()

        order_by: List[SelectionSort] = []
        if self.accept_kw("ORDER", "BY"):
            order_by = self.parse_order_list()

        top_n = None
        if self.accept_kw("TOP"):
            top_n = int(self.expect(TokType.INT).value)

        offset, size = 0, None
        if self.accept_kw("LIMIT"):
            first = int(self.expect(TokType.INT).value)
            if self.peek().type == TokType.COMMA:
                self.next()
                offset, size = first, int(self.expect(TokType.INT).value)
            elif self.accept_kw("OFFSET"):
                size, offset = first, int(self.expect(TokType.INT).value)
            else:
                size = first

        options = QueryOptions()
        if self.accept_kw("OPTION"):
            self.expect(TokType.LPAREN)
            while True:
                key = self.next().value
                self.expect(TokType.OP)  # '='
                val = self.next().value
                options.options[key] = val
                if key == "timeoutMs":
                    options.timeout_ms = int(val)
                elif key == "trace":
                    options.trace = str(val).lower() in ("true", "1")
                if self.peek().type == TokType.COMMA:
                    self.next()
                    continue
                break
            self.expect(TokType.RPAREN)

        if self.peek().type != TokType.EOF:
            raise PqlSyntaxError(
                f"trailing input at {self.peek().pos}: {self.peek().value!r}")

        # -- assemble ------------------------------------------------------
        aggs = [it for it in select_items if isinstance(it, AggregationInfo)]
        cols = [it for it in select_items if isinstance(it, str)]
        vecs = [it for it in select_items if isinstance(it, VectorSimilarity)]
        wins = [it for it in select_items if isinstance(it, WindowSpec)]
        if aggs and cols:
            raise PqlSyntaxError(
                "cannot mix aggregations and plain columns in SELECT "
                "(use GROUP BY for grouped output)")

        req = BrokerRequest(table_name=table, filter=filt,
                            query_options=options)
        if wins:
            if join is not None:
                raise PqlSyntaxError(
                    "window functions cannot mix with JOIN")
            if aggs or vecs or group_by_cols or having is not None:
                raise PqlSyntaxError(
                    "window functions cannot mix with aggregations, "
                    "GROUP BY, HAVING or VECTOR_SIMILARITY")
            if order_by or top_n is not None:
                raise PqlSyntaxError(
                    "outer ORDER BY/TOP do not apply to window queries — "
                    "rows come back in (PARTITION BY, ORDER BY) window "
                    "order")
            if "*" in cols:
                raise PqlSyntaxError(
                    "window queries must name their display columns "
                    "explicitly (SELECT * is not supported)")
            req.windows = wins
            req.selection = Selection(columns=cols, order_by=[],
                                      offset=offset,
                                      size=size if size is not None else 10)
            req.limit = size if size is not None else 10
            return req
        if vecs:
            if join is not None:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY cannot mix with JOIN")
            if len(vecs) > 1:
                raise PqlSyntaxError(
                    "only one VECTOR_SIMILARITY clause per query")
            if aggs or group_by_cols or having is not None or order_by:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY cannot mix with aggregations, "
                    "GROUP BY, HAVING or ORDER BY (results are ranked "
                    "by similarity score)")
            if "*" in cols:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY with SELECT * is not supported — "
                    "name the ride-along columns explicitly")
            if top_n is not None or size is not None:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY takes k as its third argument; "
                    "TOP/LIMIT do not apply")
            v = vecs[0]
            req.vector = v
            req.selection = Selection(columns=cols, order_by=[],
                                      offset=0, size=v.k)
            req.limit = v.k
            return req
        if aggs:
            req.aggregations = aggs
            if group_by_cols:
                req.group_by = GroupBy(columns=group_by_cols,
                                       top_n=top_n or size or 10)
            req.having = having
            req.limit = top_n or size or 10
        else:
            if group_by_cols:
                raise PqlSyntaxError("GROUP BY requires aggregations")
            req.selection = Selection(columns=cols or ["*"],
                                      order_by=order_by, offset=offset,
                                      size=size if size is not None else 10)
            req.limit = size if size is not None else 10
        if join is not None:
            _finalize_join(req, table, *join)
        return req

    def parse_join_clause(self, fact_table: str):
        """``JOIN dim ON a.x = b.y`` — returns (dim_table, left, right)
        raw qualified names; resolution against the two table names
        happens in _finalize_join once the whole query is parsed."""
        dim = self.expect(TokType.IDENT).value
        if dim == fact_table:
            raise PqlSyntaxError("self-joins are not supported")
        self.expect_kw("ON")
        left = self.expect(TokType.IDENT).value
        t = self.next()
        if t.type != TokType.OP or t.value != "=":
            raise PqlSyntaxError(
                f"JOIN ... ON supports only equality conditions, got "
                f"{t.value!r} at {t.pos}")
        right = self.expect(TokType.IDENT).value
        return dim, left, right

    def parse_select_list(self):
        items = []
        if self.peek().type == TokType.STAR:
            self.next()
            return ["*"]
        while True:
            items.append(self.parse_select_item())
            if self.peek().type == TokType.COMMA:
                self.next()
                continue
            return items

    def parse_select_item(self):
        t = self.peek()
        if t.type == TokType.IDENT and \
                self.toks[self.i + 1].type == TokType.LPAREN:
            if t.upper == "VECTOR_SIMILARITY":
                return self.parse_vector_call()
            if t.upper == "ROW_NUMBER":
                self.next()
                self.expect(TokType.LPAREN)
                self.expect(TokType.RPAREN)
                return self.parse_over_clause("ROW_NUMBER", None)
            if is_aggregation_function(t.value):
                agg = self.parse_agg_call()
                if self.peek().type == TokType.KEYWORD and \
                        self.peek().upper == "OVER":
                    if agg.function_name != "SUM":
                        raise PqlSyntaxError(
                            f"window function {agg.function_name} is not "
                            "supported (ROW_NUMBER | SUM)")
                    if agg.column == "*" or \
                            expr_mod.is_expression(agg.column):
                        raise PqlSyntaxError(
                            "SUM(...) OVER takes a plain column argument")
                    return self.parse_over_clause("SUM", agg.column)
                return agg
        if t.type == TokType.IDENT:
            return self.next().value
        raise PqlSyntaxError(f"bad select item at {t.pos}: {t.value!r}")

    def parse_over_clause(self, function: str,
                          column: Optional[str]) -> WindowSpec:
        """``OVER ( [PARTITION BY cols] ORDER BY cols )`` — ORDER BY is
        mandatory: the running-aggregate frame is defined by the window
        order, so an orderless window has no deterministic meaning."""
        self.expect_kw("OVER")
        self.expect(TokType.LPAREN)
        partition_by: List[str] = []
        if self.accept_kw("PARTITION", "BY"):
            partition_by = [self.expect(TokType.IDENT).value]
            while self.peek().type == TokType.COMMA:
                self.next()
                partition_by.append(self.expect(TokType.IDENT).value)
        if not self.accept_kw("ORDER", "BY"):
            raise PqlSyntaxError(
                f"window specification at {self.peek().pos} needs ORDER "
                "BY (running-aggregate frames are defined by the window "
                "order)")
        order_by = self.parse_order_list()
        self.expect(TokType.RPAREN)
        return WindowSpec(function=function, column=column,
                          partition_by=partition_by, order_by=order_by)

    def parse_vector_call(self) -> VectorSimilarity:
        """VECTOR_SIMILARITY(col, [f, f, ...], k[, 'COSINE'|'DOT'|'MIPS']
        [, nprobe=N]) — nprobe > 0 requests IVF ANN probing (segments
        without a built index fall back to the exact scan)."""
        self.next()                              # VECTOR_SIMILARITY
        self.expect(TokType.LPAREN)
        col = self.expect(TokType.IDENT).value
        self.expect(TokType.COMMA)
        self.expect(TokType.LBRACKET)
        q: List[float] = []
        while self.peek().type != TokType.RBRACKET:
            t = self.next()
            if t.type not in (TokType.INT, TokType.FLOAT):
                raise PqlSyntaxError(
                    f"expected a number in the query vector at {t.pos}, "
                    f"got {t.value!r}")
            q.append(float(t.value))
            if self.peek().type == TokType.COMMA:
                self.next()
        self.expect(TokType.RBRACKET)
        if not q:
            raise PqlSyntaxError("empty query vector")
        self.expect(TokType.COMMA)
        t = self.peek()
        k = int(self.expect(TokType.INT).value)
        if k <= 0:
            raise PqlSyntaxError(f"VECTOR_SIMILARITY k must be positive "
                                 f"at {t.pos}, got {k}")
        metric = "COSINE"
        nprobe = 0
        while self.peek().type == TokType.COMMA:
            self.next()
            t = self.peek()
            if t.type == TokType.STRING:
                m = self.next().value.upper()
                if m not in ("COSINE", "DOT", "MIPS"):
                    raise PqlSyntaxError(
                        f"unknown similarity metric {m!r} "
                        "(COSINE | DOT | MIPS)")
                metric = m
            elif t.type == TokType.IDENT and t.value.lower() == "nprobe":
                self.next()
                op = self.expect(TokType.OP)
                if op.value != "=":
                    raise PqlSyntaxError(
                        f"expected nprobe=N at {op.pos}, got {op.value!r}")
                nt = self.peek()
                nprobe = int(self.expect(TokType.INT).value)
                if nprobe <= 0:
                    raise PqlSyntaxError(
                        f"nprobe must be positive at {nt.pos}, got "
                        f"{nprobe}")
            else:
                raise PqlSyntaxError(
                    f"expected 'METRIC' or nprobe=N at {t.pos}, got "
                    f"{t.value!r}")
        self.expect(TokType.RPAREN)
        return VectorSimilarity(column=col, query=q, k=k, metric=metric,
                                nprobe=nprobe)

    def parse_agg_call(self) -> AggregationInfo:
        name = self.next().upper
        self.expect(TokType.LPAREN)
        if self.peek().type == TokType.STAR:
            self.next()
            col = "*"
        else:
            col = self.parse_column_or_expression()
        self.expect(TokType.RPAREN)
        return AggregationInfo(function_name=name, column=col)

    def parse_column_or_expression(self) -> str:
        """Plain column, or a transform call like time_convert(col,'D','H')
        — returned as a canonical expression string (parity:
        TransformExpressionTree's standardized column name)."""
        t = self.expect(TokType.IDENT)
        if self.peek().type != TokType.LPAREN or \
                not expr_mod.is_transform_function(t.value):
            return t.value
        return expr_mod.to_string(self._parse_expr_call(t.value))

    def _parse_expr_call(self, fname: str):
        self.expect(TokType.LPAREN)
        args = []
        if self.peek().type != TokType.RPAREN:
            args.append(self._parse_expr_arg())
            while self.peek().type == TokType.COMMA:
                self.next()
                args.append(self._parse_expr_arg())
        self.expect(TokType.RPAREN)
        return expr_mod.Call(fname.lower(), tuple(args))

    def _parse_expr_arg(self):
        t = self.next()
        if t.type == TokType.STRING:
            return expr_mod.Lit(t.value, is_string=True)
        if t.type in (TokType.INT, TokType.FLOAT):
            return expr_mod.Lit(t.value)
        if t.type == TokType.IDENT:
            if self.peek().type == TokType.LPAREN and \
                    expr_mod.is_transform_function(t.value):
                return self._parse_expr_call(t.value)
            return expr_mod.Col(t.value)
        raise PqlSyntaxError(
            f"bad expression argument at {t.pos}: {t.value!r}")

    def parse_ident_list(self) -> List[str]:
        out = [self.parse_column_or_expression()]
        while self.peek().type == TokType.COMMA:
            self.next()
            out.append(self.parse_column_or_expression())
        return out

    def parse_order_list(self) -> List[SelectionSort]:
        out = []
        while True:
            col = self.expect(TokType.IDENT).value
            asc = True
            if self.accept_kw("ASC"):
                asc = True
            elif self.accept_kw("DESC"):
                asc = False
            out.append(SelectionSort(column=col, ascending=asc))
            if self.peek().type == TokType.COMMA:
                self.next()
                continue
            return out

    # -- WHERE predicates --------------------------------------------------
    def parse_predicate(self) -> FilterQueryTree:
        return self.parse_or()

    def parse_or(self) -> FilterQueryTree:
        left = self.parse_and()
        children = [left]
        while self.accept_kw("OR"):
            children.append(self.parse_and())
        if len(children) == 1:
            return left
        return FilterQueryTree(FilterOperator.OR, children=children)

    def parse_and(self) -> FilterQueryTree:
        left = self.parse_unary()
        children = [left]
        while self.accept_kw("AND"):
            children.append(self.parse_unary())
        if len(children) == 1:
            return left
        return FilterQueryTree(FilterOperator.AND, children=children)

    def parse_unary(self) -> FilterQueryTree:
        if self.peek().type == TokType.LPAREN:
            self.next()
            node = self.parse_or()
            self.expect(TokType.RPAREN)
            return node
        # REGEXP_LIKE(col, 'pattern')
        t = self.peek()
        if t.type == TokType.IDENT and t.upper == "REGEXP_LIKE" and \
                self.toks[self.i + 1].type == TokType.LPAREN:
            self.next(); self.next()
            col = self.expect(TokType.IDENT).value
            self.expect(TokType.COMMA)
            pat = self.expect(TokType.STRING).value
            self.expect(TokType.RPAREN)
            return FilterQueryTree(FilterOperator.REGEXP_LIKE, column=col,
                                   values=[pat])
        return self.parse_comparison()

    def parse_literal(self) -> str:
        t = self.next()
        if t.type in (TokType.STRING, TokType.INT, TokType.FLOAT,
                      TokType.IDENT):
            return t.value
        raise PqlSyntaxError(f"expected literal at {t.pos}, got {t.value!r}")

    def parse_comparison(self) -> FilterQueryTree:
        col = self.parse_column_or_expression()
        t = self.peek()
        if t.type == TokType.OP:
            op = self.next().value
            val = self.parse_literal()
            return _comparison_to_tree(col, op, val)
        negate = self.accept_kw("NOT")
        if self.accept_kw("BETWEEN"):
            lo = self.parse_literal()
            self.expect_kw("AND")
            hi = self.parse_literal()
            node = FilterQueryTree(FilterOperator.RANGE, column=col,
                                   lower=lo, upper=hi,
                                   lower_inclusive=True, upper_inclusive=True)
            if negate:
                raise PqlSyntaxError("NOT BETWEEN is not supported")
            return node
        if self.accept_kw("IN"):
            self.expect(TokType.LPAREN)
            vals = [self.parse_literal()]
            while self.peek().type == TokType.COMMA:
                self.next()
                vals.append(self.parse_literal())
            self.expect(TokType.RPAREN)
            return FilterQueryTree(
                FilterOperator.NOT_IN if negate else FilterOperator.IN,
                column=col, values=vals)
        if self.accept_kw("IS"):
            is_not = self.accept_kw("NOT")
            self.expect_kw("NULL")
            return FilterQueryTree(
                FilterOperator.IS_NOT_NULL if is_not else FilterOperator.IS_NULL,
                column=col)
        raise PqlSyntaxError(f"bad predicate near {t.pos}: {t.value!r}")

    # -- HAVING ------------------------------------------------------------
    def parse_having(self) -> HavingNode:
        return self.parse_having_or()

    def parse_having_or(self) -> HavingNode:
        children = [self.parse_having_and()]
        while self.accept_kw("OR"):
            children.append(self.parse_having_and())
        if len(children) == 1:
            return children[0]
        return HavingNode(FilterOperator.OR, children=children)

    def parse_having_and(self) -> HavingNode:
        children = [self.parse_having_unary()]
        while self.accept_kw("AND"):
            children.append(self.parse_having_unary())
        if len(children) == 1:
            return children[0]
        return HavingNode(FilterOperator.AND, children=children)

    def parse_having_unary(self) -> HavingNode:
        if self.peek().type == TokType.LPAREN:
            self.next()
            node = self.parse_having_or()
            self.expect(TokType.RPAREN)
            return node
        agg = self.parse_agg_call()
        t = self.peek()
        if t.type == TokType.OP:
            op = self.next().value
            val = self.parse_literal()
            tree = _comparison_to_tree("_", op, val)
            return HavingNode(tree.operator, agg=agg, values=tree.values,
                              lower=tree.lower, upper=tree.upper,
                              lower_inclusive=tree.lower_inclusive,
                              upper_inclusive=tree.upper_inclusive)
        if self.accept_kw("BETWEEN"):
            lo = self.parse_literal()
            self.expect_kw("AND")
            hi = self.parse_literal()
            return HavingNode(FilterOperator.RANGE, agg=agg, lower=lo,
                              upper=hi)
        if self.accept_kw("IN"):
            self.expect(TokType.LPAREN)
            vals = [self.parse_literal()]
            while self.peek().type == TokType.COMMA:
                self.next()
                vals.append(self.parse_literal())
            self.expect(TokType.RPAREN)
            return HavingNode(FilterOperator.IN, agg=agg, values=vals)
        raise PqlSyntaxError(f"bad HAVING predicate at {t.pos}")


def _qual_split(name: str, fact: str, dim: str, what: str):
    """``table.column`` → (side, column) against the two joined tables."""
    if expr_mod.is_expression(name):
        raise PqlSyntaxError(
            f"transform expressions are not supported in JOIN queries "
            f"({what} {name!r})")
    if "." not in name:
        raise PqlSyntaxError(
            f"{what} {name!r} must be qualified as <table>.<column> in a "
            f"JOIN query (FROM {fact} JOIN {dim})")
    t, c = name.split(".", 1)
    if t == fact:
        return "fact", c
    if t == dim:
        return "dim", c
    raise PqlSyntaxError(
        f"{what} {name!r} references unknown table {t!r} "
        f"(FROM {fact} JOIN {dim})")


def _filter_side(node: FilterQueryTree, fact: str, dim: str) -> str:
    if node.is_leaf():
        return _qual_split(node.column, fact, dim, "WHERE column")[0]
    sides = {_filter_side(c, fact, dim) for c in node.children}
    if len(sides) != 1:
        raise PqlSyntaxError(
            "a nested OR predicate cannot span both join sides — only "
            "top-level AND may mix fact-side and dim-side conditions")
    return sides.pop()


def _strip_qualifiers(node: FilterQueryTree, fact: str, dim: str) -> None:
    if node.is_leaf():
        node.column = _qual_split(node.column, fact, dim,
                                  "WHERE column")[1]
        return
    for c in node.children:
        _strip_qualifiers(c, fact, dim)


def _finalize_join(req: BrokerRequest, fact: str, dim: str,
                   left: str, right: str) -> None:
    """Resolve qualified names of a JOIN query into the compiled form:
    fact columns unqualified, dim columns kept ``<dim>.<col>``-qualified
    (group keys) or collected into the JoinSpec; the WHERE tree splits
    into fact-side conjuncts (stay on the request) and dim-side
    conjuncts (pushed down into the stage-1 dim scan)."""
    if req.is_selection and not req.is_aggregation:
        raise PqlSyntaxError(
            "JOIN queries must aggregate (SELECT agg(...) "
            "[GROUP BY ...]) — row selection over joins is not supported")
    l_side, l_col = _qual_split(left, fact, dim, "join key")
    r_side, r_col = _qual_split(right, fact, dim, "join key")
    if {l_side, r_side} != {"fact", "dim"}:
        raise PqlSyntaxError(
            "JOIN ... ON must relate one fact-side and one dim-side "
            f"column (got {left} = {right})")
    fact_key = l_col if l_side == "fact" else r_col
    dim_key = r_col if l_side == "fact" else l_col

    join = JoinSpec(dim_table=dim, fact_key=fact_key, dim_key=dim_key)

    # WHERE: split top-level AND conjuncts by side
    if req.filter is not None:
        conjuncts = req.filter.children \
            if req.filter.operator == FilterOperator.AND \
            else [req.filter]
        fact_nodes, dim_nodes = [], []
        for c in conjuncts:
            (fact_nodes if _filter_side(c, fact, dim) == "fact"
             else dim_nodes).append(c)
        for c in fact_nodes + dim_nodes:
            _strip_qualifiers(c, fact, dim)
        req.filter = None if not fact_nodes else (
            fact_nodes[0] if len(fact_nodes) == 1 else
            FilterQueryTree(FilterOperator.AND, children=fact_nodes))
        join.dim_filter = None if not dim_nodes else (
            dim_nodes[0] if len(dim_nodes) == 1 else
            FilterQueryTree(FilterOperator.AND, children=dim_nodes))

    # aggregations: fact metrics only (COUNT(*) excepted)
    for a in req.aggregations:
        if a.column == "*":
            continue
        side, c = _qual_split(a.column, fact, dim, "aggregation argument")
        if side != "fact":
            raise PqlSyntaxError(
                f"aggregation over dim-table column {a.column!r} is not "
                "supported — aggregate fact metrics; dim columns may "
                "filter (WHERE) and group (GROUP BY)")
        a.column = c
    if req.having is not None:
        _rewrite_having_join(req.having, fact, dim)

    # GROUP BY: fact keys unqualified, dim keys stay qualified
    if req.group_by is not None:
        out = []
        for g in req.group_by.columns:
            side, c = _qual_split(g, fact, dim, "group-by column")
            if side == "fact":
                out.append(c)
            else:
                out.append(f"{dim}.{c}")
                if c not in join.dim_columns:
                    join.dim_columns.append(c)
        req.group_by.columns = out
    req.join = join


def _rewrite_having_join(node: HavingNode, fact: str, dim: str) -> None:
    for c in node.children:
        _rewrite_having_join(c, fact, dim)
    if node.agg is not None and node.agg.column != "*":
        side, c = _qual_split(node.agg.column, fact, dim,
                              "HAVING aggregation argument")
        if side != "fact":
            raise PqlSyntaxError(
                f"HAVING over dim-table column {node.agg.column!r} is "
                "not supported")
        node.agg.column = c


def _comparison_to_tree(col: str, op: str, val: str) -> FilterQueryTree:
    if op == "=":
        return FilterQueryTree(FilterOperator.EQUALITY, column=col,
                               values=[val])
    if op in ("<>", "!="):
        return FilterQueryTree(FilterOperator.NOT, column=col, values=[val])
    if op == "<":
        return FilterQueryTree(FilterOperator.RANGE, column=col, upper=val,
                               upper_inclusive=False)
    if op == "<=":
        return FilterQueryTree(FilterOperator.RANGE, column=col, upper=val,
                               upper_inclusive=True)
    if op == ">":
        return FilterQueryTree(FilterOperator.RANGE, column=col, lower=val,
                               lower_inclusive=False)
    if op == ">=":
        return FilterQueryTree(FilterOperator.RANGE, column=col, lower=val,
                               lower_inclusive=True)
    raise PqlSyntaxError(f"unknown comparison operator {op!r}")
