"""Server-side cluster participant: state transitions → segment lifecycle.

Parity: pinot-server/.../starter/helix/SegmentOnlineOfflineStateModelFactory
.java:81-156 (OFFLINE→ONLINE downloads + loads, OFFLINE→CONSUMING starts
the LLC consumer, CONSUMING→ONLINE swaps in the committed copy,
ONLINE→OFFLINE unloads, →DROPPED deletes local data) +
SegmentFetcherAndLoader (deep-store fetch → ImmutableSegmentLoader).
"""
from __future__ import annotations

import os
import tempfile
import threading
from typing import Optional

from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.metrics import MetricsRegistry, ServerMeter
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.state_machine import StateModel
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.server.instance import ServerInstance


class ServerParticipant(StateModel):
    def __init__(self, server: ServerInstance, manager: ResourceManager,
                 completion=None, work_dir: Optional[str] = None):
        """`completion`: the controller's RealtimeSegmentManager (or an
        HTTP client speaking the same protocol) — required for realtime
        tables; `work_dir`: where committed segments are built."""
        self.server = server
        self.manager = manager
        self.completion = completion
        self.work_dir = work_dir
        self._realtime = None
        # CONSUMING and ONLINE transitions for different segments can
        # arrive on different state-machine threads; the lazy realtime
        # manager must be built exactly once
        self._realtime_lock = threading.Lock()
        # readiness: GOOD once current state converges with ideal state
        # (parity: HelixServerStarter registering ServiceStatus callbacks)
        from pinot_tpu.common.service_status import (
            IdealStateAndCurrentStateMatchCallback, set_service_status)
        set_service_status(server.instance_id,
                           IdealStateAndCurrentStateMatchCallback(
                               manager.coordinator, server.instance_id))

    @property
    def realtime(self):
        with self._realtime_lock:
            if self._realtime is None:
                if self.completion is None:
                    raise RuntimeError(
                        "realtime transition but no completion client "
                        "wired")
                from pinot_tpu.realtime.data_manager import \
                    RealtimeTableDataManager
                work = self.work_dir or os.path.join(
                    tempfile.gettempdir(),
                    f"pinot_tpu_rt_{self.server.instance_id}")
                self._realtime = RealtimeTableDataManager(
                    self.server, self.manager, self.completion, work,
                    fetcher=self._fetch_segment_dir)
            return self._realtime

    def _work_root(self) -> str:
        return self.work_dir or os.path.join(
            tempfile.gettempdir(),
            f"pinot_tpu_seg_{self.server.instance_id}")

    def local_segment_dir(self, table: str, segment: str) -> str:
        """This server's local artifact cache location for a segment —
        the cold-start recovery source (survives process restarts)."""
        return os.path.join(self._work_root(), "fetched", table, segment)

    def quarantine_root(self) -> str:
        return os.path.join(self._work_root(), "quarantine")


    def _fetch_segment_dir(self, table: str, segment: str,
                           download_path: str,
                           expected_crc=None) -> str:
        """SegmentFetcherAndLoader parity: a remote downloadPath (e.g.
        http://controller/deepstore/...) is fetched through the PinotFS
        registry into the server's local segment cache; local paths
        load in place (the shared-filesystem deployment).

        Every artifact is CRC-verified against the cluster-state record
        before it is served. A valid cached copy short-circuits the
        download — a restarted server reloads its committed segments
        from local disk (cold-start recovery); a corrupt copy is moved
        to quarantine/ and re-fetched, and a corrupt DOWNLOAD is
        quarantined and fails the transition (→ ERROR replica, repaired
        by the controller's integrity scrubber).
        """
        from pinot_tpu.segment.integrity import (SegmentIntegrityError,
                                                 quarantine_segment,
                                                 verify_segment)
        metrics = getattr(self.server, "metrics", None) or \
            MetricsRegistry()
        download_path = self.manager.resolve_download_path(download_path)
        if "://" not in download_path or \
                download_path.startswith("file://"):
            local = download_path.replace("file://", "", 1)
            # shared-filesystem deployment: verify in place; the deep
            # store is the controller's to quarantine, not this server's
            verify_segment(local, expected_crc)
            return local
        from pinot_tpu.common.filesystem import get_fs
        local = self.local_segment_dir(table, segment)
        if os.path.isdir(local):
            try:
                verify_segment(local, expected_crc)
                metrics.meter(ServerMeter.SEGMENT_LOCAL_RELOADS).mark()
                return local            # cold start: no re-download
            except SegmentIntegrityError:
                metrics.meter(ServerMeter.SEGMENT_CRC_MISMATCHES).mark()
                quarantine_segment(local, self.quarantine_root())
        # transient deep-store failures (controller restarting, network
        # blip) retry with backoff before the transition goes ERROR
        # (parity: SegmentFetcherAndLoader's RetryPolicies-wrapped fetch)
        from pinot_tpu.common.retry import ExponentialBackoffRetryPolicy
        ExponentialBackoffRetryPolicy(attempts=3, initial_delay_s=0.2) \
            .attempt(lambda: get_fs(download_path).copy(download_path,
                                                        local),
                     # transient classes only: a 404/permission/URI error
                     # can't heal and must fail the transition fast
                     retry_on=(ConnectionError, TimeoutError, OSError))
        metrics.meter(ServerMeter.SEGMENT_DOWNLOADS).mark()
        # seeded crash point: process dies after the download landed but
        # before verification/registration — restart must re-validate
        # the cached bytes before serving them
        crash_points.hit("server.post_download")
        try:
            verify_segment(local, expected_crc)
        except SegmentIntegrityError:
            metrics.meter(ServerMeter.SEGMENT_CRC_MISMATCHES).mark()
            quarantine_segment(local, self.quarantine_root())
            raise
        return local

    def scan_local_artifacts(self) -> dict:
        """Cold-start scan: CRC-validate every cached artifact under the
        work dir, quarantining corrupt ones BEFORE transitions replay —
        a restarted server then re-enters its assignments serving only
        verified local copies (valid ones reload with no deep-store
        re-download). Returns {"valid": [...], "quarantined": [...]} of
        (table, segment) pairs."""
        from pinot_tpu.segment.integrity import (SegmentIntegrityError,
                                                 quarantine_segment,
                                                 verify_segment)
        report = {"valid": [], "quarantined": []}
        fetched = os.path.join(self._work_root(), "fetched")
        if not os.path.isdir(fetched):
            return report
        for table in sorted(os.listdir(fetched)):
            tdir = os.path.join(fetched, table)
            if not os.path.isdir(tdir):
                continue
            for segment in sorted(os.listdir(tdir)):
                seg_dir = os.path.join(tdir, segment)
                if not os.path.isdir(seg_dir):
                    continue
                record = self.manager.segment_metadata(table, segment)
                expected = (record or {}).get("crc")
                try:
                    verify_segment(seg_dir, expected)
                    report["valid"].append((table, segment))
                except SegmentIntegrityError:
                    quarantine_segment(seg_dir, self.quarantine_root())
                    report["quarantined"].append((table, segment))
        return report

    def on_become_consuming(self, table: str, segment: str) -> None:
        self.realtime.start_consuming(table, segment)

    def on_become_online(self, table: str, segment: str) -> None:
        if table.endswith("_REALTIME"):
            self.realtime.on_segment_online(table, segment)
            return
        meta = self.manager.segment_metadata(table, segment)
        if meta is None:
            raise ValueError(f"no metadata for {table}/{segment}")
        # SegmentPreProcessor parity: the current schema synthesizes
        # default columns for pre-evolution segments, and configured
        # inverted indexes are generated when the artifact lacks them
        from pinot_tpu.common.table_name import raw_table
        schema = self.manager.get_schema(raw_table(table))
        config = self.manager.get_table_config(table)
        seg_dir = self._fetch_segment_dir(table, segment,
                                          meta["downloadPath"],
                                          expected_crc=meta.get("crc"))
        seg = ImmutableSegmentLoader.load(
            seg_dir, schema=schema,
            index_loading_config=(config.indexing_config
                                  if config else None))
        self.server.data_manager.table(table, create=True).add_segment(seg)
        # residency admission: the manager decides the attach tier
        # (device within budget, host over it) and keeps the verified
        # local artifact dir as the disk-tier reload source; device
        # warm-up stays routed through it (lazy by default)
        residency = getattr(self.server, "residency", None)
        if residency is not None:
            residency.track(table, seg, seg_dir=seg_dir)

    def on_become_offline(self, table: str, segment: str) -> None:
        if self._realtime is not None and table.endswith("_REALTIME"):
            self._realtime.on_segment_offline(table, segment)
            return
        tdm = self.server.data_manager.table(table)
        if tdm is not None:
            tdm.remove_segment(segment)

    def on_become_dropped(self, table: str, segment: str) -> None:
        # a dropped segment's cached artifact must not survive to be
        # reused by a future same-name upload (reloads bounce through
        # OFFLINE, not DROPPED, so refresh reuse is unaffected)
        import shutil
        shutil.rmtree(self.local_segment_dir(table, segment),
                      ignore_errors=True)

    def seal_consuming(self, timeout_s: float = 20.0) -> bool:
        """Graceful drain: seal (commit) the consuming segments this
        server owns, where possible, before it departs. No-op (True)
        when the server never consumed."""
        if self._realtime is None:
            return True
        return self._realtime.seal_all(timeout_s)

    def shutdown(self) -> None:
        if self._realtime is not None:
            self._realtime.shutdown()
