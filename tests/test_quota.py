"""Broker ingress quota: token-bucket semantics, injectable clock
(no wall-clock sleeps anywhere here), per-tenant buckets, broker-count
convergence, and the Retry-After surface.

Parity targets: HelixExternalViewBasedQueryQuotaManager (per-table QPS
from quotaConfig.maxQueriesPerSecond, divided across online brokers)
with the token-bucket upgrade the overload PR introduces.
"""
import pytest

from pinot_tpu.broker.quota import (HitCounter, QueryQuotaManager,
                                    TokenBucket)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ---------------------------------------------------------------------------
# TokenBucket
# ---------------------------------------------------------------------------


def test_bucket_burst_then_refill():
    b = TokenBucket(rate=2.0, now_s=0.0)   # burst defaults to max(1, 2)
    assert b.peek(0.0)
    b.commit()
    assert b.peek(0.0)
    b.commit()
    assert not b.peek(0.0)                 # burst spent
    assert b.retry_after_s(0.0) == pytest.approx(0.5)
    assert b.peek(0.6)                     # 0.6s x 2/s = 1.2 tokens
    b.commit()
    assert not b.peek(0.6)


def test_bucket_fractional_rate_admits_one():
    b = TokenBucket(rate=0.5, now_s=0.0)
    assert b.burst == 1.0                  # never below one request
    assert b.peek(0.0)
    b.commit()
    assert not b.peek(1.0)
    assert b.peek(2.0)                     # one token back after 2s


def test_bucket_reconfigure_preserves_tokens():
    b = TokenBucket(rate=10.0, now_s=0.0)
    for _ in range(8):
        b.commit()
    b.reconfigure(5.0, None)
    assert b.rate == 5.0
    assert b.tokens == pytest.approx(2.0)  # NOT a fresh burst
    b.reconfigure(1.0, None)               # burst shrinks below tokens
    assert b.tokens <= b.burst == 1.0


def test_reconfigure_settles_idle_gap_at_old_rate():
    # a quota raise after an idle stretch must not retroactively credit
    # the whole gap at the NEW rate — that would hand the table the
    # full fresh burst the instant the config lands
    b = TokenBucket(rate=2.0, now_s=0.0)
    for _ in range(2):
        b.commit()                         # empty at t=0
    b.reconfigure(100.0, None, now_s=100.0)
    # the 100s gap was settled at the OLD rate (capped at old burst 2)
    assert b.tokens == pytest.approx(2.0)
    assert b.burst == 100.0 and b.peek(100.0)


# ---------------------------------------------------------------------------
# QueryQuotaManager — the satellite fix: exact-at-limit traffic is
# stable and REJECTED requests consume nothing, so a throttled tenant
# recovers as soon as its bucket refills.
# ---------------------------------------------------------------------------


def test_exact_at_limit_traffic_never_flaps():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 10.0)
    # 10 QPS offered at exactly 10 QPS quota, for 5 seconds
    rejected = 0
    for _ in range(50):
        clk.advance(0.1)
        if not q.acquire("t"):
            rejected += 1
    assert rejected == 0


def test_rejected_requests_consume_nothing():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 2.0)
    assert q.acquire("t")
    assert q.acquire("t")
    # a flood of rejected attempts while throttled...
    for _ in range(100):
        assert not q.acquire("t")
    # ...must not delay recovery: 1s at 2/s refills 2 full tokens
    clk.advance(1.0)
    assert q.acquire("t")
    assert q.acquire("t")
    assert not q.acquire("t")


def test_acquire_injectable_now_ms_needs_no_sleeps():
    q = QueryQuotaManager(clock=lambda: 0.0)
    q.set_qps_quota("t", 1.0)
    assert q.acquire("t", now_ms=0.0)
    assert not q.acquire("t", now_ms=100.0)
    assert q.acquire("t", now_ms=1100.0)   # 1.1s later: one token back


def test_retry_after_from_refill_time():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 4.0)
    for _ in range(4):
        assert q.acquire("t")
    d = q.acquire("t")
    assert not d
    assert d.cause == "tableQuota"
    assert d.retry_after_s == pytest.approx(0.25)


def test_unconfigured_table_always_admits():
    q = QueryQuotaManager(clock=lambda: 0.0)
    for _ in range(1000):
        assert q.acquire("anything")


# ---------------------------------------------------------------------------
# Per-tenant buckets
# ---------------------------------------------------------------------------


def test_tenant_bucket_isolates_within_table():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 100.0)
    q.set_tenant_qps_quota("t", "aggressor", 2.0)
    for _ in range(2):
        assert q.acquire("t", "aggressor")
    d = q.acquire("t", "aggressor")
    assert not d and d.cause == "tenantQuota"
    # other tenants and untagged traffic ride the table bucket only
    assert q.acquire("t", "victim")
    assert q.acquire("t", None)


def test_tenant_rejection_does_not_debit_table_bucket():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 3.0)
    q.set_tenant_qps_quota("t", "a", 1.0)
    assert q.acquire("t", "a")
    for _ in range(10):
        assert not q.acquire("t", "a")     # tenant-throttled
    # the table bucket still has its remaining 2 tokens for others
    assert q.acquire("t", "b")
    assert q.acquire("t", "b")
    assert not q.acquire("t", "b")


def test_table_rejection_does_not_debit_tenant_bucket():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 1.0)
    q.set_tenant_qps_quota("t", "a", 5.0)
    assert q.acquire("t", "a")             # spends table's only token
    d = q.acquire("t", "a")
    assert not d and d.cause == "tableQuota"
    # tenant bucket untouched by the table-level rejection: after the
    # table refills, all remaining tenant tokens are still there
    clk.advance(4.0)
    assert q.acquire("t", "a")             # tenant 4 spent of 5... no:
    clk.advance(60.0)                      # refill both fully
    spent = 0
    while q.acquire("t", "a") and spent < 20:
        spent += 1
        clk.advance(1.0)                   # table refills 1/s; tenant 5/s
    assert spent >= 5


# ---------------------------------------------------------------------------
# Convergence across brokers (cluster-watcher path)
# ---------------------------------------------------------------------------


def test_configure_table_divides_by_broker_count():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.configure_table("t", 30.0, {"a": 9.0}, num_brokers=3)
    stats = q.stats()["t"]
    assert stats["maxQps"] == pytest.approx(10.0)
    assert stats["tenants"]["a"]["maxQps"] == pytest.approx(3.0)


def test_configure_table_removes_stale_tenants_and_quota():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.configure_table("t", 10.0, {"a": 5.0, "b": 5.0})
    q.configure_table("t", None, {"a": 5.0})
    stats = q.stats()["t"]
    assert stats["maxQps"] is None         # table quota dropped
    assert set(stats["tenants"]) == {"a"}
    # and with the quota gone, traffic flows freely again
    for _ in range(100):
        assert q.acquire("t", "c")


def test_reconfigure_same_rate_preserves_bucket_state():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.configure_table("t", 2.0, {})
    assert q.acquire("t")
    assert q.acquire("t")
    assert not q.acquire("t")
    # a view-change re-apply of the SAME config must not re-arm burst
    q.configure_table("t", 2.0, {})
    assert not q.acquire("t")


# ---------------------------------------------------------------------------
# HitCounter (observed offered load; injectable now_ms end to end)
# ---------------------------------------------------------------------------


def test_hit_counter_injectable_clock_window():
    h = HitCounter()
    for i in range(5):
        h.hit(now_ms=10_000 + i * 100)
    assert h.hits_in_window(now_ms=10_400) == 5
    # a full window later they have all aged out
    assert h.hits_in_window(now_ms=11_500) == 0


def test_observed_qps_counts_rejected_attempts():
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 1.0)
    for _ in range(7):
        q.acquire("t")                     # 1 admitted + 6 rejected
    assert q.observed_qps("t", now_ms=clk.t * 1e3) == 7


# ---------------------------------------------------------------------------
# Cluster-watcher convergence (table config → this broker's buckets)
# ---------------------------------------------------------------------------


class _StubCoordinator:
    def watch_external_views(self, fn):
        self.on_view = fn

    def tables(self):
        return []


class _StubManager:
    """One typed config (t_OFFLINE); the realtime side has none."""

    def __init__(self, config):
        self.config = config

    def get_table_config(self, table):
        return self.config if table == "t_OFFLINE" else None


def _watcher_for(config, quota, num_brokers=1):
    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
    return BrokerClusterWatcher(
        _StubCoordinator(), _StubManager(config), quota=quota,
        num_brokers_fn=lambda: num_brokers)


def test_watcher_converges_quota_and_tenants_from_table_config():
    import json as _json

    from pinot_tpu.common.table_config import QuotaConfig, TableConfig

    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    config = TableConfig(
        "t", quota_config=QuotaConfig(max_queries_per_second=30.0),
        custom_config={"tenantQuotas": _json.dumps({"a": 9.0})})
    w = _watcher_for(config, q, num_brokers=3)
    w._apply_quota_config("t_OFFLINE")
    stats = q.stats()["t"]
    # cluster-wide 30 qps over 3 live brokers → 10 here; tenant 9 → 3
    assert stats["maxQps"] == pytest.approx(10.0)
    assert stats["tenants"]["a"]["maxQps"] == pytest.approx(3.0)


def test_watcher_malformed_tenant_quotas_fail_open():
    from pinot_tpu.common.table_config import QuotaConfig, TableConfig

    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    config = TableConfig(
        "t", quota_config=QuotaConfig(max_queries_per_second=10.0),
        custom_config={"tenantQuotas": "{not json"})
    w = _watcher_for(config, q)
    w._apply_quota_config("t_OFFLINE")
    stats = q.stats()["t"]
    assert stats["maxQps"] == pytest.approx(10.0)
    assert stats["tenants"] == {}          # malformed → no tenant limit
    assert q.acquire("t", "anyone")


def test_watcher_no_quota_config_leaves_table_unlimited():
    from pinot_tpu.common.table_config import TableConfig

    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    w = _watcher_for(TableConfig("t"), q)
    w._apply_quota_config("t_OFFLINE")
    assert "t" not in q.stats()
    for _ in range(100):
        assert q.acquire("t", "anyone")


def test_zero_rate_quota_rejects_with_finite_retry_after():
    """maxQueriesPerSecond=0 blocks a table: after the single burst
    token, every acquire rejects with a FINITE Retry-After (inf would
    break the JSON body and the HTTP header's ceil)."""
    import math

    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    q.set_qps_quota("t", 0.0)
    assert q.acquire("t")              # burst floor admits one
    d = q.acquire("t")
    assert not d
    assert math.isfinite(d.retry_after_s) and d.retry_after_s > 0


def test_observed_qps_uses_manager_clock_not_wall_clock():
    """acquire() stamps offered-load hits on the manager's clock;
    observed_qps must read the window on the SAME clock — with the
    default monotonic clock a wall-clock read would see every hit as
    ancient and always report 0."""
    q = QueryQuotaManager()            # default clock: time.monotonic
    q.set_qps_quota("t", 100.0)
    for _ in range(5):
        q.acquire("t")
    assert q.observed_qps("t") == 5
    assert q.stats()["t"]["observedQps"] == 5


def test_broker_membership_change_redivides_quota_shares():
    """A broker joining or dying changes every broker's share of each
    table quota but fires NO external-view event — reapply_quotas (the
    live-instance hook) must re-divide by the current count."""
    from pinot_tpu.common.table_config import QuotaConfig, TableConfig

    count = [1]
    config = TableConfig(
        "t", quota_config=QuotaConfig(max_queries_per_second=100.0))
    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher

    class _Coord(_StubCoordinator):
        def tables(self):
            return ["t_OFFLINE"]

        def external_view(self, table):
            from pinot_tpu.common.cluster_state import TableView
            return TableView(table, {})

    q = QueryQuotaManager(clock=FakeClock())
    w = BrokerClusterWatcher(_Coord(), _StubManager(config), quota=q,
                             num_brokers_fn=lambda: count[0])
    w._apply_quota_config("t_OFFLINE")
    assert q.stats()["t"]["maxQps"] == pytest.approx(100.0)
    count[0] = 2                           # a second broker joined
    w.reapply_quotas()
    assert q.stats()["t"]["maxQps"] == pytest.approx(50.0)
    count[0] = 1                           # ...and died again
    w.reapply_quotas()
    assert q.stats()["t"]["maxQps"] == pytest.approx(100.0)


def test_workload_tag_gated_by_access_control():
    """An explicit OPTION(workload=...) spends THAT tenant's quota and
    joins its scheduler group — the ACL's allow_workload hook can bind
    tags to authenticated principals (default: allow, cooperative)."""
    import tempfile as _tempfile

    from fixtures import build_segment
    from pinot_tpu.broker import (BrokerRequestHandler,
                                  InProcessTransport, RoutingManager)
    from pinot_tpu.broker.access_control import AllowAllAccessControl
    from pinot_tpu.common.cluster_state import ONLINE, TableView
    from pinot_tpu.server import ServerInstance

    class OwnTagOnly(AllowAllAccessControl):
        def allow_workload(self, identity, workload):
            return workload == "alice"

    servers = {"S": ServerInstance("S")}
    seg, _ = build_segment(_tempfile.mkdtemp(), n=300, seed=31,
                           name="acl_0")
    servers["S"].data_manager.table("baseballStats_OFFLINE",
                                    create=True).add_segment(seg)
    routing = RoutingManager()
    routing.update_view(TableView("baseballStats_OFFLINE",
                                  {"acl_0": {"S": ONLINE}}))
    handler = BrokerRequestHandler(routing, InProcessTransport(servers),
                                   access_control=OwnTagOnly())
    try:
        ok = handler.handle("SELECT COUNT(*) FROM baseballStats "
                            "OPTION(workload=alice)")
        assert not ok.exceptions
        denied = handler.handle("SELECT COUNT(*) FROM baseballStats "
                                "OPTION(workload=victim)")
        assert denied.exceptions[0]["errorCode"] == 180
        assert "workload" in denied.exceptions[0]["message"]
    finally:
        servers["S"].stop()
        handler.close()


def test_watcher_hybrid_types_merge_not_clobber():
    """A hybrid table's quota lives on whichever typed config defines
    it; a view change on the OTHER type must not clobber it (and when
    both types define quotas, the raw-table bucket gets the sum)."""
    import json as _json

    from pinot_tpu.common.table_config import (QuotaConfig, TableConfig,
                                               TableType)

    class _Mgr:
        def __init__(self, configs):
            self.configs = configs

        def get_table_config(self, table):
            return self.configs.get(table)

    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
    clk = FakeClock()
    q = QueryQuotaManager(clock=clk)
    configs = {
        "t_OFFLINE": TableConfig(
            "t", quota_config=QuotaConfig(max_queries_per_second=30.0),
            custom_config={"tenantQuotas": _json.dumps({"a": 9.0})}),
        "t_REALTIME": TableConfig("t", table_type=TableType.REALTIME),
    }
    w = BrokerClusterWatcher(_StubCoordinator(), _Mgr(configs), quota=q,
                             num_brokers_fn=lambda: 1)
    # the REALTIME view change (no quotaConfig on that side) converges
    # the MERGED config — the offline quota survives
    w._apply_quota_config("t_REALTIME")
    stats = q.stats()["t"]
    assert stats["maxQps"] == pytest.approx(30.0)
    assert stats["tenants"]["a"]["maxQps"] == pytest.approx(9.0)
    # both sides defining quotas: allowances sum at the raw bucket
    configs["t_REALTIME"] = TableConfig(
        "t", table_type=TableType.REALTIME,
        quota_config=QuotaConfig(max_queries_per_second=10.0),
        custom_config={"tenantQuotas": _json.dumps({"a": 1.0})})
    w._apply_quota_config("t_OFFLINE")
    stats = q.stats()["t"]
    assert stats["maxQps"] == pytest.approx(40.0)
    assert stats["tenants"]["a"]["maxQps"] == pytest.approx(10.0)
