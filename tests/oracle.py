"""Independent pure-numpy oracle for golden query checks.

Deliberately written against the RAW column arrays (never the segment /
engine code paths) so engine bugs can't cancel out — the same role H2 plays
in the reference's integration tests
(ClusterIntegrationTestUtils.setUpH2TableWithAvro).
"""
from __future__ import annotations

import re
from typing import Dict, List

import numpy as np


def mask_eq(col, v):
    return np.asarray([x == v for x in col]) if isinstance(col[0], list) \
        else (np.asarray(col) == v)


class Oracle:
    """cols: dict of raw numpy arrays / list-of-lists (MV)."""

    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = cols
        self.n = len(next(iter(cols.values())))

    def mask(self, fn) -> np.ndarray:
        """fn: row-dict → bool, evaluated row-at-a-time (slow but simple)."""
        out = np.zeros(self.n, dtype=bool)
        keys = list(self.cols.keys())
        for i in range(self.n):
            row = {k: self.cols[k][i] for k in keys}
            out[i] = bool(fn(row))
        return out

    # -- aggregations ------------------------------------------------------
    def count(self, m):
        return int(m.sum())

    def vals(self, col, m):
        v = self.cols[col]
        if isinstance(v, list):  # MV
            return np.array([x for i in np.nonzero(m)[0] for x in v[i]])
        return np.asarray(v)[m]

    def sum(self, col, m):
        return float(np.sum(self.vals(col, m).astype(np.float64)))

    def min(self, col, m):
        v = self.vals(col, m)
        return float(v.min()) if len(v) else float("inf")

    def max(self, col, m):
        v = self.vals(col, m)
        return float(v.max()) if len(v) else float("-inf")

    def avg(self, col, m):
        v = self.vals(col, m).astype(np.float64)
        return float(v.mean()) if len(v) else float("-inf")

    def minmaxrange(self, col, m):
        v = self.vals(col, m)
        return float(v.max() - v.min()) if len(v) else float("-inf")

    def distinctcount(self, col, m):
        return int(len(np.unique(self.vals(col, m))))

    def percentile(self, col, m, q):
        v = np.sort(self.vals(col, m).astype(np.float64))
        if len(v) == 0:
            return float("-inf")
        return float(v[min((len(v) * q) // 100, len(v) - 1)])

    # -- vector similarity -------------------------------------------------
    def vector_topk(self, col: str, query, k: int, m,
                    metric: str = "cosine"):
        """Exact filtered top-k over an embedding column: list of
        (docid, score) ranked score-desc with docid-asc tie-break.

        The score is the engine's contract — a balanced pairwise f32
        tree over the pow2-padded dim axis (cosine divides by the f32
        tree norms) — written here independently of the engine code.
        """
        mat = np.asarray(self.cols[col], dtype=np.float32)
        q = np.asarray(query, dtype=np.float32)
        dim_pad = 1
        while dim_pad < max(mat.shape[1], 1):
            dim_pad *= 2
        mp = np.zeros((len(mat), dim_pad), np.float32)
        mp[:, : mat.shape[1]] = mat
        qp = np.zeros(dim_pad, np.float32)
        qp[: len(q)] = q

        def tree(x):
            x = np.asarray(x, np.float32)
            while x.shape[-1] > 1:
                x = x[..., 0::2] + x[..., 1::2]
            return x[..., 0]

        scores = tree(mp * qp[None, :])
        if metric.lower() in ("cosine",):
            denom = np.sqrt(tree(mp * mp)).astype(np.float32) * \
                np.float32(np.sqrt(tree(qp * qp)))
            with np.errstate(divide="ignore", invalid="ignore"):
                scores = (scores / denom).astype(np.float32)
            scores[~(denom > 0)] = -np.inf
        docs = np.nonzero(m)[0]
        s = scores[docs]
        order = np.lexsort((docs, -s))[:k]
        return [(int(docs[i]), float(s[i])) for i in order]

    # -- group by ----------------------------------------------------------
    def group_by(self, gcols: List[str], m, agg):
        """agg: (name, col) → dict[group_tuple → final value]."""
        groups: Dict[tuple, np.ndarray] = {}
        idx = np.nonzero(m)[0]
        key_arrays = [self.cols[c] for c in gcols]
        by_key: Dict[tuple, list] = {}
        for i in idx:
            key = tuple(k[i] for k in key_arrays)
            by_key.setdefault(key, []).append(i)
        out = {}
        name, col = agg
        for key, rows in by_key.items():
            rm = np.zeros(self.n, dtype=bool)
            rm[rows] = True
            out[key] = getattr(self, name)(col, rm) if col else self.count(rm)
        return out
