"""Layered per-process instance configuration.

Parity: the commons-configuration properties layer — ServerConf,
ControllerConf, broker Configuration, constants in CommonConstants
(SURVEY.md §5.6a). Precedence: explicit overrides > environment
(PINOT_TPU_<KEY with dots as __>) > properties file > defaults.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

# defaults (parity: CommonConstants)
DEFAULTS: Dict[str, str] = {
    "pinot.server.query.executor.timeout": "15000",       # ms
    "pinot.server.query.scheduler.algorithm": "fcfs",
    "pinot.server.query.scheduler.workers": "4",
    "pinot.server.netty.port": "8098",
    "pinot.broker.timeout.ms": "15000",
    "pinot.broker.client.queryPort": "8099",
    "pinot.broker.routing.table.builder": "balanced",
    "pinot.controller.port": "9000",
    "pinot.controller.retention.frequencyInSeconds": "21600",
    "controller.realtime.segment.commit.timeoutSeconds": "120",
    "pinot.server.instance.dataDir": "",
    "pinot.minion.workers": "1",
}


def _env_key(key: str) -> str:
    return "PINOT_TPU_" + key.replace(".", "__").upper()


class InstanceConfig:
    """One process's configuration view."""

    def __init__(self, overrides: Optional[Dict[str, str]] = None,
                 properties_file: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
        self._file: Dict[str, str] = {}
        if properties_file and os.path.exists(properties_file):
            self._file = self._parse(properties_file)
        self._overrides = dict(overrides or {})
        self._env = os.environ if env is None else env

    @staticmethod
    def _parse(path: str) -> Dict[str, str]:
        out = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith(("#", "!")):
                    continue
                if "=" in line:
                    k, v = line.split("=", 1)
                    out[k.strip()] = v.strip()
        return out

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        if key in self._overrides:
            return self._overrides[key]
        ek = _env_key(key)
        if ek in self._env:
            return self._env[ek]
        if key in self._file:
            return self._file[key]
        return DEFAULTS.get(key, default)

    def get_int(self, key: str, default: Optional[int] = None
                ) -> Optional[int]:
        v = self.get(key, None)
        return int(v) if v is not None and v != "" else default

    def get_float(self, key: str, default: Optional[float] = None
                  ) -> Optional[float]:
        v = self.get(key, None)
        return float(v) if v is not None and v != "" else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, None)
        if v is None or v == "":
            return default
        return str(v).lower() in ("1", "true", "yes", "on")

    def subset(self, prefix: str) -> Dict[str, str]:
        """All resolved keys under a prefix (defaults + file + overrides)."""
        keys = set(DEFAULTS) | set(self._file) | set(self._overrides)
        return {k: self.get(k) for k in sorted(keys)
                if k.startswith(prefix)}
