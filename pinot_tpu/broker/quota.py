"""Per-table QPS quota with a sliding hit counter.

Parity: pinot-broker/.../queryquota/HelixExternalViewBasedQueryQuotaManager
+ HitCounter — per-table max QPS enforced over a rolling window, hits
bucketed per 100ms.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

BUCKETS = 10
BUCKET_MS = 100


class HitCounter:
    def __init__(self):
        self._times = [0] * BUCKETS
        self._counts = [0] * BUCKETS
        self._lock = threading.Lock()

    def hit(self, now_ms: Optional[int] = None) -> None:
        now_ms = int(time.time() * 1e3) if now_ms is None else now_ms
        idx = (now_ms // BUCKET_MS) % BUCKETS
        with self._lock:
            stamp = now_ms // BUCKET_MS
            if self._times[idx] != stamp:
                self._times[idx] = stamp
                self._counts[idx] = 0
            self._counts[idx] += 1

    def hits_in_window(self, now_ms: Optional[int] = None) -> int:
        now_ms = int(time.time() * 1e3) if now_ms is None else now_ms
        lo = now_ms // BUCKET_MS - BUCKETS + 1
        with self._lock:
            return sum(c for t, c in zip(self._times, self._counts)
                       if t >= lo)


class QueryQuotaManager:
    def __init__(self):
        self._quotas: Dict[str, float] = {}
        self._counters: Dict[str, HitCounter] = {}
        self._lock = threading.Lock()

    def set_qps_quota(self, table: str, max_qps: Optional[float]) -> None:
        with self._lock:
            if max_qps is None:
                self._quotas.pop(table, None)
                self._counters.pop(table, None)
            else:
                self._quotas[table] = max_qps
                self._counters.setdefault(table, HitCounter())

    def acquire(self, table: str) -> bool:
        """Record a hit; False when the table is over quota."""
        with self._lock:
            quota = self._quotas.get(table)
            counter = self._counters.get(table)
        if quota is None or counter is None:
            return True
        counter.hit()
        window_s = BUCKETS * BUCKET_MS / 1e3
        return counter.hits_in_window() <= quota * window_s
