"""Tiered segment residency: HBM ↔ host ↔ disk under a device budget.

Parity: the reference never dies when a table outgrows RAM —
PinotDataBuffer mmaps segments off-heap and lets the OS page cold data
(segment-spi/.../memory/PinotDataBuffer.java), so overload is a latency
problem, not a crash. This build's "native memory" is HBM, which has no
OS pager, so the manager rebuilds the tiering explicitly:

- **device** — column lanes resident in HBM (the PR 15 residency
  ledger attributes every byte); queries run the device kernels.
- **host** — device lanes released; queries execute through the
  ``host_exec`` numpy oracle on the retained host arrays.
- **disk** — host row payloads dropped too; the CRC-verified local
  artifact (PR 4) is the reload source. The first query pays a metered
  cold reload (``residencyColdHits``) with the PR 8 result cache as the
  shock absorber for repeats.

Admission is budgeted against the PROCESS-GLOBAL ledger total
(``obs/residency.LEDGER.total_bytes()``), not a private estimate, so
sharded stacks, join/window operands and exchange blocks all count.
Victims are chosen by (heat asc, bytes desc); heat is a half-life-
decayed per-segment access clock seeded from the per-table query-
processing stats (PR 5), so a cold table's bulk attach cannot evict a
hot table's working set.

Tier transitions are a staged swap: demotion verifies the fallback copy
(host arrays; for disk also the artifact), PUBLISHES the new tier so
fresh queries route off-device, drains in-flight query pins, and only
then releases lanes — no query ever reads a half-demoted lane.
Promotion uploads before publishing. The three armed crash points
(``residency.demote_staged`` / ``residency.pre_publish`` /
``residency.pre_release``) let the kill-restart suite stop the swap at
every stage, and tpulint's protocol tier extracts this file's
``demote_segment`` / ``promote_segment`` step order and model-checks
publisher × evictor × query × crash interleavings against
`no-read-of-released-lane`, `budget-conservation` and
`promoted-implies-artifact` (analysis/protocol.py, extract_residency).
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.metrics import (ServerGauge, ServerMeter,
                                      ServerQueryPhase)
from pinot_tpu.obs import profiler as obs_profiler
from pinot_tpu.obs.residency import LEDGER

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_DISK = "disk"
TIERS = (TIER_DEVICE, TIER_HOST, TIER_DISK)

#: env override for the device byte budget (config key
#: ``deviceBytesBudget`` on ServerInstance); unset → unbounded, which
#: preserves the pre-manager behavior exactly
BUDGET_ENV = "PINOT_TPU_DEVICE_BYTES_BUDGET"
#: optional host-RAM budget: when the host tier outgrows it, the
#: coldest host-tier segments continue to disk
HOST_BUDGET_ENV = "PINOT_TPU_HOST_BYTES_BUDGET"

#: heat decays with this half-life; an untouched segment loses half its
#: heat every interval, so "cold" is a property of recency, not age
HEAT_HALF_LIFE_S = 30.0
#: a non-device segment at or above this heat wants a promotion slot —
#: the promotion-backlog gauge (and the admission brownout watermark)
#: counts exactly these
PROMOTE_MIN_HEAT = 0.5
#: demotion waits at most this long for in-flight pins to drain before
#: skipping the victim (a wedged query must not wedge the evictor)
PIN_DRAIN_TIMEOUT_S = 30.0


class ResidencyError(RuntimeError):
    """A tier transition could not be performed safely (e.g. demote to
    disk without a reloadable artifact)."""


class _Entry:
    """Residency state for one tracked immutable segment."""

    __slots__ = ("table", "name", "seg", "seg_dir", "tier", "heat",
                 "last_access", "device_bytes", "host_bytes", "pins",
                 "epoch", "cond", "swap_lock", "disk_columns",
                 "cold_hits")

    def __init__(self, table: str, seg, seg_dir: Optional[str],
                 now: float, seed_heat: float):
        self.table = table
        self.name = seg.segment_name
        self.seg = seg
        self.seg_dir = seg_dir
        self.tier = TIER_DEVICE
        self.heat = seed_heat
        self.last_access = now
        self.device_bytes = int(seg.device_bytes_estimate())
        from pinot_tpu.segment.loader import segment_host_bytes
        self.host_bytes = int(segment_host_bytes(seg))
        self.pins = 0
        self.epoch = 0
        self.cond = threading.Condition()
        # serializes demote/promote on this entry; pin/unpin do NOT
        # take it (a drain-waiting evictor must not block unpinning)
        self.swap_lock = threading.Lock()
        self.disk_columns: Tuple[str, ...] = ()
        self.cold_hits = 0


class ResidencyManager:
    """Budgeted, heat-driven HBM residency for immutable segments.

    One instance per server process (HBM is a per-process resource —
    the module-global ``MANAGER`` mirrors the ledger's process-global
    convention); ``ServerInstance`` configures the budget and wires the
    metrics registry, removal listeners and release hooks.
    """

    def __init__(self, budget_bytes: Optional[int] = None,
                 host_budget_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_bytes = budget_bytes
        self.host_budget_bytes = host_budget_bytes
        self._clock = clock
        self._lock = threading.Lock()
        # segment name → entry; bounded by the segments this server
        # hosts: untrack (the data-manager removal listener) pops
        self._entries: Dict[str, _Entry] = {}
        self._metrics = None
        # called with the segment name whenever its device lanes are
        # released, so derived caches (sharded stacks) evict promptly
        self._release_hooks: List[Callable[[str], None]] = []
        # called under budget pressure BEFORE victim demotion — derived
        # duplicated HBM (stack caches) is the cheapest eviction
        self._pressure_hooks: List[Callable[[], None]] = []

    # -- configuration ------------------------------------------------------
    def configure(self, budget_bytes: Optional[int],
                  host_budget_bytes: Optional[int] = None) -> None:
        with self._lock:
            self.budget_bytes = budget_bytes
            self.host_budget_bytes = host_budget_bytes

    def bind_metrics(self, metrics) -> None:
        """Wire gauges onto a component registry: per-tier
        deviceBytesResident twins (`|tier:<t>` suffix → `tier` label)
        and the promotion backlog the admission brownout watches."""
        with self._lock:
            self._metrics = metrics
        for tier in TIERS:
            metrics.gauge(ServerGauge.RESIDENCY_TIER_BYTES,
                          table=f"|tier:{tier}").set_callable(
                lambda t=tier: self.tier_bytes(t))
        metrics.gauge(ServerGauge.RESIDENCY_PROMOTION_BACKLOG) \
            .set_callable(self.promotion_backlog)
        LEDGER.set_entry_annotator(self._annotate_entry)

    def add_release_hook(self, fn: Callable[[str], None]) -> None:
        self._release_hooks.append(fn)

    def add_pressure_hook(self, fn: Callable[[], None]) -> None:
        self._pressure_hooks.append(fn)

    # -- tracking -----------------------------------------------------------
    def track(self, table: str, seg, *,
              seg_dir: Optional[str] = None) -> str:
        """Register a segment under residency management (attach path).
        Admission is decided HERE: within budget the segment enters
        device-tier (warm uploads proceed); over budget it enters
        host-tier directly — a cold table's bulk reload cannot evict a
        hot table's working set, because eviction only claims victims
        strictly colder than the segment asking."""
        now = self._clock()
        entry = _Entry(table, seg, seg_dir, now,
                       self._seed_heat(table))
        with self._lock:
            self._entries[entry.name] = entry
        if not self._admit_device(entry):
            entry.tier = TIER_HOST
        return entry.name

    def untrack(self, segment_name: str) -> None:
        """Removal-listener hook: the data manager owns destruction;
        the manager only forgets (and stops gauging) the segment."""
        with self._lock:
            self._entries.pop(segment_name, None)

    def tracked(self, segment_name: str) -> Optional[str]:
        entry = self._entries.get(segment_name)
        return entry.tier if entry is not None else None

    def warm_device(self, segment_name: str, columns=None) -> bool:
        """Budget-routed eager warm-up: uploads a tracked segment's
        lanes only while it holds device tier (the loader's raw
        ``seg.warm_device()`` bypasses admission — serving paths go
        through here). Returns whether the warm actually ran."""
        entry = self._entries.get(segment_name)
        if entry is None or entry.tier != TIER_DEVICE:
            return False
        entry.seg.warm_device(columns)
        return True

    # -- heat ---------------------------------------------------------------
    def _seed_heat(self, table: str) -> float:
        """New segments of query-hot tables start warm (PR 5 per-table
        queryProcessing stats feed the seed) so attach ordering does
        not decide who gets evicted first."""
        base = 1.0
        if self._metrics is not None:
            timer = self._metrics.peek_timer(
                ServerQueryPhase.QUERY_PROCESSING, table=table)
            if timer is not None and timer.count:
                base += math.log2(1.0 + timer.count)
        return base

    def _heat(self, entry: _Entry, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        dt = max(0.0, now - entry.last_access)
        return entry.heat * 0.5 ** (dt / HEAT_HALF_LIFE_S)

    def _touch(self, entry: _Entry) -> None:
        now = self._clock()
        entry.heat = self._heat(entry, now) + 1.0
        entry.last_access = now

    # -- query-path hooks ---------------------------------------------------
    def device_allowed(self, seg) -> bool:
        """Per-segment execution gate: untracked segments keep the
        default device path; tracked segments run device kernels only
        while device-tier (host/disk serve through host_exec)."""
        entry = self._entries.get(getattr(seg, "segment_name", None))
        return entry is None or entry.tier == TIER_DEVICE

    def begin_query(self, segments: Sequence) -> List[Tuple[_Entry, int]]:
        """Per-query entry: bump heat, reload disk-tier segments
        (metered cold hits), promote hot off-device segments when the
        budget admits them, and pin each tracked segment's lane epoch
        so a concurrent demotion cannot release lanes mid-read. The
        returned token MUST be passed to end_query (try/finally)."""
        entries = []
        for seg in segments:
            entry = self._entries.get(getattr(seg, "segment_name", None))
            if entry is not None and entry.seg is seg:
                entries.append(entry)
        # pin strictly BEFORE tier work: victim scans skip pinned
        # entries, so once our pins are up no eviction we trigger below
        # (and no concurrent one) can release a lane this query reads.
        # Promotion/reload never drain pins, so holding our own pins
        # here cannot self-deadlock
        pinned: List[Tuple[_Entry, int]] = []
        for entry in entries:
            with entry.cond:
                entry.pins += 1
                pinned.append((entry, entry.epoch))
        # the ledger counts HBM the manager did not allocate (join/
        # window/exchange scratch, realtime snapshots); when THAT
        # pushes the total over budget, shed the coldest unpinned
        # segments — external pressure degrades residency, it never
        # breaks the budget invariant
        if self.budget_bytes is not None and \
                LEDGER.total_bytes() > self.budget_bytes:
            self._evict_for(0, float("inf"))
        for entry in entries:
            self._touch(entry)
            if entry.tier == TIER_DISK:
                self.ensure_host(entry.name)
            if entry.tier != TIER_DEVICE and \
                    self._heat(entry) >= PROMOTE_MIN_HEAT:
                self.promote_segment(entry.name)
        return pinned

    def end_query(self, token: List[Tuple[_Entry, int]]) -> None:
        for entry, _epoch in token:
            with entry.cond:
                entry.pins -= 1
                entry.cond.notify_all()

    def mutable_device_allowed(self, _mseg) -> bool:
        """Gate for realtime frozen-snapshot uploads: under budget
        pressure the consuming segment serves host-side instead of
        freezing a new device snapshot."""
        if self.budget_bytes is None:
            return True
        return LEDGER.total_bytes() < self.budget_bytes

    # -- admission / eviction ----------------------------------------------
    def _admit_device(self, entry: _Entry) -> bool:
        """May `entry` occupy HBM? Judged against the LEDGER total (the
        ground truth that includes stacks/join/window/exchange bytes),
        evicting strictly-colder victims first when over budget."""
        if self.budget_bytes is None:
            return True
        need = entry.device_bytes
        if LEDGER.total_bytes() + need <= self.budget_bytes:
            return True
        self._evict_for(need, self._heat(entry))
        return LEDGER.total_bytes() + need <= self.budget_bytes

    def _evict_for(self, need: int, asking_heat: float) -> None:
        """Free HBM for `need` bytes: derived caches first (pressure
        hooks), then device-tier victims strictly colder than the
        asking segment, ordered (heat asc, bytes desc)."""
        for hook in self._pressure_hooks:
            hook()
        if LEDGER.total_bytes() + need <= self.budget_bytes:
            return
        now = self._clock()
        with self._lock:
            # pinned entries are under active read — poor victims; skip
            # them rather than stall the asker on their drain (a racing
            # pin after this check still drains in demote_segment).
            # Mid-swap entries (locked swap_lock) are skipped too: one
            # of them may be the ASKER whose promotion is driving this
            # eviction, and its lock is not reentrant
            victims = [e for e in self._entries.values()
                       if e.tier == TIER_DEVICE and e.pins == 0 and
                       not e.swap_lock.locked() and
                       self._heat(e, now) < asking_heat]
        victims.sort(key=lambda e: (self._heat(e, now),
                                    -e.device_bytes, e.name))
        for victim in victims:
            if LEDGER.total_bytes() + need <= self.budget_bytes:
                return
            try:
                self.demote_segment(victim.name, TIER_HOST)
            except ResidencyError:
                # drain timeout / stage failure: eviction degrades (the
                # asker stays off-device), it never fails the query
                continue
        self._enforce_host_budget()

    def _enforce_host_budget(self) -> None:
        """Host tier overflow continues to disk (coldest first) when a
        host budget is configured — the second stage of degradation."""
        if self.host_budget_bytes is None:
            return
        now = self._clock()
        with self._lock:
            # a mid-swap host-tier entry may be the asker promoting out
            # of this tier right now (it holds its own swap_lock, which
            # is not reentrant) — never pick it as a victim; pinned
            # entries are under active read, skip them likewise
            hosted = [e for e in self._entries.values()
                      if e.tier == TIER_HOST and e.pins == 0 and
                      not e.swap_lock.locked()]
        hosted.sort(key=lambda e: (self._heat(e, now),
                                   -e.host_bytes, e.name))
        held = sum(e.host_bytes for e in hosted)
        for victim in hosted:
            if held <= self.host_budget_bytes:
                return
            try:
                if self.demote_segment(victim.name, TIER_DISK):
                    held -= victim.host_bytes
            except ResidencyError:
                continue

    # -- staged tier transitions -------------------------------------------
    #
    # The step order below is EXTRACTED by analysis/protocol.py
    # (extract_residency) and model-checked; renaming the helper calls
    # or reordering the publish/drain/release sequence is a protocol
    # change and shows up as a protocol-model.json diff.

    def demote_segment(self, key: str, tier: str) -> bool:
        """Staged demotion (device→host, or any→disk). Publishes the
        fallback BEFORE releasing the device lanes: stage/verify the
        host copy (and, for disk, the reload artifact), publish the
        tier so new queries route off-device, drain in-flight query
        pins, then release."""
        assert tier in (TIER_HOST, TIER_DISK), tier
        entry = self._entries.get(key)
        if entry is None:
            return False
        with entry.swap_lock:
            if entry.tier == tier or \
                    (tier == TIER_HOST and entry.tier == TIER_DISK):
                return False
            self._stage_host(entry)
            crash_points.hit("residency.demote_staged")
            if tier == TIER_DISK:
                self._require_artifact(entry)
            crash_points.hit("residency.pre_publish")
            entry.tier = tier
            self._await_unpinned(entry)
            crash_points.hit("residency.pre_release")
            self._release_lanes(entry, tier)
            entry.epoch += 1
        if self._metrics is not None:
            self._metrics.meter(ServerMeter.RESIDENCY_DEMOTIONS,
                                table=tier).mark()
        return True

    def promote_segment(self, key: str) -> bool:
        """Staged promotion back to HBM: reload from the artifact when
        disk-tier, upload the lanes, and only then publish device-tier
        — a query routed mid-promotion still takes the host path
        against intact host arrays."""
        entry = self._entries.get(key)
        if entry is None:
            return False
        with entry.swap_lock:
            if entry.tier == TIER_DEVICE:
                return False
            if not self._admit_device(entry):
                return False
            if entry.tier == TIER_DISK:
                self._reload_from_artifact(entry)
            entry.seg.warm_device()
            entry.tier = TIER_DEVICE
            entry.epoch += 1
        if self._metrics is not None:
            self._metrics.meter(ServerMeter.RESIDENCY_PROMOTIONS,
                                table=entry.table).mark()
        obs_profiler.count_path("residencyPromote")
        return True

    def ensure_host(self, key: str) -> None:
        """Promote a disk-tier segment to host (the cold-hit path):
        reload+rebind BEFORE publishing host-tier, so a racing query
        never sees a half-rebound segment."""
        entry = self._entries.get(key)
        if entry is None:
            return
        with entry.swap_lock:
            if entry.tier != TIER_DISK:
                return
            self._reload_from_artifact(entry)
            entry.tier = TIER_HOST
            entry.epoch += 1

    # -- transition steps ---------------------------------------------------
    def _stage_host(self, entry: _Entry) -> None:
        """Verify the host copy every fallback path needs is present
        (device lanes are views OVER host arrays, so device-tier
        implies host copies — this guards the disk→host edge case and
        future refactors, loudly)."""
        if entry.tier == TIER_DISK:
            raise ResidencyError(
                f"segment '{entry.name}' is disk-tier; promote before "
                "demoting again")
        seg = entry.seg
        for name in seg.column_names:
            ds = seg.data_source(name)
            if ds.dict_ids is None and ds._raw_values is None and \
                    ds.raw_chunks is None and ds.mv_dict_ids is None \
                    and ds.vec_values is None and ds.dictionary is None:
                raise ResidencyError(
                    f"segment '{entry.name}' column '{name}' has no "
                    "host copy to publish")

    def _require_artifact(self, entry: _Entry) -> None:
        """A disk-tier segment must stay reloadable: verify the
        artifact parses NOW (promoted-implies-artifact, the invariant
        the model checker holds crash-at-every-step) and record which
        columns it can restore — schema-synthesized default columns and
        virtual columns keep their (tiny) host arrays."""
        if entry.seg_dir is None:
            raise ResidencyError(
                f"segment '{entry.name}' has no artifact directory; "
                "cannot demote to disk")
        from pinot_tpu.segment.metadata import SegmentMetadata
        try:
            meta = SegmentMetadata.load(entry.seg_dir)
        except Exception as exc:
            raise ResidencyError(
                f"segment '{entry.name}' artifact at "
                f"'{entry.seg_dir}' is not reloadable: {exc}") from exc
        entry.disk_columns = tuple(
            name for name in entry.seg.column_names
            if name in meta.columns)

    def _await_unpinned(self, entry: _Entry) -> None:
        """Drain in-flight query pins before releasing lanes — the
        runtime half of no-read-of-released-lane. Times out (skipping
        nothing: the release still happens only for an unpinned entry
        or after the deadline logs the wedge) rather than wedging the
        evictor forever behind a stuck query."""
        deadline = time.monotonic() + PIN_DRAIN_TIMEOUT_S
        with entry.cond:
            while entry.pins > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ResidencyError(
                        f"segment '{entry.name}' pins did not drain in "
                        f"{PIN_DRAIN_TIMEOUT_S}s; aborting demotion")
                entry.cond.wait(timeout=remaining)

    def _release_lanes(self, entry: _Entry, tier: str) -> None:
        """Release the device lanes (and, for disk, the host row
        payloads the verified artifact can restore), then poke release
        hooks so derived caches (sharded stacks) drop promptly."""
        entry.seg.release_device_lanes()
        if tier == TIER_DISK:
            entry.seg.release_host_lanes(entry.disk_columns)
        for hook in self._release_hooks:
            hook(entry.name)

    def _reload_from_artifact(self, entry: _Entry) -> None:
        """Disk→host: load a fresh copy of the artifact and rebind its
        host payloads into the LIVE segment object (identity preserved
        for the data manager / caches). Metered as a cold hit and
        profiler-attributed so PROFILE artifacts name the cost."""
        from pinot_tpu.segment.loader import ImmutableSegmentLoader
        fresh = ImmutableSegmentLoader.load(entry.seg_dir)
        entry.seg.rebind_host_lanes(fresh)
        entry.cold_hits += 1
        if self._metrics is not None:
            self._metrics.meter(ServerMeter.RESIDENCY_COLD_HITS,
                                table=entry.table).mark()
        obs_profiler.count_path("residencyCold")

    # -- observability ------------------------------------------------------
    def tier_bytes(self, tier: str) -> int:
        """Estimated bytes per tier: device reads the entries' device
        charge, host/disk read the retained host footprint."""
        with self._lock:
            entries = [e for e in self._entries.values()
                       if e.tier == tier]
        if tier == TIER_DEVICE:
            return sum(e.device_bytes for e in entries)
        if tier == TIER_HOST:
            return sum(e.host_bytes for e in entries)
        return sum(e.host_bytes for e in entries)

    def promotion_backlog(self) -> int:
        """Segments hot enough for HBM but still off-device — the
        admission controller brownouts above a watermark of these (a
        reload storm means queries already pay cold/host penalties;
        shedding load early beats timing out late)."""
        now = self._clock()
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e.tier != TIER_DEVICE and
                       self._heat(e, now) >= PROMOTE_MIN_HEAT)

    def snapshot(self) -> dict:
        """JSON-able manager view (joined into /debug/residency)."""
        now = self._clock()
        with self._lock:
            entries = list(self._entries.values())
        tiers = {t: {"segments": 0, "bytes": 0} for t in TIERS}
        segs = []
        for e in sorted(entries, key=lambda e: e.name):
            tiers[e.tier]["segments"] += 1
            tiers[e.tier]["bytes"] += (e.device_bytes
                                       if e.tier == TIER_DEVICE
                                       else e.host_bytes)
            segs.append({"segment": e.name, "table": e.table,
                         "tier": e.tier,
                         "heat": round(self._heat(e, now), 3),
                         "deviceBytes": e.device_bytes,
                         "hostBytes": e.host_bytes,
                         "pins": e.pins, "epoch": e.epoch,
                         "coldHits": e.cold_hits})
        return {"deviceBytesBudget": self.budget_bytes,
                "ledgerTotalBytes": LEDGER.total_bytes(),
                "promotionBacklog": self.promotion_backlog(),
                "tiers": tiers, "segments": segs}

    def _annotate_entry(self, entry: dict) -> None:
        """Snapshot-entry annotator installed on the ledger: stamps
        `tier` and last-access `heat` onto /debug/residency's largest-
        entries rows for segments this manager tracks."""
        tracked = self._entries.get(entry.get("segment", ""))
        if tracked is not None:
            entry["tier"] = tracked.tier
            entry["heat"] = round(self._heat(tracked), 3)

    def shutdown(self) -> None:
        if LEDGER._entry_annotator is self._annotate_entry:
            LEDGER.set_entry_annotator(None)
        with self._lock:
            self._entries.clear()


def budget_from_env() -> Optional[int]:
    raw = os.environ.get(BUDGET_ENV, "").strip()
    return int(raw) if raw else None


def host_budget_from_env() -> Optional[int]:
    raw = os.environ.get(HOST_BUDGET_ENV, "").strip()
    return int(raw) if raw else None


#: the process-global manager (HBM is a per-process resource, like the
#: ledger); ServerInstance configures budget/metrics at boot
MANAGER = ResidencyManager(budget_from_env(), host_budget_from_env())
