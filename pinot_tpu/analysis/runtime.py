"""Runtime complement to the static host-sync rule.

tpulint proves what it can from the AST; this guard catches the rest
at runtime. With ``PINOT_TPU_DEBUG_TRANSFERS=1`` every per-segment
execution runs under ``jax.transfer_guard_device_to_host("disallow")``:
the explicit, batched ``jax.device_get`` per combine still works
(explicit transfers are always allowed), while any silent device→host
pull — a stray ``.item()``, ``np.asarray`` on a device array, printing
a device value — raises at the offending call site instead of shipping
as a per-query stall. Set the env var to ``log`` to trace instead of
raise. Off (the default) this is a zero-cost nullcontext.
"""
from __future__ import annotations

import contextlib
import os

ENV_VAR = "PINOT_TPU_DEBUG_TRANSFERS"


_OFF = ("", "0", "false", "no", "off")
_ON = ("1", "true", "yes", "on")
_MODES = ("allow", "log", "disallow")


def debug_transfer_guard():
    """Context manager guarding implicit device→host transfers."""
    mode = os.environ.get(ENV_VAR, "").lower()
    if mode in _OFF:
        return contextlib.nullcontext()
    if mode in _ON:
        mode = "disallow"
    elif mode not in _MODES:
        raise ValueError(
            f"{ENV_VAR}={mode!r}: expected one of "
            f"{_OFF + _ON + _MODES}")
    import jax
    guard = getattr(jax, "transfer_guard_device_to_host", None)
    if guard is None:   # very old jax: fall back to the global guard
        guard = jax.transfer_guard
    return guard(mode)
