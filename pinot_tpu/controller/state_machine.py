"""Cluster state-machine engine: ideal state → transitions → external view.

Parity: the Helix core loop as Pinot uses it (docs/architecture.rst:35-120):
the controller writes IdealStates (table = resource, segment = partition);
participants (servers) receive state transitions
(SegmentOnlineOfflineStateModelFactory.java:81-156 —
OFFLINE→ONLINE loads a segment, ONLINE→OFFLINE unloads, →DROPPED deletes,
OFFLINE→CONSUMING starts a realtime consumer); current states compose into
ExternalViews that spectators (brokers) watch for routing.

Store layout:
  /IDEALSTATES/<table>              {"segments": {seg: {instance: state}}}
  /CURRENTSTATES/<instance>/<table> {"segments": {seg: state}}
  /EXTERNALVIEW/<table>             {"segments": {seg: {instance: state}}}
  /LIVEINSTANCES/<instance>         {"tags": [...]}
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from pinot_tpu.common.cluster_state import (CONSUMING, ERROR, OFFLINE,
                                            ONLINE, TableView)
from pinot_tpu.controller.property_store import PropertyStore

log = logging.getLogger(__name__)

DROPPED = "DROPPED"

IDEAL = "/IDEALSTATES"
CURRENT = "/CURRENTSTATES"
VIEW = "/EXTERNALVIEW"
LIVE = "/LIVEINSTANCES"


class StateModel:
    """Participant-side transition handlers (segment lifecycle).

    Parity: SegmentOnlineOfflineStateModelFactory's state model.
    """

    def on_become_online(self, table: str, segment: str) -> None:
        pass

    def on_become_consuming(self, table: str, segment: str) -> None:
        pass

    def on_become_offline(self, table: str, segment: str) -> None:
        pass

    def on_become_dropped(self, table: str, segment: str) -> None:
        pass


def apply_transitions(model: StateModel, table: str, inst: str,
                      wanted: Dict[str, str],
                      current: Dict[str, str]) -> bool:
    """Drive `model` from `current` toward `wanted`; mutate `current`.

    Shared by the in-process coordinator and the remote ParticipantAgent
    (server/agent.py) — the transition semantics
    (SegmentOnlineOfflineStateModelFactory parity, ERROR on failure,
    offline+drop on unassignment) must be identical in both deployments.
    Returns whether `current` changed.
    """
    changed = False
    for seg, target in wanted.items():
        state = current.get(seg, OFFLINE)
        if state == target:
            continue
        try:
            if target == ONLINE:
                model.on_become_online(table, seg)
            elif target == CONSUMING:
                model.on_become_consuming(table, seg)
            elif target == OFFLINE:
                model.on_become_offline(table, seg)
            elif target == DROPPED:
                if state in (ONLINE, CONSUMING):
                    model.on_become_offline(table, seg)
                model.on_become_dropped(table, seg)
            current[seg] = target
        except Exception:  # noqa: BLE001 — transition failure => ERROR
            log.exception("transition %s -> %s failed for %s/%s on %s",
                          state, target, table, seg, inst)
            current[seg] = ERROR
        changed = True
    # segments no longer assigned to this instance: offline + drop
    for seg in [s for s in current if s not in wanted]:
        if current[seg] in (ONLINE, CONSUMING):
            try:
                model.on_become_offline(table, seg)
                model.on_become_dropped(table, seg)
            except Exception:  # noqa: BLE001
                log.exception("unassign failed for %s/%s", table, seg)
        del current[seg]
        changed = True
    return changed


def compose_view(store: PropertyStore, table: str) -> None:
    """Recompute /EXTERNALVIEW/<table> from live instances' current states.

    Writes only on change, so redundant composers (the in-process
    coordinator and a ViewComposer over the same store) don't generate
    watch noise.  The read-compute-write cycle is serialized per store
    (compose_lock): without it, a composer thread that read stale
    current states could overwrite a newer view last and leave routing
    wrong until the next current-state event.
    """
    lock = getattr(store, "compose_lock", None)
    if lock is None:
        # every PropertyStore implementation must carry the lock; a
        # silent per-call fallback lock would disable the serialization
        # this docstring promises (round-2 advisor finding)
        raise TypeError(
            f"{type(store).__name__} has no compose_lock; view "
            "composition requires per-store serialization")
    with lock:
        view: Dict[str, Dict[str, str]] = {}
        for inst in store.children(LIVE):
            current = (store.get(f"{CURRENT}/{inst}/{table}") or {}
                       ).get("segments", {})
            for seg, state in current.items():
                if state != DROPPED:
                    view.setdefault(seg, {})[inst] = state
        new = {"segments": view}
        if store.get(f"{VIEW}/{table}") != new:
            store.set(f"{VIEW}/{table}", new)


class ViewComposer:
    """Controller-side external-view maintenance for remote participants.

    Parity: the Helix controller recomputing ExternalViews from
    CurrentStates + LiveInstances.  The in-process coordinator composes
    views synchronously after driving its own participants; remote
    participants (server/agent.py) write current states over the store,
    and this composer reacts to those writes — including the ephemeral
    current-state/live-instance removal when a server dies.
    """

    def __init__(self, store: PropertyStore, gate=None):
        """`gate`: optional () -> bool — with multiple controllers over
        one store, only the LEAD controller's composer runs (parity:
        one Helix controller computing external views); a standby's
        composer stays quiet until its gate opens, then catches up via
        recompose_all (wired to the leadership listener)."""
        self.store = store
        self.gate = gate
        self._watcher = self._on_change
        store.watch(CURRENT + "/", self._watcher)
        store.watch(LIVE + "/", self._watcher)

    def _on_change(self, path: str, record: Optional[dict]) -> None:
        if self.gate is not None and not self.gate():
            return
        if path.startswith(CURRENT + "/"):
            parts = path[len(CURRENT) + 1:].split("/", 1)
            if len(parts) == 2:
                compose_view(self.store, parts[1])
            return
        # live-instance change: membership affects every table's view
        self.recompose_all()

    def recompose_all(self) -> None:
        """Recompute every table's view — the catch-up a just-promoted
        standby runs for the events its gate suppressed."""
        for table in self.store.children(IDEAL):
            compose_view(self.store, table)

    def close(self) -> None:
        self.store.unwatch(self._watcher)


class ClusterCoordinator:
    """Drives participants toward ideal state; composes external views."""

    def __init__(self, store: Optional[PropertyStore] = None):
        self.store = store or PropertyStore()
        self._participants: Dict[str, StateModel] = {}
        self._lock = threading.RLock()

    # -- membership --------------------------------------------------------
    def register_participant(self, instance_id: str, model: StateModel,
                             tags: Optional[List[str]] = None) -> None:
        with self._lock:
            self._participants[instance_id] = model
            self.store.set(f"{LIVE}/{instance_id}",
                           {"tags": list(tags or ["DefaultTenant"])})
        self._reconcile_all()

    def deregister_participant(self, instance_id: str) -> None:
        """Instance death (ephemeral node loss): drop from views.

        Current-state records die with the instance (they described a
        process that no longer exists) — otherwise a restarted instance
        under the same id would be believed to still host its segments and
        never receive load transitions."""
        with self._lock:
            self._participants.pop(instance_id, None)
            self.store.remove(f"{LIVE}/{instance_id}")
            for path in self.store.list_paths(f"{CURRENT}/{instance_id}/"):
                self.store.remove(path)
        for table in self.tables():
            self._recompute_view(table)

    def live_instances(self, tag: Optional[str] = None) -> List[str]:
        from pinot_tpu.controller.tenants import live_instances_with_tag
        return live_instances_with_tag(self.store, tag)

    # -- ideal state -------------------------------------------------------
    def set_ideal_state(self, table: str,
                        segments: Dict[str, Dict[str, str]]) -> None:
        self.store.set(f"{IDEAL}/{table}", {"segments": segments})
        self._reconcile(table)

    def update_ideal_state(self, table: str, fn) -> Dict:
        rec = self.store.update(
            f"{IDEAL}/{table}",
            lambda old: {"segments": fn(dict((old or {}).get("segments",
                                                            {})))})
        self._reconcile(table)
        return rec["segments"]

    def ideal_state(self, table: str) -> Dict[str, Dict[str, str]]:
        rec = self.store.get(f"{IDEAL}/{table}") or {}
        return rec.get("segments", {})

    def drop_table(self, table: str) -> None:
        self.update_ideal_state(
            table, lambda segs: {s: {i: DROPPED for i in m}
                                 for s, m in segs.items()})
        self.store.remove(f"{IDEAL}/{table}")
        self.store.remove(f"{VIEW}/{table}")
        for inst in self.store.children(CURRENT):
            self.store.remove(f"{CURRENT}/{inst}/{table}")

    def tables(self) -> List[str]:
        return self.store.children(IDEAL)

    # -- views -------------------------------------------------------------
    def external_view(self, table: str) -> TableView:
        rec = self.store.get(f"{VIEW}/{table}") or {}
        return TableView(table, rec.get("segments", {}))

    def watch_external_views(self, callback: Callable[[TableView], None]
                             ) -> None:
        def on_change(path: str, rec: Optional[dict]) -> None:
            table = path[len(VIEW) + 1:]
            callback(TableView(table, (rec or {}).get("segments", {})))

        self.store.watch(VIEW + "/", on_change)

    # -- reconciliation ----------------------------------------------------
    def _reconcile_all(self) -> None:
        for table in self.tables():
            self._reconcile(table)

    def _reconcile(self, table: str) -> None:
        with self._lock:
            ideal = self.ideal_state(table)
            for inst, model in list(self._participants.items()):
                self._reconcile_instance(table, inst, model, ideal)
            self._recompute_view(table)

    def _reconcile_instance(self, table: str, inst: str, model: StateModel,
                            ideal: Dict[str, Dict[str, str]]) -> None:
        path = f"{CURRENT}/{inst}/{table}"
        current = (self.store.get(path) or {}).get("segments", {})
        wanted = {seg: states[inst] for seg, states in ideal.items()
                  if inst in states}
        if apply_transitions(model, table, inst, wanted, current):
            self.store.set(path, {"segments": current})

    def _recompute_view(self, table: str) -> None:
        compose_view(self.store, table)
