"""Server query executor: acquire → prune → execute → DataTable.

Parity: pinot-core/.../query/executor/ServerQueryExecutorV1Impl.java:100-267
— refcounted segment acquisition, pruning, per-segment execution (device
kernels, with the mesh-sharded combine when segments are homogeneous),
timeout accounting, execution-stats metadata on the DataTable.
"""
from __future__ import annotations

import json
import time
from typing import List, Optional

from pinot_tpu.common.datatable import (DataTable, MISSING_SEGMENTS_KEY,
                                        SEGMENT_MISSING_EXC_PREFIX)
from pinot_tpu.common.metrics import (MetricsRegistry, ServerMeter,
                                      ServerQueryPhase)
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.obs import profiler as obs_profiler
from pinot_tpu.obs.profiler import QueryProfile
from pinot_tpu.obs.tracing import TraceContext, make_trace_context
from pinot_tpu.query.blocks import IntermediateResultsBlock
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.server.data_manager import InstanceDataManager


class InstanceQueryExecutor:
    """Executes InstanceRequests against this server's tables."""

    def __init__(self, data_manager: InstanceDataManager,
                 mesh=None, use_device: bool = True,
                 default_timeout_ms: float = 15_000.0,
                 metrics: Optional[MetricsRegistry] = None,
                 segment_executor=None, residency=None):
        self.data_manager = data_manager
        # segment_executor: the scheduler's query-worker pool — per-
        # segment plans fan out on it (CombineOperator parity); None
        # keeps the sequential per-segment loop
        self.executor = ServerQueryExecutor(
            use_device=use_device, segment_executor=segment_executor)
        # residency manager: heat accounting, tier routing (host/disk-
        # tier segments execute through host_exec), query pins so a
        # concurrent demotion never releases a lane mid-read. Defaults
        # to the process-global manager, which is unbudgeted (= the
        # pre-manager behavior) until someone configures a budget.
        from pinot_tpu.server import residency_manager
        self.residency = residency if residency is not None \
            else residency_manager.MANAGER
        self.executor.device_gate = self.residency.device_allowed
        self.executor.mutable_gate = self.residency.mutable_device_allowed
        self.sharded = None
        if mesh is not None:
            from pinot_tpu.parallel.sharded import ShardedQueryExecutor
            self.sharded = ShardedQueryExecutor(mesh=mesh)
            data_manager.add_removal_listener(self.sharded.evict_segment)
        self.default_timeout_ms = default_timeout_ms
        self.metrics = metrics or MetricsRegistry("server")

    def execute(self, request: InstanceRequest,
                scheduler_wait_ms: float = 0.0,
                deadline: Optional[float] = None,
                deser_ms: float = 0.0) -> DataTable:
        """`deadline`: absolute time.monotonic() instant from the
        broker-propagated budget; expired work is dropped or truncated
        instead of computing answers nobody will read."""
        t_start = time.perf_counter()
        self.metrics.meter(ServerMeter.QUERIES).mark()
        vec = request.query.vector
        if vec is not None and int(getattr(vec, "nprobe", 0) or 0) > 0:
            self.metrics.meter(ServerMeter.IVF_NPROBE_QUERIES).mark()
        self.metrics.timer(ServerQueryPhase.SCHEDULER_WAIT).update(
            scheduler_wait_ms)
        if deadline is not None and time.monotonic() >= deadline:
            self.metrics.meter(ServerMeter.DEADLINE_EXPIRED_QUERIES).mark()
            dt = DataTable()
            dt.metadata["requestId"] = str(request.request_id)
            dt.exceptions.append(
                "DeadlineExceededError: query budget expired before "
                "execution started; dropped without executing")
            return dt
        # the server's span subtree roots under the broker's dispatch
        # span (parent_span_id) so the reduce step can merge one
        # cross-process trace tree with correct parent links
        trace = make_trace_context(request.enable_trace,
                                   trace_id=request.trace_id,
                                   parent_span_id=request.parent_span_id,
                                   root_name="server")
        if deser_ms:
            trace.record(ServerQueryPhase.REQUEST_DESERIALIZATION,
                         deser_ms)
        trace.record(ServerQueryPhase.SCHEDULER_WAIT, scheduler_wait_ms)
        query = request.query
        if query.windows and request.exchange_sources is not None:
            # window stage 2 (coordinator): all data arrives through the
            # exchange — no local segment acquisition at all
            return self._execute_window_stage(request, deadline)
        timeout_ms = query.query_options.timeout_ms or self.default_timeout_ms
        if request.deadline_budget_ms is not None:
            # the broker's remaining budget caps the server-side timeout
            timeout_ms = min(timeout_ms, request.deadline_budget_ms)
        tdm = self.data_manager.table(query.table_name)
        if tdm is None:
            dt = DataTable()
            dt.exceptions.append(
                f"TableDoesNotExistError: {query.table_name}")
            return dt

        profile = QueryProfile(query.table_name)
        acquired, missing = tdm.acquire_segments(request.search_segments)
        # residency entry: bump heat, reload disk-tier segments, pin
        # lane epochs so demotion drains us before releasing (paired
        # end_query in the finally below)
        residency_token = self.residency.begin_query(
            [s.segment for s in acquired])
        try:
            segments = [s.segment for s in acquired]
            # capture result-cache key states BEFORE execution: an
            # upsert validDocIds bump mid-query would otherwise key
            # pre-invalidation rows under the POST-bump version — a
            # persistent lie every later identical query would hit.
            # Keying under the pre-bump version is safe: versions only
            # grow, so a probe can never construct the raced key again
            # (the entry is at worst dead weight until evicted).
            from pinot_tpu.server.result_cache import segment_cache_states
            pre_states = None if missing else segment_cache_states(segments)
            from pinot_tpu.query.plan import preprocess_request
            # FASTHLL derived rewrite happens HERE, once, before the
            # per-segment fan-out: this request instance is private to
            # this server query (deserialized per dispatch), and the
            # DataTable columns below must carry the rewritten names
            query = preprocess_request(segments, query)
            if query.join is not None:
                # join stage 2: fetch the (partition-filtered) dim
                # blocks and attach the probe context; StageCompileError
                # → typed reply, never a generic execution fault
                from pinot_tpu.query.stages.errors import (
                    StageCompileError, stage_error_datatable)
                try:
                    query = self._attach_join_context(request, query,
                                                      segments, deadline)
                except StageCompileError as e:
                    return stage_error_datatable(
                        request.request_id, "joinCompile", str(e))
                try:
                    with obs_profiler.active(profile, trace):
                        block = self._execute_segments(
                            query, segments, trace, deadline=deadline)
                except StageCompileError as e:
                    # raised from per-segment planning (e.g. the fact
                    # key column's type fails the integer contract)
                    return stage_error_datatable(
                        request.request_id, "joinCompile", str(e))
            else:
                with obs_profiler.active(profile, trace):
                    block = self._execute_segments(query, segments, trace,
                                                   deadline=deadline)
            if missing:
                block.exceptions.append(
                    f"{SEGMENT_MISSING_EXC_PREFIX} {sorted(missing)}")
            elapsed_ms = (time.perf_counter() - t_start) * 1e3
            if elapsed_ms > timeout_ms:
                block.exceptions.append(
                    f"QueryTimeoutError: {elapsed_ms:.0f}ms > "
                    f"{timeout_ms:.0f}ms")
            block.stats.time_used_ms = elapsed_ms
            self.metrics.timer(ServerQueryPhase.QUERY_PROCESSING).update(
                elapsed_ms)
            # per-table twin: the admission controller's rolling
            # service-time estimate (deadline-aware shedding) reads it
            self.metrics.timer(ServerQueryPhase.QUERY_PROCESSING,
                               table=query.table_name).update(elapsed_ms)
            trace.record(ServerQueryPhase.QUERY_PROCESSING, elapsed_ms)
            dt = DataTable.from_block(query, block)
            dt.metadata["requestId"] = str(request.request_id)
            # frozen (name, crc, validDocIds-version) states of the
            # segments this answer was computed over, captured at
            # acquisition time above — the instance layer keys the
            # result cache on them; None = uncacheable (mutable
            # segment, missing CRC, or missing segments)
            dt.cache_states = pre_states
            profile.finish_from_stats(block.stats)
            # the operator profile always travels (a handful of ints);
            # the broker folds it into rolling per-table stats
            dt.metadata["profileInfo"] = profile.to_json_str()
            if missing:
                dt.metadata[MISSING_SEGMENTS_KEY] = json.dumps(
                    sorted(missing))
            if request.enable_trace:
                dt.metadata["traceInfo"] = trace.to_json_str()
            return dt
        finally:
            self.residency.end_query(residency_token)
            for sdm in acquired:
                tdm.release_segment(sdm)

    def execute_batch(self, requests: List[InstanceRequest],
                      scheduler_wait_ms: List[float],
                      deadline: Optional[float]) -> List[DataTable]:
        """One sealed coalescer batch: N same-shape requests over one
        table + segment list, sharing device dispatches.

        The coalescer only seals groups whose members share a table,
        search-segment list, and plan-shape key, carry no trace, and
        are not staged (join/window/exchange) — the invariants this
        path leans on. Returns DataTables aligned with `requests`.
        """
        t_start = time.perf_counter()
        n = len(requests)
        for wait_ms in scheduler_wait_ms:
            self.metrics.meter(ServerMeter.QUERIES).mark()
            self.metrics.timer(ServerQueryPhase.SCHEDULER_WAIT).update(
                wait_ms)
        if deadline is not None and time.monotonic() >= deadline:
            out = []
            for request in requests:
                self.metrics.meter(
                    ServerMeter.DEADLINE_EXPIRED_QUERIES).mark()
                dt = DataTable()
                dt.metadata["requestId"] = str(request.request_id)
                dt.exceptions.append(
                    "DeadlineExceededError: query budget expired before "
                    "execution started; dropped without executing")
                out.append(dt)
            return out
        table = requests[0].query.table_name
        tdm = self.data_manager.table(table)
        if tdm is None:
            out = []
            for request in requests:
                dt = DataTable()
                dt.metadata["requestId"] = str(request.request_id)
                dt.exceptions.append(
                    f"TableDoesNotExistError: {table}")
                out.append(dt)
            return out

        trace = make_trace_context(False)
        profile = QueryProfile(table)
        acquired, missing = tdm.acquire_segments(
            requests[0].search_segments)
        residency_token = self.residency.begin_query(
            [s.segment for s in acquired])
        try:
            segments = [s.segment for s in acquired]
            from pinot_tpu.server.result_cache import segment_cache_states
            pre_states = None if missing else \
                segment_cache_states(segments)
            from pinot_tpu.query.plan import preprocess_request
            # preprocess HERE (not just inside the executor): the
            # DataTable columns must carry any FASTHLL-rewritten names
            queries = [preprocess_request(segments, r.query)
                       for r in requests]
            with obs_profiler.active(profile, trace):
                blocks = self.executor.execute_batch(
                    queries, segments, trace=trace, deadline=deadline)
            elapsed_ms = (time.perf_counter() - t_start) * 1e3
            out = []
            for request, query, block in zip(requests, queries, blocks):
                if missing:
                    block.exceptions.append(
                        f"{SEGMENT_MISSING_EXC_PREFIX} {sorted(missing)}")
                timeout_ms = query.query_options.timeout_ms or \
                    self.default_timeout_ms
                if request.deadline_budget_ms is not None:
                    timeout_ms = min(timeout_ms,
                                     request.deadline_budget_ms)
                if elapsed_ms > timeout_ms:
                    block.exceptions.append(
                        f"QueryTimeoutError: {elapsed_ms:.0f}ms > "
                        f"{timeout_ms:.0f}ms")
                block.stats.time_used_ms = elapsed_ms
                # every member pays (and reports) the batch wall time —
                # it really did wait for the shared dispatch
                self.metrics.timer(
                    ServerQueryPhase.QUERY_PROCESSING).update(elapsed_ms)
                self.metrics.timer(ServerQueryPhase.QUERY_PROCESSING,
                                   table=table).update(elapsed_ms)
                dt = DataTable.from_block(query, block)
                dt.metadata["requestId"] = str(request.request_id)
                dt.cache_states = pre_states
                # per-member profile: own result stats; the dispatch /
                # transfer / path numbers are the BATCH's (each member
                # honestly rode every shared dispatch), batchSize says so
                mp = QueryProfile(table)
                mp.dispatches = profile.dispatches
                mp.transfer_bytes = profile.transfer_bytes
                mp.kernel_ms = profile.kernel_ms
                mp.paths = dict(profile.paths)
                mp.batch_size = n
                mp.finish_from_stats(block.stats)
                dt.metadata["profileInfo"] = mp.to_json_str()
                if missing:
                    dt.metadata[MISSING_SEGMENTS_KEY] = json.dumps(
                        sorted(missing))
                out.append(dt)
            return out
        finally:
            self.residency.end_query(residency_token)
            for sdm in acquired:
                tdm.release_segment(sdm)

    def _attach_join_context(self, request: InstanceRequest, query,
                             segments: List, deadline: Optional[float]):
        """Build the JoinContext from the exchanged dim blocks and
        attach it to a server-local request copy."""
        import copy
        from pinot_tpu.query.stages import join as stages_join
        from pinot_tpu.query.stages.errors import StageCompileError
        if request.exchange_sources is None:
            raise StageCompileError(
                "join query dispatched without exchange sources (stage-1 "
                "dim scan missing)")
        fact_parts = stages_join.fact_partition_info(
            segments, query.join.fact_key)
        ctx = stages_join.build_context(query.join,
                                        request.exchange_sources,
                                        fact_parts, deadline_s=deadline)
        if segments:
            # fact-key contract check up front (exists, SV integer) —
            # an empty dim side must not mask a misspelled/mistyped key
            from pinot_tpu.query.plan import _join_key_source
            _join_key_source(ctx, segments[0])
        query = copy.copy(query)
        query._join_ctx = ctx
        return query

    def _execute_window_stage(self, request: InstanceRequest,
                              deadline: Optional[float]) -> DataTable:
        from pinot_tpu.query.stages.errors import (StageCompileError,
                                                   stage_error_datatable)
        from pinot_tpu.query.stages.window import execute_window_stage
        try:
            blk = execute_window_stage(
                request.query, request.exchange_sources,
                deadline_s=deadline,
                use_device=self.executor.use_device)
        except StageCompileError as e:
            return stage_error_datatable(request.request_id,
                                         "windowCompile", str(e))
        dt = DataTable.from_block(request.query, blk)
        dt.metadata["requestId"] = str(request.request_id)
        return dt

    def _execute_segments(self, query, segments: List, trace: TraceContext,
                          deadline: Optional[float] = None
                          ) -> IntermediateResultsBlock:
        # the sharded combine stacks ALL segments' lanes in HBM — it
        # only applies when every segment is device-tier (a demoted
        # segment must not be re-uploaded through the stack path)
        if self.sharded is not None and len(segments) > 1 and \
                all(self.residency.device_allowed(s) for s in segments):
            from pinot_tpu.parallel.sharded import NotShardable
            from pinot_tpu.query.plan import (GroupsLimitExceeded,
                                              UnsupportedOnDevice)
            try:
                with trace.span(ServerQueryPhase.SHARDED_EXECUTION):
                    blk = self.sharded.execute(query, segments)
                blk.execution_path = "sharded"
                obs_profiler.count_path("sharded", len(segments))
                return blk
            except (NotShardable, GroupsLimitExceeded, UnsupportedOnDevice):
                pass
        blk = self.executor.execute(query, segments, trace=trace,
                                    deadline=deadline)
        blk.execution_path = "sequential"
        return blk
