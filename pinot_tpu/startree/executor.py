"""Star-tree query execution: route eligible queries to a cube.

Parity: core/startree/ query side — StarTreeFilterOperator +
StarTreeAggregationExecutor/StarTreeGroupByExecutor and the plan nodes
that swap in when a query's dimensions/metrics are covered
(StarTreeV2's eligibility rules). Here the cube is a columnar grouped
table, so execution is: evaluate the filter over the cube's dictId lanes
(reusing the host filter evaluator through a segment-shaped facade),
then weighted aggregation over the surviving groups.

Cubes are small by construction (bounded at build), so this runs
host-side numpy — O(groups) instead of the device's O(docs); doc-scale
work never happens at all, which is the entire point of the structure.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.query.aggregation import make_functions
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock

_COVERED_BASES = {"COUNT", "SUM", "AVG", "MIN", "MAX", "MINMAXRANGE"}


class _CubeDataSource:
    """Segment-DataSource-shaped view of one cube dimension lane."""

    def __init__(self, parent_ds, ids: np.ndarray):
        self.metadata = parent_ds.metadata
        self.dictionary = parent_ds.dictionary
        self.dict_ids = ids
        self.raw_values = None
        self.mv_dict_ids = None
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None


class _CubeView:
    """Segment-shaped facade so host filter evaluation runs unchanged."""

    def __init__(self, segment, cube):
        self._segment = segment
        self._cube = cube
        self.num_docs = cube.n_groups
        self.segment_name = segment.segment_name

    def has_column(self, col: str) -> bool:
        return col in self._cube.dim_ids

    def data_source(self, col: str) -> _CubeDataSource:
        return _CubeDataSource(self._segment.data_source(col),
                               self._cube.dim_ids[col])


def _eligible_cube(segment, request: BrokerRequest, functions):
    """Pick the first cube covering the query, or None.

    Coverage: filter + group columns ⊆ dimensions (expressions allowed in
    filters when their source columns are dimensions); aggregations are
    COUNT(*) or covered-base functions over cube metrics.
    """
    cubes = getattr(segment, "star_trees", None)
    if not cubes or not request.is_aggregation or request.is_selection:
        return None
    if request.query_options.options.get("useStarTree") == "false":
        return None
    needed_dims = set()
    for c in request.filter_columns():
        needed_dims.update(expr_mod.referenced_columns(c))
    group_cols = list(request.group_by.columns) if request.group_by else []
    for c in group_cols:
        if expr_mod.is_expression(c):
            return None                       # group keys must be plain dims
        needed_dims.add(c)
    needed_metrics = set()
    for f in functions:
        if f.info.is_mv:
            return None
        if f.info.base == "COUNT":
            continue
        if f.info.base not in _COVERED_BASES:
            return None
        if expr_mod.is_expression(f.column):
            return None
        needed_metrics.add(f.column)
    for cube in cubes:
        if needed_dims <= set(cube.dimensions) and \
                needed_metrics <= set(cube.metrics):
            return cube
    return None


def try_star_tree_execute(segment, request: BrokerRequest
                          ) -> Optional[IntermediateResultsBlock]:
    """Execute over a covering cube; None when not eligible."""
    if not getattr(segment, "star_trees", None):
        return None
    functions = make_functions(request.aggregations)
    cube = _eligible_cube(segment, request, functions)
    if cube is None:
        return None
    from pinot_tpu.query import host_exec
    view = _CubeView(segment, cube)
    try:
        mask = host_exec._eval_filter(request.filter, view)
    except Exception:  # noqa: BLE001 — unresolvable predicate: fall back
        return None

    blk = IntermediateResultsBlock()
    counts = cube.counts
    matched_docs = int(counts[mask].sum())
    if request.is_group_by:
        _cube_group_by(segment, cube, request, functions, mask, blk)
    else:
        blk.agg_intermediates = [
            _cube_aggregate(cube, f, mask) for f in functions]
    blk.stats = ExecutionStats(
        num_docs_scanned=int(mask.sum()),         # groups, not raw docs —
        # parity: star-tree queries report aggregated doc counts
        num_entries_scanned_in_filter=cube.n_groups,
        num_segments_processed=1,
        num_segments_matched=1 if matched_docs else 0,
        total_docs=segment.num_docs)
    return blk


def _cube_aggregate(cube, f, mask: np.ndarray):
    base = f.info.base
    cnt = int(cube.counts[mask].sum())
    if base == "COUNT":
        return cnt
    if cnt == 0:
        return None
    stats = cube.metric_stats[f.column]
    if base == "SUM":
        return float(stats["sum"][mask].sum())
    if base == "AVG":
        return (float(stats["sum"][mask].sum()), cnt)
    if base == "MIN":
        return float(stats["min"][mask].min())
    if base == "MAX":
        return float(stats["max"][mask].max())
    if base == "MINMAXRANGE":
        return (float(stats["min"][mask].min()),
                float(stats["max"][mask].max()))
    raise ValueError(base)


def _cube_group_by(segment, cube, request, functions, mask: np.ndarray,
                   blk: IntermediateResultsBlock) -> None:
    gcols = request.group_by.columns
    sel = np.nonzero(mask)[0]
    lanes = [cube.dim_ids[c][sel].astype(np.int64) for c in gcols]
    cards = [segment.data_source(c).metadata.cardinality for c in gcols]
    key = np.zeros(len(sel), dtype=np.int64)
    for lane, card in zip(lanes, cards):
        key = key * card + lane
    uniq, inverse = np.unique(key, return_inverse=True)
    g = len(uniq)

    value_cols = []
    rem = uniq.copy()
    for c, card in zip(reversed(gcols), reversed(cards)):
        d = segment.data_source(c).dictionary
        value_cols.append(d.decode(rem % card))
        rem //= card
    value_cols.reverse()

    counts = np.zeros(g, dtype=np.int64)
    np.add.at(counts, inverse, cube.counts[sel])
    per_fn: List[List] = []
    for f in functions:
        base = f.info.base
        if base == "COUNT":
            per_fn.append([int(c) for c in counts])
            continue
        stats = cube.metric_stats[f.column]
        if base in ("SUM", "AVG"):
            sums = np.zeros(g)
            np.add.at(sums, inverse, stats["sum"][sel])
            if base == "SUM":
                per_fn.append([float(s) for s in sums])
            else:
                per_fn.append([(float(s), int(c))
                               for s, c in zip(sums, counts)])
        else:
            mins = np.full(g, np.inf)
            maxs = np.full(g, -np.inf)
            np.minimum.at(mins, inverse, stats["min"][sel])
            np.maximum.at(maxs, inverse, stats["max"][sel])
            if base == "MIN":
                per_fn.append([float(v) for v in mins])
            elif base == "MAX":
                per_fn.append([float(v) for v in maxs])
            else:
                per_fn.append([(float(a), float(b))
                               for a, b in zip(mins, maxs)])

    blk.group_map = {
        tuple(_plain(vc[i]) for vc in value_cols):
            [per_fn[fi][i] for fi in range(len(functions))]
        for i in range(g)}


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
