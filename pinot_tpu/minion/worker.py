"""Minion worker: claims tasks, converts segments, re-uploads.

Parity: pinot-minion/.../MinionStarter.java + TaskFactory — a Helix
participant that runs task-framework jobs. Here the worker polls the
property-store task queue (atomic claim), downloads the segment from the
deep store, runs the registered executor, uploads the converted segment
through the controller manager (a refresh bounce re-loads it on
servers), and marks the task COMPLETED/ERROR.
"""
from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import traceback
from typing import List, Optional

from pinot_tpu.common.faults import InjectedCrash
from pinot_tpu.minion.executors import (MinionContext, TaskExecutorRegistry)
from pinot_tpu.minion.tasks import (COMPLETED, ERROR, SEGMENT_NAME_KEY,
                                    TABLE_NAME_KEY, PinotTaskConfig,
                                    TaskQueue)

log = logging.getLogger(__name__)


class MinionEventObserver:
    """Task lifecycle callbacks (parity: pinot-minion's
    MinionEventObserver SPI + MinionEventObserverFactory — observers are
    notified at task start / success / error, e.g. for metrics or
    progress reporting). Default methods are no-ops so observers
    override only what they need."""

    def notify_task_start(self, task: PinotTaskConfig) -> None:
        pass

    def notify_task_success(self, task: PinotTaskConfig) -> None:
        pass

    def notify_task_error(self, task: PinotTaskConfig,
                          error: BaseException) -> None:
        pass


class MinionWorker:
    def __init__(self, manager, instance_id: str = "Minion_0",
                 work_dir: Optional[str] = None,
                 registry: Optional[TaskExecutorRegistry] = None,
                 context: Optional[MinionContext] = None,
                 observers: Optional[List[MinionEventObserver]] = None,
                 metrics=None):
        self.manager = manager                      # ControllerManager
        self.instance_id = instance_id
        self.queue = TaskQueue(manager.store)
        self.registry = registry or TaskExecutorRegistry()
        self.observers: List[MinionEventObserver] = list(observers or ())
        self.context = context or MinionContext()
        if self.context.deadness_lookup is None:
            # compaction drop lists ride the cluster store (published
            # by servers at seal) — executors stay store-agnostic
            from pinot_tpu.realtime.upsert import deadness_path
            self.context.deadness_lookup = \
                lambda t, s: manager.store.get(deadness_path(t, s))
        # the crash-safe swap driver for rewrites that REPLACE their
        # inputs (upsert compaction, merge) — shares the controller
        # manager's store/deep-store handles
        from pinot_tpu.controller.compaction import SegmentSwapManager
        self.swaps = SegmentSwapManager(manager, metrics=metrics)
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="minion_")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- single task ------------------------------------------------------

    def run_one(self) -> Optional[str]:
        """Claim and execute one task; returns its id or None when idle."""
        task = self.queue.claim(self.instance_id,
                                self.registry.task_types())
        if task is None:
            return None
        self._notify(lambda o: o.notify_task_start(task))
        try:
            self._execute(task)
            if not self.queue.finish(task, COMPLETED,
                                     worker_id=self.instance_id):
                # the claim lease expired and the task was requeued
                # from under us (possibly already re-run): our outcome
                # must not clobber the newer claim's
                log.warning("minion %s lost the claim on %s before "
                            "completion landed", self.instance_id,
                            task.task_id)
            else:
                self._notify(lambda o: o.notify_task_success(task))
        except InjectedCrash:
            # simulated kill -9: the process is gone mid-task — the
            # claim stays IN_PROGRESS until its lease expires and the
            # queue requeues it (never mark ERROR for a death)
            raise
        except Exception as e:  # noqa: BLE001 — task isolation boundary
            self.queue.finish(task, ERROR,
                              f"{type(e).__name__}: {e}\n"
                              f"{traceback.format_exc(limit=5)}",
                              worker_id=self.instance_id)
            self._notify(lambda o: o.notify_task_error(task, e))
        return task.task_id

    def _notify(self, fn) -> None:
        for obs in self.observers:
            try:
                fn(obs)
            except Exception:  # noqa: BLE001 — observers never break tasks
                pass

    def _execute(self, task: PinotTaskConfig) -> None:
        table = task.configs[TABLE_NAME_KEY]
        segments = [s for s in
                    task.configs.get(SEGMENT_NAME_KEY, "").split(",") if s]
        executor = self.registry.get(task.task_type)
        if executor is None:
            raise ValueError(f"no executor for task type {task.task_type}")
        if self._finish_interrupted_swap(task, table, segments):
            return
        from pinot_tpu.common.table_name import raw_table
        schema = self.manager.get_schema(raw_table(table)) or \
            self.manager.get_schema(table)
        config = self.manager.get_table_config(table)
        if schema is None or config is None:
            raise ValueError(f"missing schema/config for {table}")
        # download from the deep store (local-FS copy here; the PinotFS
        # SPI covers remote stores)
        inputs = []
        task_dir = os.path.join(self.work_dir, task.task_id)
        os.makedirs(task_dir, exist_ok=True)
        for seg in segments:
            meta = self.manager.segment_metadata(table, seg)
            if meta is None:
                raise ValueError(f"segment {seg} not found in {table}")
            local = os.path.join(task_dir, "in", seg)
            os.makedirs(os.path.dirname(local), exist_ok=True)
            # resolve by scheme: an HTTP-advertised downloadPath fetches
            # through the deep-store client (re-based onto the current
            # controller endpoint), local paths copy directly
            from pinot_tpu.common.filesystem import get_fs
            src = self.manager.resolve_download_path(meta["downloadPath"])
            src_fs = get_fs(src) if "://" in src else self.manager.fs
            src_fs.copy(src, local)
            # minions verify inputs like servers do — a corrupt artifact
            # must not be silently merged/purged into a new segment
            from pinot_tpu.segment.integrity import verify_segment
            verify_segment(local, meta.get("crc"))
            inputs.append(local)
        out_dir = os.path.join(task_dir, "out")
        os.makedirs(out_dir, exist_ok=True)
        result = executor.execute(task, schema, config, inputs, out_dir,
                                  self.context)
        if result.replaces:
            # the rewrite supersedes its inputs: swap them atomically
            # through the crash-safe staged-commit protocol
            self.swaps.swap_segments(table, result.replaces,
                                     result.out_dir)
        else:
            self.manager.add_segment(table, result.out_dir)
        shutil.rmtree(task_dir, ignore_errors=True)

    def _finish_interrupted_swap(self, task: PinotTaskConfig, table: str,
                                 segments: List[str]) -> bool:
        """A re-queued swap task whose previous attempt crashed after
        the durable intent landed: resume the swap instead of
        rebuilding (the staged/published rewrite rolls forward). Also
        short-circuits a task whose previous attempt fully swapped but
        died before its COMPLETED write. Returns True when the task
        needs no rebuild."""
        from pinot_tpu.controller.compaction import SWAPS_ROOT
        from pinot_tpu.minion.executors import (IVF_RETRAIN_TASK,
                                                UPSERT_COMPACTION_TASK)
        out_name = task.configs.get("outputSegmentName", "")
        if not out_name and task.task_type in (UPSERT_COMPACTION_TASK,
                                               IVF_RETRAIN_TASK):
            # same-name rewrites: the swap intent is keyed by the input
            out_name = segments[0] if segments else ""
        if not out_name:
            return False
        intent = self.manager.store.get(
            f"{SWAPS_ROOT}/{table}/{out_name}")
        if intent:
            # THIS task's previous claim died mid-swap (the lease
            # expired, or we'd never have claimed it) — resume exactly
            # its swap, immediately; other tasks' live swaps are their
            # claimants' (or the janitor's) to finish
            log.warning("minion %s: resuming interrupted swap of %s/%s "
                        "from its intent record", self.instance_id,
                        table, out_name)
            self.swaps.resume_swaps(table, min_age_s=0.0, only=out_name)
            # rolled FORWARD (record now carries the rewrite's crc) →
            # done; rolled BACK (nothing was published, old world
            # intact) → fall through and rebuild
            rec = self.manager.segment_metadata(table, out_name) or {}
            return rec.get("crc") == intent.get("newCrc")
        from pinot_tpu.realtime.upsert import deadness_path
        if task.task_type == UPSERT_COMPACTION_TASK and \
                self.manager.store.get(
                    deadness_path(table, out_name)) is None:
            # the deadness record died with a completed swap (or the
            # segment was deleted): nothing provably dead to drop
            log.info("minion %s: no published deadness for %s/%s — "
                     "nothing to compact", self.instance_id, table,
                     out_name)
            return True
        if out_name and task.task_type != UPSERT_COMPACTION_TASK and \
                self.manager.segment_metadata(table, out_name) and \
                all(self.manager.segment_metadata(table, s) is None
                    for s in segments):
            return True          # merge already swapped in fully
        return False

    # -- background loop --------------------------------------------------

    def start(self, poll_interval_s: float = 0.2) -> None:
        def loop():
            while not self._stop.is_set():
                if self.run_one() is None:
                    self._stop.wait(poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=self.instance_id)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def drain(self) -> List[str]:
        """Run queued tasks to completion (test/batch convenience)."""
        done = []
        while True:
            tid = self.run_one()
            if tid is None:
                return done
            done.append(tid)
