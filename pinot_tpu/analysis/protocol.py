"""Protocol tier: transition-system extraction + explicit-state model
checking of the crash-pointed distributed protocols.

The repo's safety invariants (docs/ROBUSTNESS.md, "Self-healing &
membership churn" and "Upserts & convergence") are enforced at runtime
by ~50 kill -9 tests, each exploring ONE crash interleaving. This
module lifts the protocols out of the source and explores EVERY
interleaving of 2 actors x crash-at-every-step, in the explicit-state
model-checking tradition (stateright / TLA+ TLC): states are small
tuples of durable + in-memory facts, transitions are the statically
extracted mutation steps, BFS with state dedup enumerates the space
(10^2-10^4 states per system), and a violated invariant yields an
ordered counterexample trace.

Extraction contract (documented in docs/ANALYSIS.md)
----------------------------------------------------
The extractor does NOT interpret arbitrary Python. For each protocol it
locates one anchor function and matches a fixed set of step shapes by
walking the statements in source order:

- ``lease``     — `ControllerLeadershipManager.try_acquire`: the
  `store.get` read, the `leaseUntil` expiry compare, the
  `rec["epoch"] = ... + 1` fencing bump, and the `store.cas` write
  (a `store.set` in its place is extracted as a BLIND write); plus
  `holds_fenced_lease`'s holder/TTL/epoch compares.
- ``rebalance`` — `SegmentRebalancer.repair_table`: `compute_repair`,
  the add fold (inner def using `setdefault`), the prune fold (inner
  def using `.pop`), the two `rebalance.*` crash points, and whether
  the prune re-checks liveness (`not in live`).
- ``takeover``  — `_ensure_partition_consuming`'s repair arm: the
  state-aware re-entry guard (`== CONSUMING` AND `in live`), the
  OFFLINE bounce fold, the `takeover.pre_resume` crash point, and the
  replace-vs-merge shape of the CONSUMING reassignment fold.
- ``upsert-seal`` — `PartitionUpsertMetadata.seal`: sidecar writes,
  the staged snapshot write, the atomic rename, the in-memory offset
  publish, and the journal truncate — in whatever order the SOURCE
  has them: the model executes the extracted order, so reordering
  rename/truncate in code produces a counterexample, not a parse error.
- ``drain``     — `DistributedServer.drain`: seal -> deregister ->
  await-external-view-clear -> await-admission-drain -> stop.
- ``compact-swap`` — `SegmentSwapManager.swap_segments` (+ the fold
  order inside `_swap_ideal_state`, spliced in place of the
  swap-serving call): stage copy, staged verify, the
  `compact.staged`/`compact.pre_swap`/`compact.pre_delete` crash
  points, intent write, same-name trash slide, atomic publish, record
  write, the drop-olds / add-new ideal-state folds, delayed-delete
  tombstoning, and the intent clear — in source order, so reordering
  the folds (serve-both window) or the tombstone (delete-before-swap)
  produces a counterexample, not a parse error.
- ``exchange`` — `ExchangeManager.put`/`get`/`_sweep` (stages/
  exchange.py) plus the stage-1 publish epilogue
  `ServerInstance._maybe_publish` (server/instance.py): the put-scope
  sweep, the replaced-entry credit, the budget overflow compare, the
  store/debit/ledger-register writes, the get-scope sweep + read, the
  sweep's evict + ledger release, and the publish→ack site order — in
  whatever order the SOURCE has them. The model runs publisher x
  fetcher x TTL sweeper x crash-at-every-step; lock flags
  (`locked_put`/`locked_get`) decide whether put/get execute
  atomically or micro-step-interleaved, so deleting the lock or
  reordering credit/compare produces a counterexample, not a parse
  error.

- ``residency`` — `ResidencyManager.demote_segment` /
  `promote_segment` (server/residency_manager.py): the staged tier
  swap — stage/verify host copy, the `residency.demote_staged` /
  `residency.pre_publish` / `residency.pre_release` crash points,
  artifact verification (disk), the tier publish, the query-pin drain,
  the lane release, and promotion's reload→upload→publish — in
  whatever order the SOURCE has them. The model runs demoter (→host,
  →disk) x promoter x a pin/read/unpin query x artifact loss x
  crash-at-every-step against `no-read-of-released-lane`,
  `promoted-implies-artifact` and `budget-conservation`, so releasing
  before the publish+drain or publishing disk tier without a verified
  artifact produces a counterexample, not a parse error.

Step SEMANTICS are bound here by step name; step ORDER and the
discipline flags come from the source. A protocol edit that preserves
the discipline re-extracts cleanly; one that breaks it either fails the
shape contract (missing step) or, better, produces a concrete
counterexample trace from the checker.

The extracted systems are also dumped to ``protocol-model.json``
(``--write-protocol-model``) and diffed against the committed copy by
the ``protocol-model`` rule, so protocol changes are review-visible the
same way wire-schema changes are.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
from collections import deque
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple)

PROTOCOL_MODEL_FILE = "protocol-model.json"
DEFAULT_MAX_STATES = 200_000

# ---------------------------------------------------------------------------
# Extraction machinery
# ---------------------------------------------------------------------------


class ExtractionError(ValueError):
    """The source no longer matches the protocol shape contract."""


def _ordered_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Pre-order, source-ordered walk (ast.walk is breadth-first and
    loses statement order, which IS the thing we extract)."""
    for child in ast.iter_child_nodes(fn):
        yield child
        yield from _ordered_nodes(child)


def _find_def(tree: ast.Module, qualname: str) -> ast.AST:
    """'Class.method' or bare 'function' → the def node."""
    parts = qualname.split(".")
    scope: ast.AST = tree
    for part in parts:
        found = None
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.name == part:
                found = node
                break
        if found is None:
            raise ExtractionError(f"definition {qualname!r} not found "
                                  "(protocol anchor moved or renamed)")
        scope = found
    return scope


from pinot_tpu.analysis.astutil import safe_unparse as _u


@dataclasses.dataclass
class Extraction:
    """One protocol's statically extracted shape."""

    name: str
    path: str
    function: str
    steps: List[Tuple[str, int]]          # (step name, line) source order
    flags: Dict[str, bool]
    problems: List[str]                   # shape-contract violations

    def step_order(self) -> List[str]:
        return [s for s, _ in self.steps]

    def line_of(self, step: str, default: int = 1) -> int:
        for s, ln in self.steps:
            if s == step:
                return ln
        return default


def _extract_steps(fn: ast.AST,
                   specs: Sequence[Tuple[str, Callable[[ast.AST], bool]]]
                   ) -> List[Tuple[str, int]]:
    """Match each spec's FIRST occurrence in source order; the result
    keeps source order (which IS the extracted protocol)."""
    found: List[Tuple[str, int]] = []
    have = set()
    for node in _ordered_nodes(fn):
        for name, pred in specs:
            if name in have:
                continue
            try:
                hit = pred(node)
            except Exception:  # noqa: BLE001 — a predicate that chokes
                hit = False    # on an odd node simply doesn't match it
            if hit:
                found.append((name, getattr(node, "lineno", 1)))
                have.add(name)
                break
    return found


def _is_call_containing(node: ast.AST, *needles: str) -> bool:
    if not isinstance(node, ast.Call):
        return False
    text = _u(node)
    return all(n in text for n in needles)


def _is_crash_hit(node: ast.AST, point: str) -> bool:
    return (isinstance(node, ast.Call) and
            _u(node.func).endswith("crash_points.hit") and
            node.args and isinstance(node.args[0], ast.Constant) and
            node.args[0].value == point)


def _load(path: str, sources: Optional[Dict[str, str]]) -> str:
    if sources is not None and path in sources:
        return sources[path]
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def _require_order(ex: Extraction, *names: str) -> None:
    """Record a problem unless the named steps exist in this order."""
    lines = []
    for n in names:
        ln = ex.line_of(n, default=-1)
        if ln < 0:
            ex.problems.append(
                f"{ex.path}::{ex.function}: required step `{n}` not "
                "found — the protocol shape contract no longer matches "
                "(see docs/ANALYSIS.md, extraction contract)")
            return
        lines.append(ln)
    if lines != sorted(lines):
        ex.problems.append(
            f"{ex.path}::{ex.function}: steps {list(names)} out of "
            f"order (lines {lines}) — the extracted discipline is "
            "broken")


# -- per-protocol extractors -------------------------------------------------

LEASE_PATH = "pinot_tpu/controller/leadership.py"
REBALANCE_PATH = "pinot_tpu/controller/rebalance.py"
TAKEOVER_PATH = "pinot_tpu/controller/realtime_manager.py"
SEAL_PATH = "pinot_tpu/realtime/upsert.py"
DRAIN_PATH = "pinot_tpu/tools/distributed.py"
COMPACT_PATH = "pinot_tpu/controller/compaction.py"
XCHG_PATH = "pinot_tpu/query/stages/exchange.py"
XCHG_SITE_PATH = "pinot_tpu/server/instance.py"
RESIDENCY_PATH = "pinot_tpu/server/residency_manager.py"


def extract_lease(sources: Optional[Dict[str, str]] = None) -> Extraction:
    src = _load(LEASE_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "ControllerLeadershipManager.try_acquire")
    steps = _extract_steps(fn, [
        ("read_lease", lambda n: _is_call_containing(n, ".get(")
         and "store" in _u(n)),
        ("expiry_check", lambda n: isinstance(n, ast.Compare)
         and "leaseUntil" in _u(n)),
        ("bump_epoch", lambda n: isinstance(n, ast.Assign)
         and "['epoch']" in _u(n.targets[0]) and "+ 1" in _u(n.value)),
        ("cas_write", lambda n: _is_call_containing(n, ".cas(")
         and "store" in _u(n)),
        ("blind_write", lambda n: _is_call_containing(n, "store.set(")),
    ])
    ex = Extraction("lease", LEASE_PATH,
                    "ControllerLeadershipManager.try_acquire", steps,
                    flags={}, problems=[])
    order = ex.step_order()
    ex.flags["cas"] = "cas_write" in order
    ex.flags["epoch_bump"] = "bump_epoch" in order
    # the fence predicate: holder + TTL + epoch COMPARES. Matched on
    # actual Compare nodes, never raw function text — a docstring that
    # mentions "epoch" must not vouch for a deleted comparison (the
    # exact regression class this tier exists to catch)
    fence_epoch = fence_holder = fence_ttl = False
    try:
        fence = _find_def(tree,
                          "ControllerLeadershipManager.holds_fenced_lease")
        compares = [_u(c) for c in ast.walk(fence)
                    if isinstance(c, ast.Compare)]
        fence_holder = any("instance" in c for c in compares)
        fence_ttl = any("leaseUntil" in c for c in compares)
        fence_epoch = any("epoch" in c for c in compares)
    except ExtractionError:
        ex.problems.append(
            f"{LEASE_PATH}: holds_fenced_lease missing — FencedStore "
            "has no fence predicate to verify")
    ex.flags["fence_holder"] = fence_holder
    ex.flags["fence_ttl"] = fence_ttl
    ex.flags["fence_epoch"] = fence_epoch
    if not (ex.flags["cas"] or "blind_write" in order):
        ex.problems.append(
            f"{LEASE_PATH}::try_acquire: no lease write (cas or set) "
            "found — shape contract broken")
    _require_order(ex, "read_lease", "expiry_check")
    return ex


def _inner_defs(fn: ast.AST) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in ast.walk(fn)
            if isinstance(n, ast.FunctionDef) and n is not fn}


def extract_rebalance(sources: Optional[Dict[str, str]] = None
                      ) -> Extraction:
    src = _load(REBALANCE_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "SegmentRebalancer.repair_table")
    inner = _inner_defs(fn)
    add_fns = sorted(n for n, d in inner.items() if "setdefault" in _u(d))
    prune_fns = sorted(n for n, d in inner.items() if ".pop(" in _u(d))
    steps = _extract_steps(fn, [
        ("compute_plan", lambda n: _is_call_containing(
            n, "self.compute_repair(")),
        ("crash:rebalance.move_staged",
         lambda n: _is_crash_hit(n, "rebalance.move_staged")),
        ("add_fold", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(a in _u(n) for a in add_fns)),
        ("crash:rebalance.pre_commit",
         lambda n: _is_crash_hit(n, "rebalance.pre_commit")),
        ("prune_fold", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(p in _u(n) for p in prune_fns)),
    ])
    ex = Extraction("rebalance", REBALANCE_PATH,
                    "SegmentRebalancer.repair_table", steps,
                    flags={}, problems=[])
    ex.flags["prune_rechecks_live"] = any(
        "not in live" in _u(inner[p]) for p in prune_fns)
    _require_order(ex, "compute_plan", "add_fold", "prune_fold")
    for cp in ("crash:rebalance.move_staged", "crash:rebalance.pre_commit"):
        if cp not in ex.step_order():
            ex.problems.append(
                f"{REBALANCE_PATH}::repair_table: crash point "
                f"`{cp.split(':', 1)[1]}` removed — the kill-restart "
                "tests can no longer split the fold")
    return ex


def extract_takeover(sources: Optional[Dict[str, str]] = None
                     ) -> Extraction:
    src = _load(TAKEOVER_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "_ensure_partition_consuming")
    inner = _inner_defs(fn)
    bounce_fns = sorted(n for n, d in inner.items() if "OFFLINE" in _u(d))
    assign_fns = sorted(n for n, d in inner.items()
                        if "CONSUMING" in _u(d) and n not in bounce_fns)
    guard_pred = None
    for node in _ordered_nodes(fn):
        if isinstance(node, ast.Call) and _u(node.func) == "any" and \
                "live" in _u(node):
            guard_pred = node
            break
    steps = _extract_steps(fn, [
        ("reentry_guard", lambda n: n is guard_pred),
        ("bounce_offline", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(b in _u(n) for b in bounce_fns)),
        ("crash:takeover.pre_resume",
         lambda n: _is_crash_hit(n, "takeover.pre_resume")),
        ("reassign_consuming", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(a in _u(n) for a in assign_fns)),
    ])
    ex = Extraction("takeover", TAKEOVER_PATH,
                    "_ensure_partition_consuming", steps,
                    flags={}, problems=[])
    guard_text = _u(guard_pred) if guard_pred is not None else ""
    ex.flags["guard_state_aware"] = ("CONSUMING" in guard_text and
                                     "live" in guard_text)
    ex.flags["has_bounce"] = "bounce_offline" in ex.step_order()
    # replace-shape: the reassign fold ASSIGNS the whole entry dict
    # (one fold writes the full replica set); setdefault/.update merge
    # shapes leave previous-generation owners alive
    replaces = False
    for a in assign_fns:
        d = inner[a]
        if any(isinstance(n, ast.Assign) and
               isinstance(n.targets[0], ast.Subscript)
               for n in ast.walk(d)) and "setdefault" not in _u(d) \
                and ".update(" not in _u(d):
            replaces = True
    ex.flags["reassign_replaces"] = replaces
    if "reassign_consuming" not in ex.step_order():
        ex.problems.append(
            f"{TAKEOVER_PATH}::_ensure_partition_consuming: CONSUMING "
            "reassignment fold not found — shape contract broken")
    if ex.flags["has_bounce"]:
        _require_order(ex, "bounce_offline", "reassign_consuming")
    return ex


def extract_seal(sources: Optional[Dict[str, str]] = None) -> Extraction:
    src = _load(SEAL_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "PartitionUpsertMetadata.seal")
    steps = _extract_steps(fn, [
        ("crash:upsert.seal", lambda n: _is_crash_hit(n, "upsert.seal")),
        ("write_sidecars", lambda n: _is_call_containing(
            n, "self._write_sidecar(")),
        ("stage_snapshot", lambda n: _is_call_containing(n, "open(tmp")),
        ("crash:upsert.keymap_snapshot",
         lambda n: _is_crash_hit(n, "upsert.keymap_snapshot")),
        ("rename_snapshot", lambda n: _is_call_containing(
            n, "os.replace(tmp")),
        ("publish_offset", lambda n: isinstance(n, ast.Assign) and
         _u(n.targets[0]) == "self.snapshot_offset"),
        ("truncate_journal", lambda n: _is_call_containing(
            n, "open(self._journal_path()", "'w'")),
    ])
    ex = Extraction("upsert-seal", SEAL_PATH,
                    "PartitionUpsertMetadata.seal", steps,
                    flags={}, problems=[])
    for required in ("stage_snapshot", "rename_snapshot",
                     "truncate_journal"):
        if required not in ex.step_order():
            ex.problems.append(
                f"{SEAL_PATH}::seal: step `{required}` not found — "
                "shape contract broken")
    # journal-append coverage (consumer side of the same system)
    try:
        ja = _find_def(tree, "PartitionUpsertMetadata._journal_append")
        ex.flags["journal_append_crash_point"] = any(
            _is_crash_hit(n, "upsert.journal_append")
            for n in ast.walk(ja))
    except ExtractionError:
        ex.flags["journal_append_crash_point"] = False
    return ex


def extract_drain(sources: Optional[Dict[str, str]] = None) -> Extraction:
    src = _load(DRAIN_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "DistributedServer.drain")
    steps = _extract_steps(fn, [
        ("seal_consuming", lambda n: _is_call_containing(
            n, "seal_consuming(")),
        ("deregister", lambda n: isinstance(n, ast.Call) and
         _u(n) == "self.agent.stop()"),
        ("await_view_clear", lambda n: _is_call_containing(
            n, "view_clear()")),
        ("await_admission_drain", lambda n: _is_call_containing(
            n, "admission.depth()")),
        ("stop_serving", lambda n: isinstance(n, ast.Call) and
         _u(n) == "self.server.stop()"),
    ])
    ex = Extraction("drain", DRAIN_PATH, "DistributedServer.drain",
                    steps, flags={}, problems=[])
    _require_order(ex, "seal_consuming", "deregister",
                   "await_view_clear", "await_admission_drain",
                   "stop_serving")
    return ex


def extract_compact(sources: Optional[Dict[str, str]] = None
                    ) -> Extraction:
    src = _load(COMPACT_PATH, sources)
    tree = ast.parse(src)
    fn = _find_def(tree, "SegmentSwapManager.swap_segments")
    outer = _extract_steps(fn, [
        ("stage_copy", lambda n: _is_call_containing(
            n, ".copy(", "stage")),
        ("verify_staged", lambda n: _is_call_containing(
            n, "verify_segment(stage")),
        ("crash:compact.staged",
         lambda n: _is_crash_hit(n, "compact.staged")),
        ("intent_write", lambda n: _is_call_containing(
            n, ".set(", "intent_path")),
        ("trash_old", lambda n: _is_call_containing(
            n, ".move(", "trash_path(canonical")),
        ("publish_new", lambda n: _is_call_containing(
            n, ".move(stage")),
        ("record_write", lambda n: _is_call_containing(
            n, "._write_record(")),
        ("crash:compact.pre_swap",
         lambda n: _is_crash_hit(n, "compact.pre_swap")),
        ("swap_serving", lambda n: _is_call_containing(
            n, "._swap_ideal_state(")),
        ("crash:compact.pre_delete",
         lambda n: _is_crash_hit(n, "compact.pre_delete")),
        ("tombstone_olds", lambda n: _is_call_containing(
            n, "._tombstone_olds(")),
        ("clear_intent", lambda n: _is_call_containing(
            n, ".remove(", "intent_path")),
    ])
    swapfn = _find_def(tree, "SegmentSwapManager._swap_ideal_state")
    inner = _inner_defs(swapfn)
    drop_fns = sorted(n for n, d in inner.items() if "DROPPED" in _u(d))
    prune_fns = sorted(n for n, d in inner.items() if ".pop(" in _u(d))
    add_fns = sorted(n for n, d in inner.items()
                     if "ONLINE" in _u(d) and n not in drop_fns)
    sub = _extract_steps(swapfn, [
        ("reload_inplace", lambda n: _is_call_containing(
            n, ".reload_segment(")),
        ("drop_olds_fold", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(f in _u(n) for f in drop_fns)),
        ("prune_olds_fold", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(f in _u(n) for f in prune_fns)),
        ("add_new_fold", lambda n: _is_call_containing(
            n, "update_ideal_state") and
            any(f in _u(n) for f in add_fns)),
    ])
    # the serving swap expands into its fold order IN PLACE — the model
    # executes the spliced program, so a fold reorder in the source
    # (serve-both window) shows up as a counterexample trace
    steps: List[Tuple[str, int]] = []
    for name, ln in outer:
        if name == "swap_serving":
            steps.extend(sub)
        else:
            steps.append((name, ln))
    ex = Extraction("compact-swap", COMPACT_PATH,
                    "SegmentSwapManager.swap_segments", steps,
                    flags={}, problems=[])
    order = ex.step_order()
    ex.flags["intent_logged"] = ("intent_write" in order and
                                 "clear_intent" in order)
    ex.flags["staged_verify"] = "verify_staged" in order
    ex.flags["inplace_reloads"] = "reload_inplace" in order
    ex.flags["delayed_delete"] = "tombstone_olds" in order
    for required in ("stage_copy", "intent_write", "publish_new",
                     "record_write", "drop_olds_fold", "add_new_fold",
                     "clear_intent"):
        if required not in order:
            ex.problems.append(
                f"{COMPACT_PATH}::swap_segments: step `{required}` not "
                "found — shape contract broken (see docs/ANALYSIS.md)")
    for cp in ("crash:compact.staged", "crash:compact.pre_swap",
               "crash:compact.pre_delete"):
        if cp not in order:
            ex.problems.append(
                f"{COMPACT_PATH}::swap_segments: crash point "
                f"`{cp.split(':', 1)[1]}` removed — the kill-restart "
                "tests can no longer split the swap")
    _require_order(ex, "stage_copy", "publish_new")
    return ex


def _uses_lock(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                if "_lock" in _u(item.context_expr):
                    return True
    return False


def extract_exchange(sources: Optional[Dict[str, str]] = None
                     ) -> Extraction:
    src = _load(XCHG_PATH, sources)
    tree = ast.parse(src)
    put_fn = _find_def(tree, "ExchangeManager.put")
    get_fn = _find_def(tree, "ExchangeManager.get")
    sweep_fn = _find_def(tree, "ExchangeManager._sweep")
    steps = _extract_steps(put_fn, [
        ("put.sweep", lambda n: _is_call_containing(n, "._sweep(")),
        # the replaced-entry credit: held = self._bytes - len(old...)
        ("put.credit_replaced", lambda n: isinstance(n, ast.Assign)
         and "self._bytes" in _u(n.value) and "old" in _u(n.value)),
        ("put.overflow_check", lambda n: isinstance(n, ast.Compare)
         and "max_bytes" in _u(n)),
        ("put.store", lambda n: isinstance(n, ast.Assign)
         and "._store[" in _u(n.targets[0])),
        ("put.debit", lambda n: isinstance(n, ast.Assign)
         and _u(n.targets[0]) == "self._bytes"),
        ("put.ledger_register",
         lambda n: _is_call_containing(n, "LEDGER.register(")),
    ])
    steps += _extract_steps(get_fn, [
        ("get.sweep", lambda n: _is_call_containing(n, "._sweep(")),
        ("get.read", lambda n: _is_call_containing(n, "._store.get(")),
    ])
    steps += _extract_steps(sweep_fn, [
        ("sweep.evict", lambda n: _is_call_containing(
            n, "._store.pop(")),
        ("sweep.ledger_release",
         lambda n: _is_call_containing(n, "LEDGER.release(")),
    ])
    ex = Extraction("exchange", XCHG_PATH, "ExchangeManager.put", steps,
                    flags={}, problems=[])
    ex.flags["locked_put"] = _uses_lock(put_fn)
    ex.flags["locked_get"] = _uses_lock(get_fn)
    standalone = False
    try:
        se = _find_def(tree, "ExchangeManager.sweep_expired")
        standalone = any(_is_call_containing(n, "._sweep(")
                         for n in ast.walk(se))
    except ExtractionError:
        pass
    ex.flags["standalone_sweep"] = standalone
    try:
        init = _find_def(tree, "ExchangeManager.__init__")
        ex.flags["ledger_sweep_hook"] = any(
            _is_call_containing(n, "add_sweeper")
            for n in ast.walk(init))
    except ExtractionError:
        ex.flags["ledger_sweep_hook"] = False
    try:
        close = _find_def(tree, "ExchangeManager.close")
        ex.flags["close_releases_ledger"] = any(
            _is_call_containing(n, "release_prefix(")
            for n in ast.walk(close))
    except ExtractionError:
        ex.flags["close_releases_ledger"] = False
    # the typed-miss surface: handle_frame answers an unknown/expired id
    # with an ExchangeMissError DataTable, and the fetch client converts
    # it into a raised ExchangeError (the 422/stageError path)
    miss_typed = False
    try:
        hf = _find_def(tree, "ExchangeManager.handle_frame")
        replies = any(_is_call_containing(n, "_miss_reply(")
                      for n in ast.walk(hf))
        cb = _find_def(tree, "_check_block")
        raises = any(isinstance(n, ast.Raise) and "ExchangeError" in _u(n)
                     for n in ast.walk(cb))
        miss_typed = replies and raises
    except ExtractionError:
        pass
    ex.flags["miss_typed"] = miss_typed
    # the publish/ack site: put must precede the ack the broker
    # schedules stage 2 from, and an overflow must surface as the typed
    # exchangeCapacity stageError
    raises_typed = any(isinstance(n, ast.Raise) and
                       "ExchangeError" in _u(n)
                       for n in ast.walk(put_fn))
    ack_after_put = False
    site_catches = False
    try:
        psrc = _load(XCHG_SITE_PATH, sources)
        site = _find_def(ast.parse(psrc),
                         "ServerInstance._maybe_publish")
        site_steps = _extract_steps(site, [
            ("ack.publish_block",
             lambda n: _is_call_containing(n, ".exchange.put(")),
            ("ack.send_ack", lambda n: isinstance(n, ast.Assign)
             and "exchangeId" in _u(n.targets[0])),
        ])
        ex.steps += site_steps
        lines = dict(site_steps)
        if "ack.publish_block" in lines and "ack.send_ack" in lines:
            ack_after_put = (lines["ack.publish_block"] <
                             lines["ack.send_ack"])
        else:
            ex.problems.append(
                f"{XCHG_SITE_PATH}::_maybe_publish: publish/ack steps "
                "not found — the stage-1 producer epilogue no longer "
                "matches the shape contract")
        site_catches = any(
            isinstance(h, ast.ExceptHandler) and h.type is not None and
            "ExchangeError" in _u(h.type) and
            "stage_error_datatable" in _u(h)
            for h in ast.walk(site))
    except (ExtractionError, SyntaxError, OSError):
        ex.problems.append(
            f"{XCHG_SITE_PATH}: ServerInstance._maybe_publish missing — "
            "the exchange publish/ack site cannot be extracted")
    ex.flags["ack_after_put"] = ack_after_put
    ex.flags["overflow_typed"] = raises_typed and site_catches
    if not ex.flags["overflow_typed"]:
        ex.problems.append(
            f"{XCHG_PATH}::put: budget overflow is not surfaced as a "
            "typed ExchangeError -> exchangeCapacity stageError — the "
            "broker would see a transport-class failure instead of the "
            "422 surface")
    order = ex.step_order()
    for required in ("put.overflow_check", "put.store", "put.debit",
                     "get.read", "sweep.evict"):
        if required not in order:
            ex.problems.append(
                f"{XCHG_PATH}: required step `{required}` not found — "
                "the exchange shape contract no longer matches "
                "(see docs/ANALYSIS.md, extraction contract)")
    return ex


def _with_lock_named(fn: ast.AST, needle: str) -> bool:
    return any(isinstance(n, ast.With) and
               any(needle in _u(item.context_expr) for item in n.items)
               for n in ast.walk(fn))


def extract_residency(sources: Optional[Dict[str, str]] = None
                      ) -> Extraction:
    """Tiered segment residency (server/residency_manager.py): the
    staged demote swap (stage/verify → publish tier → drain query pins
    → release lanes, with the three `residency.*` crash points), the
    promote swap (reload → upload → publish), and the discipline flags
    (swap_lock serialization, budget admitted against the LEDGER total,
    disk→host reload published only after the rebind)."""
    src = _load(RESIDENCY_PATH, sources)
    tree = ast.parse(src)
    dem_fn = _find_def(tree, "ResidencyManager.demote_segment")
    pro_fn = _find_def(tree, "ResidencyManager.promote_segment")
    steps = _extract_steps(dem_fn, [
        ("demote.stage_host",
         lambda n: _is_call_containing(n, "._stage_host(")),
        ("demote.crash_staged",
         lambda n: _is_crash_hit(n, "residency.demote_staged")),
        ("demote.require_artifact",
         lambda n: _is_call_containing(n, "._require_artifact(")),
        ("demote.crash_pre_publish",
         lambda n: _is_crash_hit(n, "residency.pre_publish")),
        ("demote.publish_tier", lambda n: isinstance(n, ast.Assign)
         and _u(n.targets[0]) == "entry.tier"
         and _u(n.value) == "tier"),
        ("demote.await_unpinned",
         lambda n: _is_call_containing(n, "._await_unpinned(")),
        ("demote.crash_pre_release",
         lambda n: _is_crash_hit(n, "residency.pre_release")),
        ("demote.release_lanes",
         lambda n: _is_call_containing(n, "._release_lanes(")),
    ])
    steps += _extract_steps(pro_fn, [
        ("promote.admit_check",
         lambda n: _is_call_containing(n, "._admit_device(")),
        ("promote.reload_artifact",
         lambda n: _is_call_containing(n, "._reload_from_artifact(")),
        ("promote.upload",
         lambda n: _is_call_containing(n, ".warm_device(")),
        ("promote.publish_tier", lambda n: isinstance(n, ast.Assign)
         and _u(n.targets[0]) == "entry.tier"
         and "TIER_DEVICE" in _u(n.value)),
    ])
    ex = Extraction("residency", RESIDENCY_PATH,
                    "ResidencyManager.demote_segment", steps,
                    flags={}, problems=[])
    ex.flags["locked_swap"] = (_with_lock_named(dem_fn, "swap_lock") and
                               _with_lock_named(pro_fn, "swap_lock"))
    if not ex.flags["locked_swap"]:
        ex.problems.append(
            f"{RESIDENCY_PATH}: demote_segment/promote_segment do not "
            "serialize on entry.swap_lock — concurrent tier transitions "
            "on one segment can tear the staged swap")
    # budget admission must read the process-global ledger total (the
    # ground truth that includes stacks/join/window/exchange bytes),
    # not a private per-manager estimate
    admits_by_ledger = False
    try:
        adm = _find_def(tree, "ResidencyManager._admit_device")
        admits_by_ledger = any(
            _is_call_containing(n, "total_bytes(")
            for n in ast.walk(adm))
    except ExtractionError:
        pass
    ex.flags["admits_by_ledger"] = admits_by_ledger
    if not admits_by_ledger:
        ex.problems.append(
            f"{RESIDENCY_PATH}::_admit_device: device admission does "
            "not read LEDGER.total_bytes() — the budget would diverge "
            "from the ledger ground truth (budget-conservation)")
    # the disk→host cold path must reload+rebind BEFORE publishing
    # host tier, or a racing query reads a half-rebound segment
    reload_before_publish = False
    try:
        eh = _find_def(tree, "ResidencyManager.ensure_host")
        eh_steps = _extract_steps(eh, [
            ("reload", lambda n: _is_call_containing(
                n, "._reload_from_artifact(")),
            ("publish", lambda n: isinstance(n, ast.Assign)
             and _u(n.targets[0]) == "entry.tier"),
        ])
        lines = dict(eh_steps)
        reload_before_publish = ("reload" in lines and
                                 "publish" in lines and
                                 lines["reload"] < lines["publish"])
    except ExtractionError:
        pass
    ex.flags["reload_before_publish"] = reload_before_publish
    if not reload_before_publish:
        ex.problems.append(
            f"{RESIDENCY_PATH}::ensure_host: the disk-tier cold reload "
            "does not rebind host lanes BEFORE publishing host tier — "
            "a racing query would read a half-rebound segment")
    # the one hard shape requirement: the host copy is staged/verified
    # before the tier flips (everything else — drain order, release
    # order, artifact verification — surfaces as a model-checker
    # counterexample rather than a parse error)
    _require_order(ex, "demote.stage_host", "demote.publish_tier")
    order = ex.step_order()
    for required in ("demote.crash_staged", "demote.crash_pre_publish",
                     "demote.crash_pre_release", "demote.publish_tier",
                     "demote.await_unpinned", "demote.release_lanes",
                     "promote.upload", "promote.publish_tier"):
        if required not in order:
            ex.problems.append(
                f"{RESIDENCY_PATH}: required step `{required}` not "
                "found — the residency shape contract no longer "
                "matches (see docs/ANALYSIS.md, extraction contract)")
    return ex


def extract_all(sources: Optional[Dict[str, str]] = None
                ) -> List[Extraction]:
    return [extract_lease(sources), extract_rebalance(sources),
            extract_takeover(sources), extract_seal(sources),
            extract_drain(sources), extract_compact(sources),
            extract_exchange(sources), extract_residency(sources)]


# ---------------------------------------------------------------------------
# Explicit-state model checker
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Action:
    label: str
    enabled: Callable[[tuple], bool]
    apply: Callable[[tuple], tuple]


@dataclasses.dataclass
class System:
    name: str
    path: str
    anchor_line: int
    init: tuple
    actions: List[Action]
    #: invariant name -> predicate(state) returning a violation message
    #: (None = holds). Checked on EVERY reached state.
    invariants: List[Tuple[str, Callable[[tuple], Optional[str]]]]


@dataclasses.dataclass
class Violation:
    system: str
    invariant: str
    message: str
    trace: List[str]                      # ordered action labels

    def render_trace(self) -> str:
        steps = " -> ".join(self.trace) if self.trace else "<initial state>"
        return (f"counterexample ({len(self.trace)} step(s)): {steps}")


@dataclasses.dataclass
class Report:
    system: str
    path: str
    anchor_line: int
    states: int
    truncated: bool
    violations: List[Violation]


def explore(system: System, max_states: int = DEFAULT_MAX_STATES
            ) -> Report:
    """BFS over all interleavings with state dedup. Deterministic:
    actions fire in list order, states are plain tuples, the frontier
    is FIFO — two runs over the same system byte-agree."""
    parent: Dict[tuple, Optional[Tuple[tuple, str]]] = {system.init: None}
    queue: deque = deque([system.init])
    violations: List[Violation] = []
    seen_inv = set()

    def trace_of(state: tuple) -> List[str]:
        out: List[str] = []
        cur = state
        while parent[cur] is not None:
            prev, label = parent[cur]
            out.append(label)
            cur = prev
        out.reverse()
        return out

    def check(state: tuple) -> None:
        for inv_name, pred in system.invariants:
            if inv_name in seen_inv:
                continue
            msg = pred(state)
            if msg is not None:
                seen_inv.add(inv_name)
                violations.append(Violation(
                    system.name, inv_name, msg, trace_of(state)))

    check(system.init)
    truncated = False
    while queue and not truncated:
        state = queue.popleft()
        for action in system.actions:
            if not action.enabled(state):
                continue
            nxt = action.apply(state)
            if nxt == state or nxt in parent:
                continue
            if len(parent) >= max_states:
                truncated = True
                break
            parent[nxt] = (state, action.label)
            check(nxt)
            queue.append(nxt)
    return Report(system.name, system.path, system.anchor_line,
                  len(parent), truncated, violations)


# ---------------------------------------------------------------------------
# Model builders — semantics keyed by extracted step names/flags
# ---------------------------------------------------------------------------

# -- lease / epoch fencing ---------------------------------------------------
#
# State: (holder, epoch, valid, serial, gen, bad, A0, A1) with actor
# Ai = (pc, snap, dec, tko, sep, myepoch, mygen, task, acq)
#   pc 0 = before read, 1 = read done, 9 = round over
#   snap = store serial captured at read (CAS witness)
#   dec/tko = decision/takeover captured at read; sep = epoch at read
#   task = pending fenced mutation (epoch, gen) from an EARLIER
#          incarnation (a periodic task's delayed write), -1 = none
#   acq = acquisitions used (bounds the space)
# `gen` is the GROUND-TRUTH leadership generation (bumped on every
# holder change, independent of the extracted epoch discipline); `bad`
# latches when a write is ADMITTED by the extracted fence while its
# issue-time generation differs from the live one — exactly invariant 3
# of ROBUSTNESS.md ("fenced writes").

_L_MAX_ACQ = 2


def _lease_actor(state, i):
    return state[6 + i]


def _lease_with(state, i, actor, **top):
    base = {"holder": state[0], "epoch": state[1], "valid": state[2],
            "serial": state[3], "gen": state[4], "bad": state[5]}
    base.update(top)
    actors = [state[6], state[7]]
    actors[i] = actor
    return (base["holder"], base["epoch"], base["valid"], base["serial"],
            base["gen"], base["bad"], actors[0], actors[1])


def build_lease_system(ex: Extraction) -> System:
    cas = ex.flags.get("cas", True)
    bump = ex.flags.get("epoch_bump", True)
    f_holder = ex.flags.get("fence_holder", True)
    f_ttl = ex.flags.get("fence_ttl", True)
    f_epoch = ex.flags.get("fence_epoch", True)

    init_actor = (0, -1, 0, 0, 0, -1, -1, -1, 0)
    init = (-1, 0, 1, 0, 0, 0, init_actor, init_actor)

    def read(i):
        def enabled(s):
            a = _lease_actor(s, i)
            return a[0] == 0 and a[8] < _L_MAX_ACQ

        def apply(s):
            holder, epoch, valid = s[0], s[1], s[2]
            a = _lease_actor(s, i)
            proceed = 1 if (holder in (-1, i) or not valid) else 0
            takeover = 1 if holder != i else 0
            na = (1, s[3], proceed, takeover, epoch, a[5], a[6], a[7],
                  a[8])
            return _lease_with(s, i, na)
        return Action(f"a{i}.read_lease", enabled, apply)

    def write(i):
        def enabled(s):
            return _lease_actor(s, i)[0] == 1

        def apply(s):
            a = _lease_actor(s, i)
            pc, snap, proceed, takeover, sep = a[0], a[1], a[2], a[3], a[4]
            if not proceed or (cas and s[3] != snap):
                # lost the race (or lease held): round over, no write
                na = (9, -1, 0, 0, 0, a[5], a[6], a[7], a[8] + 1)
                return _lease_with(s, i, na)
            epoch = sep + 1 if (takeover and bump) else sep
            gen = s[4] + 1 if takeover else s[4]
            # a pending task from an earlier incarnation SURVIVES the
            # re-acquire (the delayed periodic-task write); only an
            # empty slot takes the fresh credentials
            task = a[7] if a[7] != -1 else (epoch, gen)
            na = (9, -1, 0, 0, 0, epoch, gen, task, a[8] + 1)
            return _lease_with(s, i, na, holder=i, epoch=epoch, valid=1,
                               serial=s[3] + 1, gen=gen)
        label = "cas_write" if cas else "blind_write"
        return Action(f"a{i}.{label}", enabled, apply)

    def apply_task(i):
        def enabled(s):
            return _lease_actor(s, i)[7] != -1

        def apply(s):
            a = _lease_actor(s, i)
            tepoch, tgen = a[7]
            admitted = ((s[0] == i or not f_holder) and
                        (s[2] == 1 or not f_ttl) and
                        (tepoch == s[1] or not f_epoch))
            bad = s[5]
            if admitted and tgen != s[4]:
                bad = 1
            na = a[:7] + (-1,) + a[8:]
            return _lease_with(s, i, na, bad=bad)
        return Action(f"a{i}.fenced_store_write", enabled, apply)

    def retry(i):
        def enabled(s):
            a = _lease_actor(s, i)
            return a[0] == 9 and a[8] < _L_MAX_ACQ

        def apply(s):
            a = _lease_actor(s, i)
            return _lease_with(s, i, (0,) + a[1:])
        return Action(f"a{i}.retry", enabled, apply)

    def crash(i):
        def enabled(s):
            a = _lease_actor(s, i)
            return a[0] == 1 and a[8] < _L_MAX_ACQ

        def apply(s):
            a = _lease_actor(s, i)
            # restart: in-memory credentials gone, pending task DIES
            # with the process (an in-flight RPC does not survive
            # kill -9); the lease record itself persists until TTL
            na = (0, -1, 0, 0, 0, -1, -1, -1, a[8])
            return _lease_with(s, i, na)
        return Action(f"a{i}.crash_restart", enabled, apply)

    def expire(s):
        return _lease_with(s, 0, _lease_actor(s, 0), valid=0)

    actions = []
    for i in (0, 1):
        actions += [read(i), write(i), apply_task(i), retry(i), crash(i)]
    actions.append(Action("env.lease_expires", lambda s: s[2] == 1,
                          expire))

    def inv_fenced(s):
        if s[5]:
            return ("a store mutation issued under a superseded "
                    "leadership generation was ADMITTED by the fence "
                    "(ROBUSTNESS invariant 3, fenced writes)")
        return None

    return System("lease", ex.path, ex.line_of("read_lease"), init,
                  actions, [("fenced-writes", inv_fenced)])


# -- rebalance add-then-prune fold -------------------------------------------
#
# World: segments s0 {X,Y}, s1 {X,Z}; replication 2; X dead at t0, may
# reincarnate. Actors: two controller incarnations running the repair
# loop concurrently (the fence normally serializes them, but the folds
# must be idempotent even without it — and a crashed actor's successor
# IS the second actor). State:
# (h0, h1, live, regressed, A0, A1); holders/live are sorted tuples of
# server ids 0=X 1=Y 2=Z; actor = (pc, plan, passes); plan = per
# segment (adds, dead).

_R_REPL = 2
_R_SEGS = 2
_R_MAX_PASSES = 2


def _reb_plan(h, live):
    plan = []
    for seg in range(_R_SEGS):
        holders = set(h[seg])
        lset = set(live)
        survivors = holders & lset
        dead = tuple(sorted(holders - lset))
        need = min(_R_REPL, len(lset)) - len(survivors)
        adds = tuple(sorted(lset - holders)[:max(0, need)])
        plan.append((adds, dead))
    return tuple(plan)


def build_rebalance_system(ex: Extraction) -> System:
    rechecks = ex.flags.get("prune_rechecks_live", True)
    order = [s for s in ex.step_order()
             if s in ("compute_plan", "add_fold", "prune_fold")]
    if not order:
        order = ["compute_plan", "add_fold", "prune_fold"]

    init_actor = (0, None, 0)
    init = (((0, 1), (0, 2)), (1, 2), 0, init_actor, init_actor)
    # state: (holders pair, live, regressed, A0, A1)

    def actor_of(s, i):
        return s[3 + i]

    def with_actor(s, i, a, holders=None, live=None, regressed=None):
        actors = [s[3], s[4]]
        actors[i] = a
        return (holders if holders is not None else s[0],
                live if live is not None else s[1],
                regressed if regressed is not None else s[2],
                actors[0], actors[1])

    def live_counts(holders, live):
        lset = set(live)
        return tuple(len(set(h) & lset) for h in holders)

    def step(i, idx, name):
        def enabled(s):
            a = actor_of(s, i)
            return a[0] == idx and a[2] < _R_MAX_PASSES

        def apply(s):
            holders, live = s[0], s[1]
            a = actor_of(s, i)
            regressed = s[2]
            if name == "compute_plan":
                plan = _reb_plan(holders, live)
                if all(not adds and not dead for adds, dead in plan):
                    # converged pass: nothing to do this round
                    return with_actor(s, i, (len(order), None, a[2] + 1))
                return with_actor(s, i, (idx + 1, plan, a[2]))
            if a[1] is None:
                return s
            before = live_counts(holders, live)
            new_h = [set(h) for h in holders]
            if name == "add_fold":
                for seg in range(_R_SEGS):
                    new_h[seg] |= set(a[1][seg][0])
            elif name == "prune_fold":
                lset = set(live)
                for seg in range(_R_SEGS):
                    for d in a[1][seg][1]:
                        if rechecks and d in lset:
                            continue     # reincarnated: keep it
                        new_h[seg].discard(d)
            nh = tuple(tuple(sorted(h)) for h in new_h)
            after = live_counts(nh, live)
            if any(b > x for b, x in zip(before, after)):
                regressed = 1
            done = idx + 1 >= len(order)
            na = (0 if done else idx + 1, None if done else a[1],
                  a[2] + (1 if done else 0))
            return with_actor(s, i, na, holders=nh, regressed=regressed)
        return Action(f"a{i}.{name}", enabled, apply)

    def crash(i):
        def enabled(s):
            a = actor_of(s, i)
            return 0 < a[0] < len(order)

        def apply(s):
            # controller died: in-memory plan lost, durable state stays
            return with_actor(s, i, (len(order), None, _R_MAX_PASSES))
        return Action(f"a{i}.crash", enabled, apply)

    def reincarnate(s):
        return (s[0], tuple(sorted(set(s[1]) | {0})), s[2], s[3], s[4])

    actions = []
    for i in (0, 1):
        for idx, name in enumerate(order):
            actions.append(step(i, idx, name))
        actions.append(crash(i))
    actions.append(Action("env.server_reincarnates",
                          lambda s: 0 not in s[1], reincarnate))

    def inv_regress(s):
        if s[2]:
            return ("a repair fold REDUCED a segment's live replica "
                    "count (pruned a live holder) — ROBUSTNESS "
                    "invariant 2, no replica-count regression")
        return None

    return System("rebalance", ex.path, ex.line_of("compute_plan"),
                  init, actions, [("no-replica-regression", inv_regress)])


# -- realtime partition takeover ---------------------------------------------
#
# World: one partition; owner A=0 CONSUMING (generation 0) and dead;
# healthy server C=1 always live; A may come back (zombie / restart).
# owners: sorted tuple of (inst, consuming?, gen). Actors: controller +
# its restarted incarnation. stalled latches when the re-entry guard
# SKIPS repair while the partition has no live consumer (the PR 9
# membership-only-guard bug).


def build_takeover_system(ex: Extraction) -> System:
    state_aware = ex.flags.get("guard_state_aware", True)
    has_bounce = ex.flags.get("has_bounce", True)
    replaces = ex.flags.get("reassign_replaces", True)
    order = ["reentry_guard"] + (["bounce_offline"] if has_bounce else []) \
        + ["reassign_consuming"]

    init_actor = 0
    init = (((0, 1, 0),), (1,), 0, 0, init_actor, init_actor)
    # (owners, live, stalled, doubled, pc0, pc1)

    def with_state(s, i, pc, owners=None, stalled=None, doubled=None):
        pcs = [s[4], s[5]]
        pcs[i] = pc
        return (owners if owners is not None else s[0],
                s[1],
                stalled if stalled is not None else s[2],
                doubled if doubled is not None else s[3],
                pcs[0], pcs[1])

    def live_consuming(owners, live):
        return [o for o in owners if o[1] == 1 and o[0] in set(live)]

    def check_double(owners):
        gens = {o[2] for o in owners if o[1] == 1}
        return 1 if len(gens) > 1 else 0

    def step(i, idx, name):
        def enabled(s):
            return [s[4], s[5]][i] == idx

        def apply(s):
            owners, live = s[0], s[1]
            if name == "reentry_guard":
                if state_aware:
                    skip = bool(live_consuming(owners, live))
                else:
                    skip = bool({o[0] for o in owners} & set(live))
                if skip:
                    stalled = s[2]
                    if not live_consuming(owners, live):
                        stalled = 1   # declined repair, nobody consumes
                    return with_state(s, i, len(order), stalled=stalled)
                return with_state(s, i, idx + 1)
            if name == "bounce_offline":
                no = tuple(sorted((inst, 0, gen)
                                  for inst, _c, gen in owners))
                return with_state(s, i, idx + 1, owners=no)
            # reassign_consuming: one fold writes the new replica set
            new_gen = max([g for _i, _c, g in owners] or [0]) + 1
            chosen = (1,)                 # healthiest live server
            if replaces:
                no = tuple(sorted((c, 1, new_gen) for c in chosen))
            else:
                kept = tuple(o for o in owners if o[0] not in chosen)
                no = tuple(sorted(kept + tuple(
                    (c, 1, new_gen) for c in chosen)))
            doubled = max(s[3], check_double(no))
            return with_state(s, i, len(order), owners=no,
                              doubled=doubled)
        return Action(f"a{i}.{name}", enabled, apply)

    def crash(i):
        def enabled(s):
            return 0 < [s[4], s[5]][i] < len(order)

        def apply(s):
            return with_state(s, i, len(order))
        return Action(f"a{i}.crash", enabled, apply)

    def revive(s):
        return (s[0], tuple(sorted(set(s[1]) | {0})), s[2], s[3],
                s[4], s[5])

    actions = []
    for i in (0, 1):
        for idx, name in enumerate(order):
            actions.append(step(i, idx, name))
        actions.append(crash(i))
    actions.append(Action("env.old_owner_returns",
                          lambda s: 0 not in s[1], revive))

    def inv_double(s):
        if s[3]:
            return ("two leadership generations hold CONSUMING replicas "
                    "of the same partition — ROBUSTNESS invariant 1, "
                    "no double-owned partition")
        return None

    def inv_stall(s):
        if s[2]:
            return ("the re-entry guard declined repair while the "
                    "partition had NO live consumer (membership-only "
                    "guard: OFFLINE-parked owners stall the partition "
                    "forever)")
        return None

    return System("takeover", ex.path, ex.line_of("reentry_guard"),
                  init, actions,
                  [("no-double-owned", inv_double),
                   ("no-takeover-stall", inv_stall)])


# -- upsert seal / snapshot / truncate ---------------------------------------
#
# Offsets 1..3; seal runs after batch 2 commits (commit boundary 2).
# Durable facts: journal, snapshot(+offset), staged copy. Crash is a
# terminal action that IMMEDIATELY evaluates recovery: what the
# restarted partition can rebuild = snapshot ∪ journal ∪ batches above
# the commit boundary (re-consumed from the topic; batches at or below
# it live in the committed segment and are never re-read). Any ACKED
# batch outside that set is lost — the machine check of "the journal is
# truncated only after the snapshot rename" (ROBUSTNESS, upsert
# invariant 3: durable state is a prefix of applied state).

_S_BATCHES = (1, 2, 3)
_S_SEAL_AFTER = 2


def build_seal_system(ex: Extraction) -> System:
    seal_order = [s for s in ex.step_order()
                  if s in ("write_sidecars", "stage_snapshot",
                           "rename_snapshot", "publish_offset",
                           "truncate_journal")]
    program: List[str] = []
    for b in _S_BATCHES:
        program += [f"apply_mem(b{b})", f"journal_append(b{b})",
                    f"ack(b{b})"]
        if b == _S_SEAL_AFTER:
            program += [f"seal.{s}" for s in seal_order]

    # state: (pc, mem, journal, snap, snap_off, staged, commit_off,
    #         acked, lost)
    init = (0, (), (), (), 0, None, 0, (), 0)

    def step(idx, name):
        def enabled(s):
            return s[0] == idx

        def apply(s):
            (pc, mem, journal, snap, snap_off, staged, commit_off,
             acked, lost) = s
            if name.startswith("apply_mem"):
                b = int(name[-2])
                mem = tuple(sorted(set(mem) | {b}))
            elif name.startswith("journal_append"):
                b = int(name[-2])
                journal = journal + (b,)
            elif name.startswith("ack"):
                b = int(name[-2])
                acked = tuple(sorted(set(acked) | {b}))
                if b == _S_SEAL_AFTER:
                    commit_off = b   # the segment commit precedes seal
            elif name == "seal.stage_snapshot":
                staged = (mem, max(acked or (0,)))
            elif name == "seal.rename_snapshot":
                if staged is not None:
                    snap, snap_off = staged
                    staged = None
            elif name == "seal.truncate_journal":
                journal = ()
            # write_sidecars / publish_offset: no durable-map effect
            return (pc + 1, mem, journal, snap, snap_off, staged,
                    commit_off, acked, lost)
        return Action(name, enabled, apply)

    def crash_apply(s):
        (pc, mem, journal, snap, snap_off, staged, commit_off,
         acked, lost) = s
        recovered = set(snap) | set(journal) | {
            b for b in _S_BATCHES if b > commit_off}
        if not set(acked) <= recovered:
            lost = 1
        # terminal: pc jumps past the program
        return (len(program), mem, journal, snap, snap_off, staged,
                commit_off, acked, lost)

    actions = [step(i, n) for i, n in enumerate(program)]
    actions.append(Action("crash_and_recover",
                          lambda s: s[0] < len(program), crash_apply))

    def inv_no_loss(s):
        if s[8]:
            return ("an ACKED batch is in neither the key-map snapshot, "
                    "the journal, nor the re-consumable topic suffix — "
                    "the journal was truncated before its covering "
                    "snapshot was durable (upsert invariant 3, durable "
                    "state is a prefix of applied state)")
        return None

    return System("upsert-seal", ex.path,
                  ex.line_of("stage_snapshot"), init, actions,
                  [("no-acked-delta-loss", inv_no_loss)])


# -- graceful drain ----------------------------------------------------------
#
# State: (pc, live, ev, stopped, errors, queries_left). The broker
# routes by external view (env.ev_sync lags env-async behind liveness);
# a query dispatched to a stopped server is a drain error — ROBUSTNESS
# invariant 4, drain is errorless. No crash transitions: a crash during
# drain IS a kill -9, which the masking/healing plane owns.


def build_drain_system(ex: Extraction) -> System:
    order = [s for s in ex.step_order()]
    if not order:
        order = ["seal_consuming", "deregister", "await_view_clear",
                 "await_admission_drain", "stop_serving"]
    init = (0, 1, 1, 0, 0, 2)

    def step(idx, name):
        def enabled(s):
            if s[0] != idx:
                return False
            if name == "await_view_clear":
                return s[2] == 0          # blocks until EV drops us
            return True

        def apply(s):
            pc, live, ev, stopped, errors, q = s
            if name == "deregister":
                live = 0
            elif name == "stop_serving":
                stopped = 1
            return (pc + 1, live, ev, stopped, errors, q)
        return Action(f"drain.{name}", enabled, apply)

    def ev_sync(s):
        return (s[0], s[1], s[1], s[3], s[4], s[5])

    def query(s):
        pc, live, ev, stopped, errors, q = s
        if stopped:
            errors = 1
        return (pc, live, ev, stopped, errors, q - 1)

    actions = [step(i, n) for i, n in enumerate(order)]
    actions.append(Action("env.ev_sync", lambda s: s[2] != s[1], ev_sync))
    actions.append(Action("env.query_routed_by_ev",
                          lambda s: s[5] > 0 and s[2] == 1, query))

    def inv_errorless(s):
        if s[4]:
            return ("a query was routed (per the external view) to a "
                    "server that had already stopped — ROBUSTNESS "
                    "invariant 4, drain is errorless")
        return None

    return System("drain", ex.path, ex.line_of("seal_consuming"),
                  init, actions, [("drain-errorless", inv_errorless)])


# -- compaction / merge swap --------------------------------------------------
#
# The merge shape (distinct old/new names) is modeled — it is the
# general case where serve-both (doubled rows) and routed-without-
# artifact are reachable; the same-name in-place shape is structurally
# immune to doubles (one name routes once). Durable facts:
#   staged      the verified rewrite sits in .staging.swap
#   olds_art    old artifacts exist in the deep store (0 = tombstoned)
#   olds_routed olds in the ideal state / routing view
#   new_art     rewrite published under its canonical name
#   new_record  new segment record written
#   new_routed  new segment in the ideal state / routing view
#   intent      durable /SWAPS intent record open
# Actors: the swap DRIVER (runs the extracted program; may crash at
# every step) and the JANITOR (SwapJanitor/requeued task, running the
# resume discipline concurrently — its step semantics are bound here,
# its opportunity set is every interleaving). Environment: a query
# routed by the view (latches `dbl` when both generations are routed),
# and the scrubber sweeping ORPHANED staging (only when no intent
# covers it — the coordination the scrubber satellite implements).
# Invariants: no-double-serve (a query must never count a row from an
# old AND the merged copy), routed-implies-artifact (a routed segment
# must be loadable — a replica restart mid-swap must be able to
# reload it; the delete-before-swap seeded bug), and no-swap-loss
# (once quiescent — driver dead/done, intent cleared — exactly one of
# old/new is fully servable: never neither).


def build_compact_system(ex: Extraction) -> System:
    program = [s for s in ex.step_order()
               if not s.startswith("crash:") and s not in
               ("verify_staged", "reload_inplace", "prune_olds_fold")]

    # state: (pc, staged, olds_art, olds_routed, new_art, new_record,
    #         new_routed, intent, dbl)
    init = (0, 0, 1, 1, 0, 0, 0, 0, 0)
    END = len(program)

    def step(idx, name):
        def enabled(s):
            return s[0] == idx

        def apply(s):
            (pc, staged, olds_art, olds_routed, new_art, new_record,
             new_routed, intent, dbl) = s
            if name == "stage_copy":
                staged = 1
            elif name == "intent_write":
                intent = 1
            elif name == "trash_old":
                pass                  # merge shape: fresh canonical name
            elif name == "publish_new":
                if not staged:
                    # the staged copy vanished (scrubber raced an
                    # intent-less window): fs.move raises, the driver
                    # ABORTS with the intent open — recovery rolls back
                    return (END,) + s[1:]
                new_art, staged = 1, 0
            elif name == "record_write":
                new_record = 1
            elif name == "drop_olds_fold":
                olds_routed = 0
            elif name == "add_new_fold":
                new_routed = 1
            elif name == "tombstone_olds":
                olds_art = 0
            elif name == "clear_intent":
                intent = 0
            return (pc + 1, staged, olds_art, olds_routed, new_art,
                    new_record, new_routed, intent, dbl)
        return Action(f"drv.{name}", enabled, apply)

    def crash(s):
        # kill -9 of the swap driver: in-memory state dies, durable
        # facts persist; the janitor (or a re-queued task) owns
        # recovery from here
        return (END,) + s[1:]

    def jan(name, enabled_fn, apply_fn):
        def apply(s):
            out = apply_fn(dict(pc=s[0], staged=s[1], olds_art=s[2],
                                olds_routed=s[3], new_art=s[4],
                                new_record=s[5], new_routed=s[6],
                                intent=s[7], dbl=s[8]))
            return (s[0], out["staged"], out["olds_art"],
                    out["olds_routed"], out["new_art"],
                    out["new_record"], out["new_routed"], out["intent"],
                    out["dbl"])

        def enabled(s):
            return bool(s[7]) and enabled_fn(dict(
                staged=s[1], olds_art=s[2], olds_routed=s[3],
                new_art=s[4], new_record=s[5], new_routed=s[6]))
        return Action(f"jan.{name}", enabled, apply)

    def upd(d, **kw):
        d = dict(d)
        d.update(kw)
        return d

    actions = [step(i, n) for i, n in enumerate(program)]
    actions.append(Action("drv.crash", lambda s: s[0] < END, crash))
    actions += [
        jan("publish", lambda f: f["staged"] and not f["new_art"],
            lambda f: upd(f, new_art=1, staged=0)),
        jan("record", lambda f: f["new_art"] and not f["new_record"],
            lambda f: upd(f, new_record=1)),
        jan("drop_olds", lambda f: f["new_art"] and f["new_record"]
            and f["olds_routed"],
            lambda f: upd(f, olds_routed=0)),
        jan("add_new", lambda f: f["new_art"] and f["new_record"]
            and not f["olds_routed"] and not f["new_routed"],
            lambda f: upd(f, new_routed=1)),
        jan("tombstone", lambda f: f["new_routed"] and f["olds_art"],
            lambda f: upd(f, olds_art=0)),
        jan("clear", lambda f: f["new_routed"] and f["new_art"]
            and f["new_record"],
            lambda f: upd(f, intent=0)),
        # rollback: nothing durable to roll forward — the old world is
        # intact, the intent clears, the requeued task rebuilds
        jan("rollback", lambda f: not f["staged"] and not f["new_art"],
            lambda f: upd(f, intent=0)),
    ]

    def query(s):
        return s[:8] + (1,)

    actions.append(Action(
        "env.query_routed_by_view",
        lambda s: bool(s[3]) and bool(s[6]) and not s[8], query))

    def sweep(s):
        return (s[0], 0) + s[2:]

    # the scrubber reclaims ORPHANED staging only — an open intent
    # protects its staging (the recovery publishes from it)
    actions.append(Action("env.scrubber_sweeps_staging",
                          lambda s: bool(s[1]) and not s[7], sweep))

    def inv_double(s):
        if s[8]:
            return ("a query counted rows from an old segment AND its "
                    "merged/compacted replacement (both routed "
                    "simultaneously) — the swap must break olds before "
                    "making the new segment visible")
        return None

    def inv_loadable(s):
        if s[3] and not s[2]:
            return ("old segments are still routed but their artifacts "
                    "were already tombstoned (delete-before-swap) — a "
                    "replica restart mid-swap cannot reload what it "
                    "serves")
        if s[6] and not (s[4] and s[5]):
            return ("the new segment is routed but its artifact/record "
                    "is not durably published — replicas cannot load "
                    "it")
        return None

    def inv_loss(s):
        quiescent = s[0] >= END and not s[7]
        servable_old = s[2] and s[3]
        servable_new = s[4] and s[5] and s[6]
        if quiescent and not (servable_old or servable_new):
            return ("swap finished (or died) with the intent cleared "
                    "and NEITHER the old nor the new segment fully "
                    "servable — rows are lost")
        return None

    return System("compact-swap", ex.path, ex.line_of("stage_copy"),
                  init, actions,
                  [("no-double-serve", inv_double),
                   ("routed-implies-artifact", inv_loadable),
                   ("no-swap-loss", inv_loss)])


# -- exchange publish / ack / fetch / TTL-sweep -------------------------------
#
# World: ONE exchange id, byte budget 1, payloads of size 1. The
# publisher publishes TWICE — the second put is the replace-publish
# that exercises the credit-before-compare budget discipline (a replace
# within the REAL occupancy must never be rejected as overflow) — and
# acks the broker in the extracted site order. The fetcher (stage 2)
# fetches once after the ack; the TTL sweeper is the residency-ledger
# scrape hook (`sweep_expired`); the environment expires the entry.
# Books tracked: the manager's held bytes AND the residency ledger's
# exchange bytes (lreg = id currently registered). Atomicity follows
# the extracted locks: with `locked_put`/`locked_get` the put/get
# programs run as single actions; without, every micro-step interleaves
# and crash lands between micro-steps — deleting the lock turns into a
# torn-books or half-published-read counterexample, not silence.
#
# State: (entry, bytes, ledger, lreg, cred, acked, expired_ever,
#         pub, fet, half, ras, silent, spur)
#   entry  0 absent / 1 live / 2 expired (TTL passed, not yet swept)
#   cred   the publisher's in-flight `held` credit local (dies with
#          the put call frame)
#   half   latched: fetch observed a half-published entry / acked-but-
#          unpublished id
#   ras    latched: fetch returned payload for an EXPIRED entry
#   silent latched: miss produced a silent empty result, not the typed
#          ExchangeMissError surface
#   spur   latched: within-budget replace-publish rejected as overflow

_X_KEYS = ("entry", "bytes", "ledger", "lreg", "cred", "acked",
           "expired_ever", "pub", "fet", "half", "ras", "silent",
           "spur")
_X_MAX_BYTES = 1


def _x_dict(s: tuple) -> dict:
    return dict(zip(_X_KEYS, s))


def _x_tuple(d: dict) -> tuple:
    return tuple(d[k] for k in _X_KEYS)


def build_exchange_system(ex: Extraction) -> System:
    order = ex.step_order()
    put_order = [s for s in order if s.startswith("put.")]
    get_order = [s for s in order if s.startswith("get.")]
    locked_put = ex.flags.get("locked_put", True)
    locked_get = ex.flags.get("locked_get", True)
    standalone = ex.flags.get("standalone_sweep", True)
    miss_typed = ex.flags.get("miss_typed", True)
    ack_after_put = ex.flags.get("ack_after_put", True)
    sweep_evicts = "sweep.evict" in order
    sweep_releases = "sweep.ledger_release" in order

    def do_sweep(d: dict) -> None:
        if sweep_evicts and d["entry"] == 2:
            d["entry"] = 0
            d["bytes"] -= 1
            if sweep_releases and d["lreg"]:
                d["ledger"] -= 1
                d["lreg"] = 0

    def op_put(name):
        def fn(d):
            if name == "put.sweep":
                do_sweep(d)
            elif name == "put.credit_replaced":
                d["cred"] = 1 if d["entry"] else 0
            elif name == "put.overflow_check":
                if d["bytes"] - d["cred"] + 1 > _X_MAX_BYTES:
                    real = d["bytes"] - (1 if d["entry"] else 0)
                    if real + 1 <= _X_MAX_BYTES:
                        d["spur"] = 1   # real occupancy admitted it
                    d["abort"] = 1      # typed raise: books untouched
            elif name == "put.store":
                d["entry"] = 1
            elif name == "put.debit":
                d["bytes"] = d["bytes"] - d["cred"] + 1
            elif name == "put.ledger_register":
                if not d["lreg"]:       # owner-replace: no double count
                    d["ledger"] += 1
                    d["lreg"] = 1
        return fn

    # the publisher program: macros of (label, ops, abort_to) — with
    # the lock an attempt is ONE atomic macro; without, each extracted
    # micro-step is its own macro and `abort_to` jumps past the attempt
    pub_macros: List[tuple] = []
    mid_after_store: set = set()
    boundary_pcs: set = set()

    def add_attempt(tag: str) -> None:
        start = len(pub_macros)
        if locked_put:
            pub_macros.append(
                (f"{tag}.put", [op_put(n) for n in put_order],
                 start + 1))
            return
        end = start + len(put_order)
        for n in put_order:
            pub_macros.append((f"{tag}.{n}", [op_put(n)], end))
        if "put.store" in put_order:
            si = put_order.index("put.store")
            mid_after_store.update(range(start + si + 1, end))

    def op_ack(d):
        d["acked"] = 1

    if ack_after_put:
        add_attempt("pub1")
        pub_macros.append(("pub.send_ack", [op_ack], None))
        add_attempt("pub2")
    else:
        pub_macros.append(("pub.send_ack", [op_ack], None))
        add_attempt("pub1")
        add_attempt("pub2")
    p_end = len(pub_macros)
    boundary_pcs.update(i for i in range(p_end + 1)
                        if i not in mid_after_store)

    def op_get(name):
        def fn(d):
            if name == "get.sweep":
                do_sweep(d)
            elif name == "get.read":
                if d["entry"] == 1:
                    if d["pub"] in mid_after_store:
                        d["half"] = 1
                elif d["entry"] == 2:
                    d["ras"] = 1        # returned an expired payload
                elif d["acked"] and not d["expired_ever"]:
                    d["half"] = 1       # acked id not yet published
                elif not miss_typed:
                    d["silent"] = 1
        return fn

    if locked_get:
        fet_macros = [("fet.get", [op_get(n) for n in get_order])]
    else:
        fet_macros = [(f"fet.{n}", [op_get(n)]) for n in get_order]
    f_end = len(fet_macros)

    init = _x_tuple(dict.fromkeys(_X_KEYS, 0))

    def pub_step(idx, label, ops, abort_to):
        def enabled(s):
            return s[7] == idx

        def apply(s):
            d = _x_dict(s)
            aborted = False
            for fn in ops:
                fn(d)
                if d.pop("abort", 0):
                    aborted = True
                    break
            if aborted:
                d["cred"] = 0
                d["pub"] = abort_to if abort_to is not None else idx + 1
            else:
                d["pub"] = idx + 1
                if abort_to is not None and d["pub"] >= abort_to:
                    d["cred"] = 0       # put frame returned
            return _x_tuple(d)
        return Action(label, enabled, apply)

    def fet_step(idx, label, ops):
        def enabled(s):
            return s[5] == 1 and s[8] == idx

        def apply(s):
            d = _x_dict(s)
            for fn in ops:
                fn(d)
            d["fet"] = idx + 1
            return _x_tuple(d)
        return Action(label, enabled, apply)

    actions = [pub_step(i, label, ops, abort_to)
               for i, (label, ops, abort_to) in enumerate(pub_macros)]
    actions += [fet_step(i, label, ops)
                for i, (label, ops) in enumerate(fet_macros)]

    def pub_crash(s):
        d = _x_dict(s)
        d["pub"], d["cred"] = p_end, 0
        return _x_tuple(d)

    def fet_crash(s):
        d = _x_dict(s)
        d["fet"] = f_end
        return _x_tuple(d)

    actions.append(Action("pub.crash", lambda s: s[7] < p_end,
                          pub_crash))
    actions.append(Action("fet.crash", lambda s: s[8] < f_end,
                          fet_crash))

    if standalone:
        def sweep_apply(s):
            d = _x_dict(s)
            do_sweep(d)
            return _x_tuple(d)
        actions.append(Action("swp.sweep_expired",
                              lambda s: s[0] == 2, sweep_apply))

    def expire(s):
        d = _x_dict(s)
        d["entry"], d["expired_ever"] = 2, 1
        return _x_tuple(d)

    actions.append(Action("env.ttl_expires", lambda s: s[0] == 1,
                          expire))

    def inv_half(s):
        if s[9]:
            return ("a fetch observed a half-published exchange entry "
                    "(stored but not yet byte-debited/ledger-"
                    "registered, or the id was ACKED to the broker "
                    "before the block was published) — stage 2 must "
                    "never see a partial put")
        return None

    def inv_ras(s):
        if s[10]:
            return ("a fetch returned payload bytes for an entry whose "
                    "TTL had already expired — get must sweep before "
                    "reading (no-read-after-sweep)")
        return None

    def inv_silent(s):
        if s[11]:
            return ("an expired/unknown exchange fetch produced a "
                    "SILENT empty result instead of the typed "
                    "ExchangeMissError/stageError surface — a join "
                    "side would silently vanish")
        return None

    def inv_spur(s):
        if s[12]:
            return ("a replace-publish within the real byte budget was "
                    "rejected as overflow — the to-be-replaced entry "
                    "must be credited BEFORE the budget compare "
                    "(debit/credit imbalance)")
        return None

    def inv_books(s):
        pub_done = s[7] >= p_end
        fet_quiet = s[8] >= f_end or (pub_done and not s[5])
        if s[7] in boundary_pcs and s[2] != s[1]:
            return ("the manager's held bytes and the residency "
                    "ledger's exchange bytes diverge outside a put "
                    "critical section — register/release no longer "
                    "pairs with debit/credit")
        if pub_done and fet_quiet and s[0] == 0 and (s[1] or s[2]):
            return ("all actors quiescent and the store empty, but "
                    "held/ledger bytes are nonzero — the exchange "
                    "leaks budget (bytes-conservation)")
        if pub_done and fet_quiet and s[0] == 2 and not standalone:
            return ("an expired entry survives quiescence with no "
                    "standalone sweep path (sweep only runs inside "
                    "put/get) — held bytes leak until process death")
        return None

    return System("exchange", ex.path, ex.line_of("put.store"), init,
                  actions,
                  [("no-half-published-read", inv_half),
                   ("no-read-after-sweep", inv_ras),
                   ("expired-fetch-is-typed", inv_silent),
                   ("no-spurious-overflow", inv_spur),
                   ("bytes-conservation", inv_books)])


# -- tiered segment residency ------------------------------------------------
#
# World: ONE managed segment and the model's byte unit is its device
# lane-set. State (tier, dev, host, art, pins, qpc, qroute, dpc, ppc,
# bad, lost, crashed): published tier (0=device/1=host/2=disk), lane
# presence bits, the on-disk artifact bit, the query pin, the query's
# pc + routed tier, the demoter/promoter pcs, and the violation
# latches. Actors: a demoter that runs the extracted demote program
# twice (→host, then →disk), a promoter that runs the extracted promote
# program after it, a query that loops begin(pin)/read/end(unpin), an
# environment action that deletes the artifact (only before the
# demoter's verify step has run — verification freezes it), and
# crash-at-every-step for demoter and promoter (the query's unpin is a
# `finally`; a process crash kills every actor, which the kill-restart
# suite covers). The swap_lock is modeled exactly where the code takes
# it: demote/promote/ensure_host serialize; pin/unpin do not.

_R_KEYS = ("tier", "dev", "host", "art", "pins", "qpc", "qroute",
           "dpc", "ppc", "bad", "lost", "crashed")


def _r_dict(s: tuple) -> dict:
    return dict(zip(_R_KEYS, s))


def _r_tuple(d: dict) -> tuple:
    return tuple(d[k] for k in _R_KEYS)


def build_residency_system(ex: Extraction) -> System:
    order = ex.step_order()
    demote_order = [s for s in order if s.startswith("demote.")]
    promote_order = [s for s in order if s.startswith("promote.")]

    def op_demote(name: str, target: int):
        def fn(d: dict) -> None:
            if name == "demote.stage_host":
                if d["host"] == 0 or d["tier"] == 2:
                    d["abort"] = 1      # ResidencyError: books untouched
            elif name == "demote.require_artifact":
                if d["art"] == 0:
                    d["abort"] = 1      # unreloadable: refuse the demote
            elif name == "demote.publish_tier":
                d["tier"] = target
            elif name == "demote.release_lanes":
                d["dev"] = 0
                if target == 2:
                    d["host"] = 0
            # crash_* markers are no-ops: the dem.crash ACTION models
            # the InjectedCrash at every pc boundary
        return fn

    # program: (label, op, step name, abort_to) per extracted micro-step
    # — swap transitions interleave with query pin/read/unpin by design
    # (the swap_lock does NOT cover the query path)
    prog: List[tuple] = []
    attempt_bounds: List[Tuple[int, int]] = []

    def add_attempt(tag: str, target: int) -> None:
        start = len(prog)
        names = [n for n in demote_order
                 if target == 2 or n != "demote.require_artifact"]
        end = start + len(names)
        for n in names:
            prog.append((f"{tag}.{n[7:]}", op_demote(n, target), n, end))
        attempt_bounds.append((start, end))

    add_attempt("dem1", 1)              # device → host
    add_attempt("dem2", 2)              # host → disk
    dem_end = len(prog)

    # the artifact-verification freeze: once the disk attempt has
    # executed require_artifact, the environment can no longer lose the
    # artifact out from under the publish (the real code verifies under
    # the swap_lock it publishes under). A mutated source that skips
    # verification leaves the environment enabled right up to the disk
    # publish — the counterexample for publish-without-artifact.
    disk_start, disk_end_pc = attempt_bounds[1]
    disk_names = [prog[i][2] for i in range(disk_start, disk_end_pc)]
    if "demote.require_artifact" in disk_names:
        env_cutoff = disk_start + disk_names.index(
            "demote.require_artifact")
    elif "demote.publish_tier" in disk_names:
        env_cutoff = disk_start + disk_names.index("demote.publish_tier")
    else:
        env_cutoff = disk_end_pc

    def dem_step(idx: int, label: str, op, step: str, abort_to: int
                 ) -> Action:
        def enabled(s: tuple) -> bool:
            if s[7] != idx:
                return False
            if step == "demote.await_unpinned":
                return s[4] == 0        # drains: blocks while pinned
            return True

        def apply(s: tuple) -> tuple:
            d = _r_dict(s)
            op(d)
            d["dpc"] = abort_to if d.pop("abort", 0) else idx + 1
            return _r_tuple(d)
        return Action(label, enabled, apply)

    actions = [dem_step(i, label, op, step, abort_to)
               for i, (label, op, step, abort_to) in enumerate(prog)]

    def op_promote(name: str):
        def fn(d: dict) -> None:
            if name == "promote.reload_artifact":
                if d["tier"] == 2:
                    if d["art"]:
                        d["host"] = 1
                    else:
                        d["lost"] = 1   # unrecoverable: data gone
                        d["abort"] = 1
            elif name == "promote.upload":
                if d["host"]:
                    d["dev"] = 1
                else:
                    d["abort"] = 1      # nothing to upload from
            elif name == "promote.publish_tier":
                d["tier"] = 0
        return fn

    pro_prog = [(f"pro.{n[8:]}", op_promote(n)) for n in promote_order]
    pro_end = len(pro_prog)

    def pro_step(idx: int, label: str, op) -> Action:
        def enabled(s: tuple) -> bool:
            return s[7] >= dem_end and s[8] == idx

        def apply(s: tuple) -> tuple:
            d = _r_dict(s)
            op(d)
            d["ppc"] = pro_end if d.pop("abort", 0) else idx + 1
            return _r_tuple(d)
        return Action(label, enabled, apply)

    actions += [pro_step(i, label, op)
                for i, (label, op) in enumerate(pro_prog)]

    # swap_lock: ensure_host (the query's disk-tier cold reload) cannot
    # run while a demote/promote attempt holds the lock mid-swap
    swap_boundaries = {0, dem_end} | {b for _a, b in attempt_bounds}

    def swap_idle(s: tuple) -> bool:
        return (s[7] in swap_boundaries and
                s[8] in (0, pro_end))

    def qry_begin(s: tuple) -> tuple:
        d = _r_dict(s)
        if d["tier"] == 2:
            # ensure_host: reload from the artifact, publish host tier
            if d["art"]:
                d["host"] = 1
                d["tier"] = 1
            else:
                d["lost"] = 1
        d["qroute"] = 0 if d["tier"] == 0 else 1
        d["pins"] = 1
        d["qpc"] = 1
        return _r_tuple(d)

    def qry_read(s: tuple) -> tuple:
        d = _r_dict(s)
        if d["qroute"] == 0 and d["dev"] == 0:
            d["bad"] = 1
        if d["qroute"] == 1 and d["host"] == 0:
            d["bad"] = 1
        d["qpc"] = 2
        return _r_tuple(d)

    def qry_end(s: tuple) -> tuple:
        d = _r_dict(s)
        d["pins"] = 0
        d["qpc"] = 0
        return _r_tuple(d)

    actions.append(Action(
        "qry.begin",
        lambda s: s[5] == 0 and (s[0] != 2 or swap_idle(s)), qry_begin))
    actions.append(Action("qry.read", lambda s: s[5] == 1, qry_read))
    actions.append(Action("qry.end", lambda s: s[5] == 2, qry_end))

    def dem_crash(s: tuple) -> tuple:
        d = _r_dict(s)
        d["dpc"], d["crashed"] = dem_end, 1
        return _r_tuple(d)

    def pro_crash(s: tuple) -> tuple:
        d = _r_dict(s)
        d["ppc"], d["crashed"] = pro_end, 1
        return _r_tuple(d)

    actions.append(Action("dem.crash", lambda s: s[7] < dem_end,
                          dem_crash))
    actions.append(Action("pro.crash",
                          lambda s: s[7] >= dem_end and s[8] < pro_end,
                          pro_crash))

    def env_lost(s: tuple) -> tuple:
        d = _r_dict(s)
        d["art"] = 0
        return _r_tuple(d)

    actions.append(Action(
        "env.artifact_lost",
        lambda s: s[3] == 1 and s[0] != 2 and s[7] <= env_cutoff,
        env_lost))

    init = _r_tuple({"tier": 0, "dev": 1, "host": 1, "art": 1,
                     "pins": 0, "qpc": 0, "qroute": 0, "dpc": 0,
                     "ppc": 0, "bad": 0, "lost": 0, "crashed": 0})

    def inv_read(s: tuple) -> Optional[str]:
        if s[9]:
            return ("a query read a lane its routed tier had already "
                    "released — demotion must publish the fallback "
                    "tier, drain in-flight pins, and only then release "
                    "(no-read-of-released-lane)")
        return None

    def inv_artifact(s: tuple) -> Optional[str]:
        if s[0] == 2 and s[3] == 0:
            return ("disk tier published with no reloadable artifact — "
                    "the artifact must be verified before the tier "
                    "flips (promoted-implies-artifact)")
        if s[10]:
            return ("a disk-tier reload found no artifact: the segment "
                    "is unrecoverable (promoted-implies-artifact)")
        return None

    def inv_budget(s: tuple) -> Optional[str]:
        quiescent = (s[7] >= dem_end and s[8] in (0, pro_end) and
                     s[4] == 0 and s[5] == 0 and not s[11])
        if quiescent and s[0] != 0 and s[1] == 1:
            return ("an off-device segment's device lanes are still "
                    "ledger-resident at quiescence — the demote path "
                    "leaks HBM past the budget (budget-conservation)")
        return None

    return System("residency", ex.path, ex.line_of("demote.publish_tier"),
                  init, actions,
                  [("no-read-of-released-lane", inv_read),
                   ("promoted-implies-artifact", inv_artifact),
                   ("budget-conservation", inv_budget)])


_BUILDERS = {
    "lease": build_lease_system,
    "rebalance": build_rebalance_system,
    "takeover": build_takeover_system,
    "upsert-seal": build_seal_system,
    "drain": build_drain_system,
    "compact-swap": build_compact_system,
    "exchange": build_exchange_system,
    "residency": build_residency_system,
}


# ---------------------------------------------------------------------------
# Entry points (used by rules/protocol_check.py, the CLI, and tests)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProtocolCheckResult:
    reports: List[Report]
    problems: List[Tuple[str, str, int, str]]   # (system, path, line, msg)

    def summary_lines(self) -> List[str]:
        out = []
        for r in self.reports:
            status = "TRUNCATED" if r.truncated else "exhaustive"
            out.append(f"{r.system}: {r.states} state(s) explored "
                       f"({status}), {len(r.violations)} violation(s)")
        return out


def check_protocols(max_states: int = DEFAULT_MAX_STATES,
                    sources: Optional[Dict[str, str]] = None,
                    only: Optional[Sequence[str]] = None
                    ) -> ProtocolCheckResult:
    reports: List[Report] = []
    problems: List[Tuple[str, str, int, str]] = []
    for ex in extract_all(sources):
        if only is not None and ex.name not in only:
            continue
        for p in ex.problems:
            problems.append((ex.name, ex.path, ex.steps[0][1]
                             if ex.steps else 1, p))
        try:
            system = _BUILDERS[ex.name](ex)
        except Exception as e:  # noqa: BLE001 — a builder crash must
            problems.append((ex.name, ex.path, 1,    # fail the gate
                             f"model build failed: {type(e).__name__}: "
                             f"{e}"))
            continue
        reports.append(explore(system, max_states))
    return ProtocolCheckResult(reports, problems)


def protocol_model(sources: Optional[Dict[str, str]] = None) -> dict:
    """The reviewable JSON dump of every extracted transition system
    (step ORDER and discipline flags — line numbers excluded so
    unrelated edits don't churn the committed file)."""
    systems = {}
    for ex in extract_all(sources):
        systems[ex.name] = {
            "file": ex.path,
            "function": ex.function,
            "steps": ex.step_order(),
            "flags": {k: ex.flags[k] for k in sorted(ex.flags)},
            "problems": sorted(ex.problems),
        }
    return {
        "version": 1,
        "comment": ("extracted protocol transition systems; regenerate "
                    "INTENTIONALLY with `python -m pinot_tpu.analysis "
                    "--write-protocol-model` and review the diff as a "
                    "crash-protocol change"),
        "systems": systems,
    }


def write_protocol_model(path: str = PROTOCOL_MODEL_FILE) -> dict:
    model = protocol_model()
    with open(path, "w") as fh:
        json.dump(model, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return model


def check_protocol_model(path: str = PROTOCOL_MODEL_FILE) -> List[str]:
    """Field-level diffs between the committed model and the live
    extraction ([] = protocols unchanged)."""
    if not os.path.exists(path):
        return [f"missing committed snapshot {path} — generate it with "
                "--write-protocol-model and commit it"]
    with open(path) as fh:
        committed = json.load(fh)
    fresh = protocol_model()
    out: List[str] = []

    def diff(a, b, at):
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                loc = f"{at}.{k}" if at else k
                if k not in b:
                    out.append(f"removed: {loc} (was {a[k]!r})")
                elif k not in a:
                    out.append(f"added: {loc} = {b[k]!r}")
                else:
                    diff(a[k], b[k], loc)
            return
        if a != b:
            out.append(f"changed: {at}: {a!r} -> {b!r}")

    diff(committed, fresh, "")
    return out
