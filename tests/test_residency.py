"""HBM residency: the runtime ledger (obs/residency.py), its gauge
wiring, the loader/exchange accounting it observes, and the lifecycle
analysis tier (device-ledger, cache-bound) that keeps every upload and
cache on the books.

The acceptance-critical test here is the cross-check: after
``warm_device()`` the ledger's bytes for a segment must agree with the
ACTUAL ``nbytes`` of the uploaded device lanes (within 5%; in practice
exact) — an accounting layer that drifts from reality is worse than
none.
"""
import os

import pytest

from pinot_tpu.analysis import analyze_paths, analyze_source
from pinot_tpu.obs import residency
from pinot_tpu.obs.residency import LEDGER, ResidencyLedger

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVING_PATH = "pinot_tpu/query/_fixture.py"      # lifecycle scope
PLAIN_PATH = "pinot_tpu/tools/_fixture.py"        # out of scope


def lifecycle_findings(source: str, path: str = SERVING_PATH,
                       rule: str = None):
    res = analyze_source(source, path, tiers=("ast", "lifecycle"))
    return [f for f in res.findings
            if rule is None or f.rule == rule]


# ---------------------------------------------------------------------------
# ResidencyLedger accounting
# ---------------------------------------------------------------------------


def test_register_is_owner_replace_not_leak():
    led = ResidencyLedger()
    led.register("a", table="t", segment="s", kind="scan", nbytes=100)
    led.register("b", table="t", segment="s", kind="vdoc", nbytes=50)
    assert led.total_bytes() == 150
    # re-upload of the same lane replaces the entry
    led.register("a", table="t", segment="s", kind="scan", nbytes=40)
    assert led.total_bytes() == 90
    assert led.kind_bytes("scan") == 40
    assert led.kind_bytes("vdoc") == 50
    assert led.release("a") == 40
    assert led.release("a") == 0          # double release is a no-op
    assert led.total_bytes() == 50


def test_release_prefix_drops_one_owners_lanes():
    led = ResidencyLedger()
    for i in range(3):
        led.register(f"ds:1:lane{i}", table="t", segment="s",
                     kind="scan", nbytes=10)
    led.register("ds:2:lane0", table="t", segment="s2", kind="scan",
                 nbytes=7)
    assert led.release_prefix("ds:1:") == 30
    assert led.total_bytes() == 7
    assert led.kind_bytes("scan") == 7


def test_snapshot_shape_and_totals():
    led = ResidencyLedger()
    led.register("x", table="tbl", segment="s0", kind="scan",
                 nbytes=100)
    led.register("y", table="tbl", segment="s0", kind="vector",
                 nbytes=30)
    led.register("z", table="", segment="", kind="exchange", nbytes=5)
    snap = led.snapshot()
    assert snap["totalDeviceBytesResident"] == 135
    assert snap["byKind"] == {"exchange": 5, "scan": 100, "vector": 30}
    assert snap["tables"]["tbl"] == {"scan": 100, "vector": 30}
    assert snap["entryCount"] == 3
    # entries are the largest-first spill, each fully attributed
    assert snap["entries"][0] == {"owner": "x", "table": "tbl",
                                  "segment": "s0", "kind": "scan",
                                  "bytes": 100}
    assert {e["owner"] for e in snap["entries"]} == {"x", "y", "z"}


def test_snapshot_respects_max_entries_but_not_totals():
    led = ResidencyLedger()
    for i in range(10):
        led.register(f"o{i}", table="t", segment="s", kind="scan",
                     nbytes=i + 1)
    snap = led.snapshot(max_entries=3)
    assert len(snap["entries"]) == 3
    assert [e["bytes"] for e in snap["entries"]] == [10, 9, 8]
    assert snap["entryCount"] == 10
    assert snap["totalDeviceBytesResident"] == sum(range(1, 11))


def test_sweepers_run_on_scrape_and_exchange_reads_only():
    led = ResidencyLedger()
    calls = []

    def sweeper():
        calls.append(1)
        return 0

    led.add_sweeper(sweeper)
    led.snapshot()                   # scrape path sweeps
    led.kind_bytes("exchange")       # exchange gauge read sweeps
    led.kind_bytes("scan")           # plain kind read must NOT
    led.total_bytes()
    assert len(calls) == 2
    led.remove_sweeper(sweeper)
    led.remove_sweeper(sweeper)      # idempotent
    led.snapshot()
    assert len(calls) == 2


def test_bind_registry_preregisters_every_kind_series():
    from pinot_tpu.common.metrics import MetricsRegistry
    from pinot_tpu.obs.prometheus import render_prometheus
    reg = MetricsRegistry("server")
    residency.bind_registry(reg)
    text = render_prometheus(reg)
    # the bare total plus one series per kind, scrapeable BEFORE any
    # upload happens (empty-registry exposition was a real bug class)
    assert "device_bytes_resident" in text
    for kind in residency.KINDS:
        assert f'"{kind}"' in text, (kind, text)


# ---------------------------------------------------------------------------
# runtime cross-check: ledger totals vs actual uploaded lane bytes
# ---------------------------------------------------------------------------


def _segment_device_bytes(seg):
    """Ground truth: sum of nbytes over every device array the segment
    is holding right now."""
    total = 0
    for ds in seg._data_sources.values():
        total += sum(int(arr.nbytes) for arr in ds._dev.values())
    if seg._valid_dev is not None:
        total += int(seg._valid_dev[1].nbytes)
    return total


def _segment_ledgered_bytes(seg):
    prefixes = tuple(f"ds:{id(ds)}:" for ds in
                     seg._data_sources.values())
    prefixes += (f"seg:{id(seg)}:",)
    snap = LEDGER.snapshot(max_entries=1_000_000)
    return sum(e["bytes"] for e in snap["entries"]
               if e["owner"].startswith(prefixes))


def test_warm_device_ledger_matches_actual_lane_bytes(tmp_path):
    from fixtures import build_segment
    seg, _cols = build_segment(str(tmp_path), n=2000, seed=3)
    try:
        seg.warm_device()
        actual = _segment_device_bytes(seg)
        ledgered = _segment_ledgered_bytes(seg)
        assert actual > 0
        # acceptance bar is 5%; the ledger is registered AT the upload
        # choke point so in practice the match is exact
        assert abs(ledgered - actual) <= 0.05 * actual, \
            (ledgered, actual)
        assert ledgered == actual
    finally:
        seg.destroy()
    assert _segment_ledgered_bytes(seg) == 0


def test_destroy_releases_every_ledgered_lane(tmp_path):
    from fixtures import build_segment
    seg, _cols = build_segment(str(tmp_path), n=1000, seed=5)
    seg.warm_device()
    assert _segment_ledgered_bytes(seg) > 0
    before = LEDGER.total_bytes()
    released = _segment_device_bytes(seg)
    seg.destroy()
    assert _segment_ledgered_bytes(seg) == 0
    assert LEDGER.total_bytes() == before - released


# ---------------------------------------------------------------------------
# exchange budget regression: publish -> overflow -> sweep -> zero
# ---------------------------------------------------------------------------


def _xchg_ledger_bytes(mgr):
    snap = LEDGER.snapshot(max_entries=1_000_000)
    return sum(e["bytes"] for e in snap["entries"]
               if e["owner"].startswith(f"xchg:{mgr.xkey}:"))


def test_exchange_budget_credit_overflow_and_ttl_sweep():
    """The full budget lifecycle the protocol model checks, executed
    for real: a typed overflow reject leaves the books untouched, a
    replace-put is judged against the budget it will actually occupy
    (credit-before-compare), and a ledger scrape sweeps the expired
    entry to quiescent zero without any put/get running."""
    from pinot_tpu.query.stages.errors import ExchangeError
    from pinot_tpu.query.stages.exchange import ExchangeManager
    t = [0.0]
    mgr = ExchangeManager(ttl_s=10.0, max_bytes=100,
                          clock=lambda: t[0])
    try:
        mgr.put("x", b"a" * 60)
        assert mgr.held_bytes() == 60
        assert _xchg_ledger_bytes(mgr) == 60
        # oversized publish: typed reject, books unchanged
        with pytest.raises(ExchangeError):
            mgr.put("y", b"b" * 50)
        assert mgr.held_bytes() == 60
        assert _xchg_ledger_bytes(mgr) == 60
        # replace-put: 90 > 100-60 gross, but the 60 it replaces is
        # credited before the compare — must be admitted
        mgr.put("x", b"c" * 90)
        assert mgr.held_bytes() == 90
        assert _xchg_ledger_bytes(mgr) == 90
        # replace-put over the REAL budget still rejects typed
        with pytest.raises(ExchangeError):
            mgr.put("x", b"d" * 101)
        assert mgr.held_bytes() == 90
        assert mgr.get("x") == b"c" * 90
        # expire, then observe via the ledger scrape ONLY: the sweeper
        # hook must bring held bytes to zero at quiescence
        t[0] = 1000.0
        assert LEDGER.kind_bytes("exchange") >= 0   # scrape sweeps
        assert mgr.held_bytes() == 0
        assert _xchg_ledger_bytes(mgr) == 0
        assert mgr.get("x") is None
    finally:
        mgr.close()
    assert _xchg_ledger_bytes(mgr) == 0


def test_exchange_close_releases_ledger_entries():
    from pinot_tpu.query.stages.exchange import ExchangeManager
    mgr = ExchangeManager(ttl_s=60.0, max_bytes=1000)
    mgr.put("a", b"x" * 10)
    mgr.put("b", b"y" * 20)
    assert _xchg_ledger_bytes(mgr) == 30
    mgr.close()
    assert _xchg_ledger_bytes(mgr) == 0


# ---------------------------------------------------------------------------
# device-ledger rule fixtures
# ---------------------------------------------------------------------------


_UNLEDGERED = '''
import jax
import jax.numpy as jnp

def upload(host):
    return jnp.asarray(host)

def place(host, sharding):
    return jax.device_put(host, sharding)
'''


def test_unledgered_uploads_flagged():
    found = lifecycle_findings(_UNLEDGERED, rule="device-ledger")
    assert len(found) == 2
    assert all("unledgered device upload" in f.message for f in found)


def test_jit_scope_uploads_exempt():
    src = '''
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

@jax.jit
def kernel(x):
    return jnp.asarray(x) + 1

def sharded(host, mesh, specs):
    def fn(x):
        return jnp.asarray(x)
    return jax.jit(shard_map(fn, mesh, in_specs=specs,
                             out_specs=specs))(host)
'''
    assert lifecycle_findings(src, rule="device-ledger") == []


def test_ledgered_choke_points_pass():
    src = '''
from pinot_tpu.obs import residency

def upload(host):
    return residency.ledgered_asarray(
        host, owner="o", table="t", segment="s", kind="scan")

def place(host, sharding):
    return residency.ledgered_put(
        host, owner="o", table="t", segment="s", kind="scan",
        sharding=sharding)
'''
    assert lifecycle_findings(src, rule="device-ledger") == []


def test_device_ledger_scoped_to_serving_path():
    # a datagen/tool upload is not resident serving state
    assert lifecycle_findings(_UNLEDGERED, path=PLAIN_PATH,
                              rule="device-ledger") == []


def test_lifecycle_tier_is_opt_in():
    # the default fast tier must not run lifecycle rules
    res = analyze_source(_UNLEDGERED, SERVING_PATH)
    assert [f for f in res.findings
            if f.rule in ("device-ledger", "cache-bound")] == []


# ---------------------------------------------------------------------------
# cache-bound rule fixtures
# ---------------------------------------------------------------------------


_UNBOUNDED_CACHES = '''
class Planner:
    def __init__(self):
        self._plans = {}
        self._stats: dict = {}

    def plan(self, key):
        cached = self._plans.get(key)
        if cached is None:
            cached = self._plans[key] = object()
        return cached

    def stat(self, key):
        if key in self._stats:
            return self._stats[key]
        self._stats[key] = 1
        return 1

_GLOBAL_CACHE = {}

def lookup(key):
    if key not in _GLOBAL_CACHE:
        _GLOBAL_CACHE[key] = key
    return _GLOBAL_CACHE[key]
'''


def test_unbounded_memoization_flagged():
    found = lifecycle_findings(_UNBOUNDED_CACHES, rule="cache-bound")
    msgs = " ".join(f.message for f in found)
    assert len(found) == 3, found
    assert "_plans" in msgs and "_stats" in msgs and \
        "_GLOBAL_CACHE" in msgs


def test_structural_bounds_pass():
    src = '''
import collections

class Bounded:
    def __init__(self):
        self._lru = {}
        self._ring = collections.deque(maxlen=64)
        self._gen = {}
        self._capped = {}

    def get(self, key):
        v = self._lru.get(key)
        if v is None:
            v = self._lru[key] = object()
            if len(self._lru) > 128:
                self._lru.pop(next(iter(self._lru)))
        return v

    def push(self, item):
        if item in self._ring:
            return
        self._ring.append(item)

    def swap(self, key):
        if key not in self._gen:
            self._gen[key] = 1
        self._gen = {}

    def add(self, key):
        self._capped.setdefault(key, 0)
        del self._capped[key]
'''
    assert lifecycle_findings(src, rule="cache-bound") == []


def test_cache_bound_suppression_states_invariant():
    src = '''
_CONNS = {}  # tpulint: disable=cache-bound -- bounded by cluster membership

def conn(key):
    c = _CONNS.get(key)
    if c is None:
        c = _CONNS[key] = object()
    return c
'''
    res = analyze_source(src, SERVING_PATH,
                         tiers=("ast", "lifecycle"))
    assert [f for f in res.findings if f.rule == "cache-bound"] == []
    assert any(f.rule == "cache-bound" for f in res.suppressed)


# ---------------------------------------------------------------------------
# live tree: the lifecycle tier is clean (zero findings, the stated
# extrinsic bounds all suppressed inline)
# ---------------------------------------------------------------------------


def test_live_tree_lifecycle_clean(monkeypatch):
    monkeypatch.chdir(REPO_ROOT)
    result = analyze_paths(["pinot_tpu"], lifecycle=True)
    lifecycle = [f for f in result.findings
                 if f.rule in ("device-ledger", "cache-bound")]
    assert lifecycle == [], [(f.path, f.line, f.message)
                             for f in lifecycle]
    assert "lifecycle" in result.timings
