"""Exchange plane: stage-1 result blocks shipped server↔server.

A stage-1 producer executes a normal scan and PUBLISHES the serialized
DataTable into its ExchangeManager under a broker-assigned exchange id
(the reply to the broker is a small ack). Stage-2 consumers fetch peer
blocks over the SAME requestId-multiplexed TCP data plane the broker
uses (transport/tcp.py) — an ``XCHG``-tagged frame addressed to the
peer's QueryServer — so big colocated fetches automatically ride the
shared-memory reply path (transport/shm.py hello negotiation), and
same-process peers (embedded clusters) short-circuit through an
in-process registry keyed by each manager's unique ``xkey``.

Lifetime: entries are TTL-bounded (a crashed broker or abandoned query
must not leak blocks) and the manager is byte-budgeted — an oversized
publish fails loudly at stage 1 instead of silently truncating a join.

Wire format (frame payload after the 8-byte correlation id):
``XCHG`` magic + UTF-8 JSON ``{"op": "fetch", "id": <exchange id>}``.
The reply is the published DataTable bytes verbatim, or a DataTable
whose exceptions carry ``ExchangeMissError`` when the id is unknown/
expired. The frame schema is pinned by the tpulint wire-schema gate
(analysis/contracts.py "exchangeFrame").
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.obs import residency
from pinot_tpu.query.stages.errors import ExchangeError

XCHG_MAGIC = b"XCHG"

DEFAULT_TTL_S = 120.0
DEFAULT_MAX_BYTES = 256 << 20

#: process-global registry: xkey → ExchangeManager. Keys are per-manager
#: UUIDs (never instance names — several embedded clusters in one test
#: process may all run a "Server_0"), so a local fetch can only ever hit
#: the exact manager the broker's source descriptor named.
_REGISTRY: Dict[str, "ExchangeManager"] = {}
_REGISTRY_LOCK = threading.Lock()


def is_exchange_frame(payload) -> bool:
    return bytes(payload[:4]) == XCHG_MAGIC


class ExchangeManager:
    """Per-server store of published stage-1 blocks."""

    def __init__(self, ttl_s: float = DEFAULT_TTL_S,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 clock=time.monotonic):
        self.xkey = uuid.uuid4().hex
        self.ttl_s = ttl_s
        self.max_bytes = max_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self._store: Dict[str, Tuple[bytes, float]] = {}
        self._bytes = 0
        with _REGISTRY_LOCK:
            _REGISTRY[self.xkey] = self
        # residency: held blocks are device-adjacent memory a stage-2
        # join will upload; the ledger sweeps us on scrape so expired
        # entries leave the books at quiescence, not on the next put/get
        residency.LEDGER.add_sweeper(self.sweep_expired)

    def close(self) -> None:
        with _REGISTRY_LOCK:
            _REGISTRY.pop(self.xkey, None)
        residency.LEDGER.remove_sweeper(self.sweep_expired)
        with self._lock:
            self._store.clear()
            self._bytes = 0
        residency.LEDGER.release_prefix(f"xchg:{self.xkey}:")

    # -- store -------------------------------------------------------------
    def put(self, xid: str, payload: bytes,
            ttl_s: Optional[float] = None) -> None:
        """`ttl_s` caps this entry's lifetime below the manager default:
        publishers pass the query's remaining deadline budget (+slack),
        so steady-state held bytes track in-flight queries instead of
        draining only at the 120s default — sustained join traffic
        would otherwise hard-cap on TTL drain, not real concurrency."""
        now = self._clock()
        ttl = self.ttl_s if ttl_s is None else min(self.ttl_s, ttl_s)
        with self._lock:
            self._sweep(now)
            # credit a to-be-replaced entry BEFORE the overflow compare:
            # a republish of xid must be judged against the budget it
            # will actually occupy, and the typed-422 reject path must
            # leave the books exactly as they were (debit/credit pairs
            # balance — the model checker's bytes-conservation invariant)
            old = self._store.get(xid)
            held = self._bytes - (len(old[0]) if old is not None else 0)
            if held + len(payload) > self.max_bytes:
                raise ExchangeError(
                    f"exchange buffer full ({held} bytes held, "
                    f"{len(payload)} offered, cap {self.max_bytes})")
            self._store[xid] = (payload, now + max(ttl, 1.0))
            self._bytes = held + len(payload)
            residency.LEDGER.register(
                f"xchg:{self.xkey}:{xid}", table="", segment="",
                kind="exchange", nbytes=len(payload))

    def get(self, xid: str) -> Optional[bytes]:
        now = self._clock()
        with self._lock:
            self._sweep(now)
            entry = self._store.get(xid)
            return entry[0] if entry is not None else None

    def sweep_expired(self) -> int:
        """Drop every expired entry NOW; returns the bytes released.
        Without this the sweep only ran inside put/get, so a quiescent
        manager held expired blocks (and their budget) indefinitely —
        exactly the leak the exchange protocol model flags when the
        `standalone_sweep` shape is missing."""
        with self._lock:
            before = self._bytes
            self._sweep(self._clock())
            return before - self._bytes

    def held_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def _sweep(self, now: float) -> None:
        # caller holds the lock
        dead = [k for k, (_p, exp) in self._store.items() if exp <= now]
        for k in dead:
            payload, _exp = self._store.pop(k)
            self._bytes -= len(payload)
            residency.LEDGER.release(f"xchg:{self.xkey}:{k}")

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    # -- data-plane frames -------------------------------------------------
    def handle_frame(self, payload) -> bytes:
        """One XCHG frame → reply bytes (the published block, or a typed
        miss DataTable)."""
        try:
            msg = json.loads(bytes(payload[4:]).decode("utf-8"))
            op = msg.get("op")
            xid = msg.get("id")
        except (ValueError, UnicodeDecodeError):
            return _miss_reply("malformed exchange frame")
        if op != "fetch" or not isinstance(xid, str):
            return _miss_reply(f"unknown exchange op {op!r}")
        block = self.get(xid)
        if block is None:
            return _miss_reply(f"exchange id {xid!r} unknown or expired")
        return block


def fetch_frame(xid: str) -> bytes:
    return XCHG_MAGIC + json.dumps({"op": "fetch", "id": xid},
                                   separators=(",", ":")).encode("utf-8")


def _miss_reply(message: str) -> bytes:
    dt = DataTable()
    dt.exceptions.append(f"ExchangeMissError: {message}")
    return dt.to_bytes()


# ---------------------------------------------------------------------------
# Fetch client (stage-2 consumers; called from scheduler worker threads)
# ---------------------------------------------------------------------------

_CLIENT_LOCK = threading.Lock()
_CLIENT_LOOP = None
_CLIENT_CONNS: Dict[Tuple[str, int], object] = {}  # tpulint: disable=cache-bound -- one connection per (host, port) peer: bounded by cluster membership


def _client_loop():
    global _CLIENT_LOOP
    with _CLIENT_LOCK:
        if _CLIENT_LOOP is None:
            from pinot_tpu.transport.tcp import EventLoopThread
            _CLIENT_LOOP = EventLoopThread()
        return _CLIENT_LOOP


def _connection(host: str, port: int):
    key = (host, port)
    with _CLIENT_LOCK:
        conn = _CLIENT_CONNS.get(key)
        if conn is None:
            from pinot_tpu.transport.tcp import ServerConnection
            conn = _CLIENT_CONNS[key] = ServerConnection(host, port)
        return conn


def _check_block(dt: DataTable) -> DataTable:
    for exc in dt.exceptions:
        if str(exc).startswith("ExchangeMissError"):
            raise ExchangeError(str(exc))
    return dt


def _fetch_local(source: dict) -> Optional[DataTable]:
    """Registry short-circuit: the decoded block, or None when the
    source is not a same-process manager."""
    mgr = _REGISTRY.get(source.get("xkey") or "")
    if mgr is None:
        return None
    payload = mgr.get(source["id"])
    if payload is None:
        raise ExchangeError(
            f"exchange id {source['id']!r} missing on local manager "
            f"{source.get('server')}")
    return _check_block(DataTable.from_bytes(payload))


def fetch_block(source: dict, timeout_s: float) -> DataTable:
    """Fetch one published stage-1 block.

    `source`: the broker's descriptor — {"server", "xkey", "id", and
    ("host", "port") when the peer is reachable over TCP}. Same-process
    peers resolve through the registry (zero-copy local bytes); remote
    peers go over the multiplexed data plane (shm replies when
    colocated). Raises ExchangeError on miss/transport failure.
    """
    local = _fetch_local(source)
    if local is not None:
        return local
    host, port = source.get("host"), source.get("port")
    if not host or not port:
        raise ExchangeError(
            f"exchange source {source.get('server')!r} is neither "
            "local nor TCP-addressable")
    loop = _client_loop()
    conn = _connection(host, int(port))
    import asyncio
    from pinot_tpu.transport.shm import datatable_from_reply
    xid = source["id"]
    try:
        raw = loop.run(
            asyncio.wait_for(conn.request(fetch_frame(xid), timeout_s),
                             timeout_s),
            timeout=timeout_s + 5.0)
    except Exception as e:  # noqa: BLE001 — transport-class failure
        raise ExchangeError(
            f"exchange fetch from {source.get('server')} "
            f"({host}:{port}) failed: {type(e).__name__}: {e}") from e
    return _check_block(datatable_from_reply(raw))


def fetch_blocks(sources: List[dict], deadline_s: Optional[float],
                 clock=time.monotonic) -> List[DataTable]:
    """Fetch every source, in the CALLER's order (callers sort for
    determinism). Local-registry sources resolve inline; remote TCP
    fetches run CONCURRENTLY on the shared client loop — the stage-2
    critical path pays the slowest peer, not the sum of RTTs."""
    budget = 10.0 if deadline_s is None else \
        max(deadline_s - clock(), 0.05)
    out: List[Optional[DataTable]] = [None] * len(sources)
    remote: List[int] = []
    for i, src in enumerate(sources):
        local = _fetch_local(src)
        if local is not None:
            out[i] = local
        else:
            remote.append(i)
    if remote:
        import asyncio
        from pinot_tpu.transport.shm import datatable_from_reply
        loop = _client_loop()
        conns = []
        for i in remote:
            src = sources[i]
            host, port = src.get("host"), src.get("port")
            if not host or not port:
                raise ExchangeError(
                    f"exchange source {src.get('server')!r} is neither "
                    "local nor TCP-addressable")
            conns.append(_connection(host, int(port)))

        async def _gather():
            return await asyncio.gather(
                *(asyncio.wait_for(
                    conn.request(fetch_frame(sources[i]["id"]), budget),
                    budget)
                  for i, conn in zip(remote, conns)),
                return_exceptions=True)

        raws = loop.run(_gather(), timeout=budget + 5.0)
        # decode (and thereby CLOSE shm replies) for every success
        # BEFORE raising on any failure — bailing on the first error
        # would leak the sibling fetches' shm segments
        first_err: Optional[ExchangeError] = None
        for i, raw in zip(remote, raws):
            if isinstance(raw, BaseException):
                if first_err is None:
                    first_err = ExchangeError(
                        f"exchange fetch from "
                        f"{sources[i].get('server')} failed: "
                        f"{type(raw).__name__}: {raw}")
                    first_err.__cause__ = raw
                continue
            try:
                out[i] = _check_block(datatable_from_reply(raw))
            except ExchangeError as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
    return out
