"""Version-portability shims over the JAX surface pinot_tpu depends on.

The engine is written against the modern JAX API; installed versions
skew in both directions (the seed shipped `jax.shard_map` call sites
onto jax 0.4.37, where the symbol lives at
`jax.experimental.shard_map.shard_map` — 33 tier-1 failures from one
name). Every version-sensitive symbol is resolved HERE, once, by
probing the installed jax with getattr — which also keeps call sites
clean under tpulint's api-compat rule: `pinot_tpu.compat.shard_map`
always resolves, whatever jax is underneath.
"""
from __future__ import annotations

import inspect

import jax

_shard_map_impl = getattr(jax, "shard_map", None)
if _shard_map_impl is None:
    # jax < 0.6: experimental spelling, `check_rep` instead of `check_vma`
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` resolved by availability.

    Accepts the modern keyword surface and translates `check_vma` to
    the pre-0.6 `check_rep` when running on the experimental impl.
    """
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if "check_vma" in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = check_vma
    else:
        kwargs["check_rep"] = check_vma
    return _shard_map_impl(f, **kwargs)
