"""Var-byte chunked raw columns (parity: VarByteChunkSingleValueWriter +
ChunkCompressorFactory): round-trip both codecs, per-chunk random access,
creator→loader→query over a raw string column, v3 container survival,
ConvertToRawIndex minion conversion of a string column.
"""
import os
import tempfile

import numpy as np
import pytest

from pinot_tpu.segment.rawchunks import (DEFLATE, PASS_THROUGH,
                                         ChunkedRawReader, write_raw_chunks)


@pytest.mark.parametrize("codec", [PASS_THROUGH, DEFLATE])
def test_round_trip_and_random_access(codec):
    base = tempfile.mkdtemp()
    vals = [f"value_{i:05d}_{'x' * (i % 17)}" for i in range(10_000)]
    write_raw_chunks(base, "c", vals, codec=codec, docs_per_chunk=1024)
    r = ChunkedRawReader.open(base, "c")
    assert r.num_docs == 10_000 and r.codec == codec
    # point lookups decompress only the needed chunk
    for doc in (0, 1, 1023, 1024, 5000, 9999):
        assert r.value(doc) == vals[doc]
    assert len(r._cache) <= 5       # bounded chunk cache
    got = r.decode_all()
    assert list(got) == vals


def test_deflate_actually_compresses():
    base = tempfile.mkdtemp()
    vals = ["the same repetitive payload"] * 50_000
    p1 = write_raw_chunks(base, "a", vals, codec=PASS_THROUGH)
    p2 = write_raw_chunks(base, "b", vals, codec=DEFLATE)
    assert os.path.getsize(p2) < os.path.getsize(p1) / 10


def test_bytes_column_round_trip():
    base = tempfile.mkdtemp()
    vals = [bytes([i % 256, (i * 7) % 256]) for i in range(3000)]
    write_raw_chunks(base, "b", vals, docs_per_chunk=512)
    r = ChunkedRawReader.open(base, "b", is_bytes=True)
    assert r.value(2999) == vals[2999]
    assert list(r.decode_all()) == vals


def test_creator_builds_and_queries_raw_string_column():
    """A STRING column configured no-dictionary goes through the chunked
    format and still answers filters/selections (host path)."""
    from fixtures import make_columns, make_schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    base = tempfile.mkdtemp()
    cfg = TableConfig("baseballStats", indexing_config=IndexingConfig(
        no_dictionary_columns=["salary", "playerName"]))
    cols = make_columns(4000, seed=5)
    d = os.path.join(base, "seg")
    SegmentCreator(make_schema(), cfg, "rawstr_0").build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    cm = seg.metadata.columns["playerName"]
    assert not cm.has_dictionary
    ds = seg.data_source("playerName")
    assert ds.raw_chunks is not None
    # point lookup against the source row
    assert ds.raw_chunks.value(123) == str(cols["playerName"][123])

    eng = QueryEngine([seg])
    target = str(cols["playerName"][0])
    exp = int(sum(1 for v in cols["playerName"] if str(v) == target))
    r = eng.query("SELECT COUNT(*) FROM baseballStats "
                  f"WHERE playerName = '{target}'")
    assert int(r.aggregation_results[0].value) == exp
    r = eng.query("SELECT playerName, runs FROM baseballStats "
                  f"WHERE playerName = '{target}' LIMIT 5")
    rows = r.selection_results.results
    assert rows and all(row[0] == target for row in rows)


def test_v3_container_keeps_chunked_raw():
    from fixtures import make_columns, make_schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    from pinot_tpu.segment.store import SegmentFormatConverter

    base = tempfile.mkdtemp()
    cfg = TableConfig("baseballStats", indexing_config=IndexingConfig(
        no_dictionary_columns=["salary", "playerName"]))
    cols = make_columns(2000, seed=6)
    d = os.path.join(base, "seg")
    SegmentCreator(make_schema(), cfg, "rawv3_0").build(cols, d)
    SegmentFormatConverter.v1_to_v3(d)
    seg = ImmutableSegmentLoader.load(d)
    ds = seg.data_source("playerName")
    assert ds.raw_chunks is not None
    assert ds.raw_chunks.value(1999) == str(cols["playerName"][1999])


def test_minion_converts_string_column_to_raw():
    """ConvertToRawIndexTask on a STRING column emits the chunked format
    and the converted segment still answers queries."""
    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.minion.executors import ConvertToRawIndexTaskExecutor
    from pinot_tpu.minion.tasks import PinotTaskConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    base = tempfile.mkdtemp()
    cols = make_columns(3000, seed=7)
    d = os.path.join(base, "seg")
    cfg = make_table_config()
    SegmentCreator(make_schema(), cfg, "conv_0").build(cols, d)
    ex = ConvertToRawIndexTaskExecutor()
    task = PinotTaskConfig(task_type=ex.task_type,
                           configs={"columnsToConvert": "teamID"})
    res = ex.execute(task, make_schema(), cfg, [d],
                     os.path.join(base, "out"), None)
    seg = ImmutableSegmentLoader.load(res.out_dir)
    assert not seg.metadata.columns["teamID"].has_dictionary
    assert seg.data_source("teamID").raw_chunks is not None
    eng = QueryEngine([seg])
    exp = int((cols["teamID"] == "BOS").sum())
    r = eng.query("SELECT COUNT(*) FROM baseballStats "
                  "WHERE teamID = 'BOS'")
    assert int(r.aggregation_results[0].value) == exp


def test_raw_string_selection_orderby_regexp():
    """Review regressions: selection gather, ORDER BY DESC, and
    REGEXP_LIKE over a chunked raw string column all take the host path
    and return correct rows."""
    from fixtures import make_columns, make_schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    base = tempfile.mkdtemp()
    cfg = TableConfig("baseballStats", indexing_config=IndexingConfig(
        no_dictionary_columns=["salary", "playerName"]))
    cols = make_columns(2000, seed=9)
    d = os.path.join(base, "seg")
    SegmentCreator(make_schema(), cfg, "rawsel_0").build(cols, d)
    eng = QueryEngine([ImmutableSegmentLoader.load(d)])

    r = eng.query("SELECT playerName FROM baseballStats LIMIT 5")
    assert len(r.selection_results.results) == 5

    r = eng.query("SELECT playerName FROM baseballStats "
                  "ORDER BY playerName DESC LIMIT 3")
    got = [row[0] for row in r.selection_results.results]
    exp = sorted((str(v) for v in cols["playerName"]), reverse=True)[:3]
    assert got == exp

    import re
    pat = "player_0[0-4].*"
    exp_n = sum(1 for v in cols["playerName"]
                if re.search(pat, str(v)))
    r = eng.query("SELECT COUNT(*) FROM baseballStats "
                  f"WHERE REGEXP_LIKE(playerName, '{pat}')")
    assert int(r.aggregation_results[0].value) == exp_n


def test_size_accounting_does_not_decode_chunks():
    from fixtures import make_columns, make_schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import (ImmutableSegmentLoader,
                                          segment_host_bytes)

    base = tempfile.mkdtemp()
    cfg = TableConfig("baseballStats", indexing_config=IndexingConfig(
        no_dictionary_columns=["salary", "playerName"]))
    d = os.path.join(base, "seg")
    SegmentCreator(make_schema(), cfg, "sz_0").build(
        make_columns(2000, seed=10), d)
    seg = ImmutableSegmentLoader.load(d)
    assert segment_host_bytes(seg) > 0
    # the size walk must NOT have materialized the chunked column
    assert seg.data_source("playerName")._raw_values is None
    seg.warm_device()     # no device lane for the raw string column
    assert seg.data_source("playerName")._raw_values is None
