"""Bitmap inverted index.

Parity: pinot-core/.../segment/creator/impl/inv/OffHeapBitmapInvertedIndexCreator
and index/readers/BitmapInvertedIndexReader.java (RoaringBitmap postings).

TPU-first representation: postings are stored CSR-style (sorted docIds per
dictId + offsets) — the moral equivalent of roaring's array containers — and
materialized on device either as
  (a) per-value doc-id lists for gather-style set ops, or
  (b) dense uint32 bit words for bitmap AND/OR kernels (only for the values a
      query actually touches, so the dense blow-up is bounded by the predicate,
      not the cardinality).
Counts for EQ/IN with no other predicate come straight from the offsets diff —
no device work at all.
"""
from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from pinot_tpu.segment import format as fmt


def build_inverted_csr(entry_ids: np.ndarray, doc_of_entry: np.ndarray,
                       cardinality: int):
    """CSR postings from (dictId, docId) pairs — one pair per SV doc,
    one per MV entry. Returns (docids int32, offsets int64)."""
    order = np.argsort(entry_ids, kind="stable")
    offsets = np.searchsorted(entry_ids[order],
                              np.arange(cardinality + 1)).astype(np.int64)
    return doc_of_entry[order].astype(np.int32), offsets


class InvertedIndexWriter:
    @staticmethod
    def write(seg_dir: str, col: str, ids: np.ndarray, cardinality: int) -> None:
        docids, offsets = build_inverted_csr(
            ids, np.arange(len(ids)), cardinality)
        np.save(os.path.join(seg_dir, fmt.INV_DOCIDS.format(col=col)),
                docids)
        np.save(os.path.join(seg_dir, fmt.INV_OFFSETS.format(col=col)),
                offsets)


class InvertedIndexReader:
    """CSR postings: docids[offsets[v]:offsets[v+1]] = sorted docs with value v."""

    def __init__(self, docids: np.ndarray, offsets: np.ndarray, num_docs: int):
        self.docids = docids
        self.offsets = offsets
        self.num_docs = num_docs

    @classmethod
    def load(cls, seg_dir, col: str, num_docs: int) -> "InvertedIndexReader":
        d = fmt.open_dir(seg_dir)
        docids = np.asarray(d.load_array(fmt.INV_DOCIDS.format(col=col)))
        offsets = np.asarray(d.load_array(fmt.INV_OFFSETS.format(col=col)))
        return cls(docids, offsets, num_docs)

    def postings(self, dict_id: int) -> np.ndarray:
        return self.docids[self.offsets[dict_id]:self.offsets[dict_id + 1]]

    def count(self, dict_id: int) -> int:
        return int(self.offsets[dict_id + 1] - self.offsets[dict_id])

    def count_range(self, lo: int, hi: int) -> int:
        """Total postings for dictIds in [lo, hi) — O(1) from offsets."""
        return int(self.offsets[hi] - self.offsets[lo])

    def bitmap_words(self, dict_ids: np.ndarray) -> np.ndarray:
        """OR of postings for the given dictIds as dense uint32 bit words.

        This is the host-side prep for the device bitmap AND/OR kernel: one
        row of packed words per queried value set.
        """
        n_words = (self.num_docs + 31) // 32
        words = np.zeros(n_words, dtype=np.uint32)
        for v in np.asarray(dict_ids).ravel():
            docs = self.postings(int(v))
            np.bitwise_or.at(words, docs // 32,
                             (np.uint32(1) << (docs % 32).astype(np.uint32)))
        return words


def bitmap_to_mask(words: np.ndarray, num_docs: int) -> np.ndarray:
    """uint32 bit words → bool[num_docs] (host-side reference impl)."""
    bits = ((words[:, None] >> np.arange(32, dtype=np.uint32)) & 1).astype(bool)
    return bits.reshape(-1)[:num_docs]
