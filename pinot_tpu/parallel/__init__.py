from pinot_tpu.parallel.sharded import (NotShardable, ShardedQueryExecutor,
                                        StackedSegments, make_mesh)

__all__ = ["NotShardable", "ShardedQueryExecutor", "StackedSegments",
           "make_mesh"]
