"""Mesh-sharded multi-segment query execution (segment data parallelism).

Parity: the reference's two combine layers — CombineOperator /
CombineGroupByOperator (pinot-core/.../operator/CombineOperator.java:27,
CombineGroupByOperator.java:107-156: per-segment plans on an ExecutorService,
merged into a shared ConcurrentHashMap) and the broker's scatter-gather
(SURVEY.md §2.18 #1/#2) — rebuilt the TPU way:

- Homogeneous segments (same schema, same padded doc count, shared
  dictionaries) are stacked onto a leading `seg` axis and sharded over a
  `jax.sharding.Mesh` with `shard_map`.
- Each device vmaps the single-segment kernel over its local shard, reduces
  locally, then combines across devices with XLA collectives over ICI:
  `psum` for counts/sums/histograms/group tables, `pmin`/`pmax` for id- or
  value-domain extrema, `all_gather` for selection lanes.
- Cross-segment combine in the dictId domain is only sound when dictionaries
  are shared; the stacker verifies that per column and raises `NotShardable`
  otherwise so callers fall back to per-segment execution + host merge (the
  same answer, just without ICI riding).

One jitted shard_map executable serves every query with the same static spec
(shapes pow2-bucketed), mirroring the single-segment plan cache.
"""
from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.query import combine as combine_mod
from pinot_tpu.query import execution
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.query.plan import InstancePlanMaker, SegmentPlan
from pinot_tpu.segment.loader import ImmutableSegment

SEG_AXIS = "seg"


class NotShardable(Exception):
    """Segments are not homogeneous enough for id-domain device combine."""


def make_mesh(devices: Optional[Sequence] = None,
              axis: str = SEG_AXIS) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


# ---------------------------------------------------------------------------
# Cross-segment combine rules, keyed by output name
# ---------------------------------------------------------------------------


def _combine_kind(key: str) -> str:
    if key.startswith("sel."):
        return "stack"          # per-segment; host merges selection rows
    if key.endswith((".parts", ".vsum", ".psums", ".csums")):
        return "stack"          # chunk partials: host combines in int64/f64
    if key.endswith((".rkeys", ".rcount", ".rpsums", ".rsum", ".rmin",
                     ".rmax")):
        return "stack"          # ranked group tables: per-segment rank
        #                         spaces; host merges by group key
    if key.endswith(".min"):
        return "min"
    if key.endswith(".max"):
        return "max"
    return "sum"                # counts, histograms, group tables


@functools.lru_cache(maxsize=256)
def get_sharded_kernel(mesh: Mesh, padded: int, filter_spec, agg_specs,
                       group_spec, select_spec, lane_keys: Tuple[str, ...]):
    """Jitted shard_map over the per-segment kernel with device combine.

    `lane_keys` is the static set of column-lane names; `.vals` lanes
    (shared dictionary value tables) are replicated, everything else is
    sharded over the `seg` axis.
    """
    from pinot_tpu.ops.kernels import build_segment_kernel
    kern = build_segment_kernel(padded, filter_spec, agg_specs, group_spec,
                                select_spec)
    col_specs = {k: P() if k.endswith(".vals") else P(SEG_AXIS)
                 for k in lane_keys}
    col_axes = {k: None if k.endswith(".vals") else 0 for k in lane_keys}

    def local(cols, params, num_docs):
        # cols leaves: [S_local, ...] (vals replicated); num_docs [S_local]
        outs = jax.vmap(lambda c, n: kern(c, params, n),
                        in_axes=(col_axes, 0))(cols, num_docs)
        combined = {}
        # per-segment matched counts (for numSegmentsMatched parity with
        # the sequential path), gathered alongside the global reduction
        per_seg = outs["stats.num_docs_matched"]
        combined["stats.seg_matched"] = jax.lax.all_gather(
            per_seg, SEG_AXIS).reshape(-1)
        for k, v in outs.items():
            kind = _combine_kind(k)
            if k.endswith(".cpsums"):
                # compacted int part sums: a straight int32 psum could
                # overflow past ~16.9M matched rows in one group, so split
                # each segment's table into 16-bit halves (each half's
                # cross-segment sum stays far inside int32) and let the
                # host recombine in int64
                flat = v.reshape((-1,) + v.shape[-2:])  # [S(*chunks), P, G]
                lo = (flat & 0xFFFF).sum(axis=0)
                hi = ((flat >> 16) & 0xFFFF).sum(axis=0)
                combined[f"{k}.lo"] = jax.lax.psum(lo, SEG_AXIS)
                combined[f"{k}.hi"] = jax.lax.psum(hi, SEG_AXIS)
                continue
            if kind == "sum":
                combined[k] = jax.lax.psum(v.sum(axis=0), SEG_AXIS)
            elif kind == "min":
                combined[k] = jax.lax.pmin(v.min(axis=0), SEG_AXIS)
            elif kind == "max":
                combined[k] = jax.lax.pmax(v.max(axis=0), SEG_AXIS)
            else:  # stack: gather all segments' lanes, restore global order
                g = jax.lax.all_gather(v, SEG_AXIS)      # [D, S_local, ...]
                combined[k] = g.reshape((-1,) + v.shape[1:])
        return combined

    # check_vma=False: outputs are replicated by construction (psum/pmin/
    # pmax/all_gather), but the static varying-axis check can't prove it
    # for the all_gather'd selection lanes.
    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(col_specs, P(), P(SEG_AXIS)),
                       out_specs=P(), check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Segment stacking
# ---------------------------------------------------------------------------


class StackedSegments:
    """Host-stacks homogeneous segments and caches sharded device arrays.

    The TPU-native replacement for the reference's per-segment mmap residency
    (PinotDataBuffer): column lanes live HBM-resident, sharded across the
    mesh, uploaded once and reused by every query.
    """

    def __init__(self, segments: Sequence[ImmutableSegment], mesh: Mesh):
        self.segments = list(segments)
        self.mesh = mesh
        n_dev = mesh.devices.size
        if not self.segments:
            raise NotShardable("no segments")
        if any(getattr(s, "is_mutable", False) for s in self.segments):
            raise NotShardable("mutable (consuming) segment in set")
        pads = {s.padded_docs for s in self.segments}
        if len(pads) != 1:
            raise NotShardable(f"padded doc counts differ: {sorted(pads)}")
        self.padded_docs = pads.pop()
        # pad segment count up to a mesh multiple with empty dummies
        self.n_real = len(self.segments)
        self.n_total = -(-self.n_real // n_dev) * n_dev
        self.num_docs = np.zeros(self.n_total, np.int32)
        self.num_docs[: self.n_real] = [s.num_docs for s in self.segments]
        self._dev_num_docs = None
        self._lanes: Dict[Tuple[str, str], object] = {}
        self._dict_checked: Dict[str, bool] = {}

    def _check_shared_dictionary(self, col: str) -> None:
        ok = self._dict_checked.get(col)
        if ok is None:
            d0 = self.segments[0].data_source(col).dictionary
            ok = all(
                np.array_equal(s.data_source(col).dictionary.values,
                               d0.values)
                for s in self.segments[1:])
            self._dict_checked[col] = ok
        if not ok:
            raise NotShardable(f"column '{col}' dictionaries differ across "
                               "segments (id-domain combine unsound)")

    def device_num_docs(self):
        if self._dev_num_docs is None:
            self._dev_num_docs = jax.device_put(
                self.num_docs, NamedSharding(self.mesh, P(SEG_AXIS)))
        return self._dev_num_docs

    def lane(self, col: str, kind: str):
        """Sharded [n_total, ...] device array for one column lane."""
        key = (col, kind)
        if key in self._lanes:
            return self._lanes[key]
        if kind in ("ids", "mv", "vals", "parts", "vlane"):
            self._check_shared_dictionary(col)
        arrs = [s.data_source(col).host_operand(kind) for s in self.segments]
        if kind == "vals":
            # dictionary values are identical; replicate instead of sharding
            out = jax.device_put(arrs[0], NamedSharding(self.mesh, P()))
            self._lanes[key] = out
            return out
        if kind == "mv":
            w = max(a.shape[1] for a in arrs)
            card = self.segments[0].data_source(col).metadata.cardinality
            arrs = [np.pad(a, ((0, 0), (0, w - a.shape[1])),
                           constant_values=card) for a in arrs]
        shapes = {a.shape for a in arrs}
        if len(shapes) != 1:
            raise NotShardable(f"column '{col}' lane shapes differ: {shapes}")
        stacked = np.stack(arrs)
        if self.n_total > self.n_real:
            pad_val = stacked.flat[0] * 0
            if kind in ("ids", "mv"):
                pad_val = self.segments[0].data_source(col).metadata.cardinality
            filler = np.full((self.n_total - self.n_real,) + stacked.shape[1:],
                             pad_val, stacked.dtype)
            stacked = np.concatenate([stacked, filler])
        out = jax.device_put(stacked, NamedSharding(self.mesh, P(SEG_AXIS)))
        self._lanes[key] = out
        return out

    def gather(self, needed_cols) -> Dict[str, object]:
        # lane keys are "<col>.<kind>" — the same names the kernels read
        return {f"{col}.{kind}": self.lane(col, kind)
                for col, kind in needed_cols}


# ---------------------------------------------------------------------------
# Sharded executor
# ---------------------------------------------------------------------------


class ShardedQueryExecutor:
    """Executes one BrokerRequest across all segments on a device mesh.

    Plans once against segment 0 (homogeneity is verified by the stacker),
    runs the sharded kernel, and finishes results host-side with the same
    code the single-segment path uses (shared dictionaries make segment 0's
    decode tables valid for the combined partials).
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 plan_maker: Optional[InstancePlanMaker] = None,
                 max_stacks: int = 4):
        self.mesh = mesh or make_mesh()
        self.plan_maker = plan_maker or InstancePlanMaker()
        # Bounded LRU keyed on the canonical (sorted) name tuple: with
        # randomized routing each server sees many orderings/subsets of the
        # same segment set; sorting collapses orderings to one stack and the
        # LRU bound caps HBM duplication across subsets. A hit additionally
        # requires segment object identity so a refreshed segment (same
        # name, new object) rebuilds instead of serving stale lanes.
        self.max_stacks = max_stacks
        self._stacks: "collections.OrderedDict[Tuple[str, ...], StackedSegments]" = \
            collections.OrderedDict()
        # Queries run on scheduler worker threads while evict_segment fires
        # from segment-transition threads; the lock guards the OrderedDict
        # and the generation counter closes the build/evict race (a stack
        # built concurrently with an eviction is served but never cached).
        self._lock = threading.Lock()
        self._evict_gen = 0

    def stack_for(self, segments: Sequence[ImmutableSegment]
                  ) -> StackedSegments:
        ordered = sorted(segments, key=lambda s: s.segment_name)
        key = tuple(s.segment_name for s in ordered)
        with self._lock:
            st = self._stacks.get(key)
            if st is not None and len(st.segments) == len(ordered) and \
                    all(a is b for a, b in zip(st.segments, ordered)):
                self._stacks.move_to_end(key)
                return st
            gen = self._evict_gen
        st = StackedSegments(ordered, self.mesh)
        with self._lock:
            if self._evict_gen == gen:
                self._stacks[key] = st
                self._stacks.move_to_end(key)
                while len(self._stacks) > self.max_stacks:
                    self._stacks.popitem(last=False)
        return st

    def evict_segment(self, segment_name: str) -> None:
        """Drop every cached stack containing `segment_name`.

        Wired as a segment-removal listener by the server data manager so a
        refreshed/deleted segment's HBM lanes are released promptly instead
        of lingering until LRU pressure.
        """
        with self._lock:
            self._evict_gen += 1
            for key in [k for k in self._stacks if segment_name in k]:
                del self._stacks[key]

    def execute(self, request: BrokerRequest,
                segments: Sequence[ImmutableSegment]
                ) -> IntermediateResultsBlock:
        t0 = time.perf_counter()
        stack = self.stack_for(segments)
        seg0 = stack.segments[0]
        # Plan is built against segment 0 and reused for every segment, so
        # EVERY dictionary-encoded column the request references must have a
        # shared dictionary — not just the ones that survive constant
        # folding (a predicate folded to MATCH_ALL/EMPTY against segment
        # 0's dictionary never reaches needed_cols, but would fold
        # differently on a segment with a different dictionary).
        for col in request.referenced_columns():
            if seg0.has_column(col) and \
                    seg0.data_source(col).metadata.has_dictionary:
                stack._check_shared_dictionary(col)
        if request.is_group_by:
            # raw group keys bin by segment 0's min/max — every segment
            # must share that range or rows would clip into wrong bins
            for col in request.group_by.columns:
                if not seg0.has_column(col):
                    continue
                cm0 = seg0.data_source(col).metadata
                if cm0.has_dictionary:
                    continue
                for s in stack.segments[1:]:
                    cm = s.data_source(col).metadata
                    if (cm.min_value, cm.max_value) != (cm0.min_value,
                                                        cm0.max_value):
                        raise NotShardable(
                            f"raw group column '{col}' min/max differ "
                            "across segments")
        plan = self.plan_maker.make_segment_plan(seg0, request)
        if plan.fast_path_result is not None:
            # metadata fast paths are per-segment host work; take the
            # sequential path for those (they're O(1) per segment anyway)
            raise NotShardable("fast-path plan; no device work to shard")

        cols = stack.gather(plan.needed_cols)
        lane_keys = tuple(sorted(cols.keys()))

        def run(agg_specs, group_spec, extra_params=()):
            fn = get_sharded_kernel(
                self.mesh, stack.padded_docs, plan.filter_spec,
                tuple(agg_specs or ()), group_spec, plan.select_spec,
                lane_keys)
            return jax.device_get(fn(
                cols, tuple(plan.params) + tuple(extra_params),
                stack.device_num_docs()))

        from pinot_tpu.query.plan import (drive_group_execution,
                                          set_group_kmax)
        blk = IntermediateResultsBlock()
        if plan.group_spec is not None:
            spec0 = set_group_kmax(plan.group_spec, stack.padded_docs)
            outs, spec_used = drive_group_execution(
                run, spec0, stack.padded_docs, int(stack.num_docs.sum()))
            if spec_used is None:
                blk.group_map = {}
            else:
                execution._finish_group_by(
                    execution._with_group_spec(plan, spec_used), outs, blk)
        else:
            outs = run(plan.agg_specs, None, ())
            if plan.agg_specs:
                execution._finish_aggregation(plan, outs, blk)
        matched = int(outs["stats.num_docs_matched"])
        if plan.select_spec is not None:
            self._finish_selection(request, plan, stack, outs, blk)

        n_leaves = execution._count_filter_leaves(plan.filter_spec)
        n_project = len({c for c, _ in plan.needed_cols})
        total_docs = int(stack.num_docs.sum())
        seg_matched = np.asarray(outs["stats.seg_matched"])[: stack.n_real]
        blk.stats = ExecutionStats(
            num_docs_scanned=matched,
            num_entries_scanned_in_filter=n_leaves * total_docs,
            num_entries_scanned_post_filter=matched * max(
                n_project - n_leaves, 0),
            num_segments_processed=stack.n_real,
            num_segments_matched=int((seg_matched > 0).sum()),
            total_docs=total_docs,
            time_used_ms=(time.perf_counter() - t0) * 1e3)
        return blk

    def _finish_selection(self, request, plan, stack, outs, blk) -> None:
        """Per-segment selection finish + host top-k merge.

        Parity: CombineService selection merge — each segment returns its
        own (already ordered/limited) rows; the combiner re-sorts and trims.
        """
        rows_all: List[tuple] = []
        columns = None
        seg_matched = np.asarray(outs["stats.seg_matched"])
        for i, seg in enumerate(stack.segments):
            sub = {k: v[i] for k, v in outs.items() if k.startswith("sel.")}
            seg_plan = SegmentPlan(
                segment=seg, request=request,
                select_spec=plan.select_spec, needed_cols=plan.needed_cols,
                select_display=plan.select_display)
            seg_blk = IntermediateResultsBlock()
            execution._finish_selection(seg_plan, sub, seg_blk,
                                        int(seg_matched[i]))
            columns = seg_blk.selection_columns
            if rows_all and seg_blk.selection_rows:
                # merge_selection_rows re-sorts (when ordered) and trims to
                # offset+size — the limit is enforced here
                rows_all = combine_mod.merge_selection_rows(
                    request, columns, rows_all, seg_blk.selection_rows)
            elif seg_blk.selection_rows:
                rows_all = seg_blk.selection_rows
        sel = request.selection
        blk.selection_rows = rows_all[: sel.offset + sel.size]
        blk.selection_columns = columns
        blk.selection_display_cols = plan.select_display
