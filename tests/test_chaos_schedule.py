"""Deterministic chaos plane + SLO/leak gate units (ISSUE 19).

Tiers:
1. **Determinism** — same seed + schedule + fake clock + adapter ⇒
   byte-identical ``timeline_json``; a different seed changes the
   seeded target choice.
2. **Windows on a fake clock** — a ``duration_s`` event arms at
   ``at_s`` and disarms via ``clear_fault`` at ``at_s + duration_s``;
   never before.
3. **Recovery tracking** — the adapter's probe resolving inside the
   deadline records ``recovered`` with the measured recovery time;
   a probe that never resolves records ``recovery_deadline_violated``
   (and ``violations()`` reports it exactly once).
4. **Coordinator robustness** — adapter verbs that raise become
   timeline ``error`` entries, empty target pools become ``skipped``
   entries, and the run completes either way.
5. **Leak-flatness detector** (obs/slo.py GaugeSeries) — flat stays
   flat, linear growth trips, a step inside the settle window (churn
   settling) passes, insufficient samples defaults to flat.
6. **Response classifier** (obs/slo.py + common/response.py) — the
   flagged-vs-unflagged split over BrokerResponse exception entries,
   and the prefix → errorCode/cause table the broker's degraded paths
   rely on.
"""
import json

import pytest

from pinot_tpu.common.chaos import (ChaosCoordinator, ChaosEvent,
                                    coerce_schedule)
from pinot_tpu.common.response import (classify_exception,
                                       exception_entry)
from pinot_tpu.obs.slo import GaugeSeries, SLOTracker, classify_response


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeCluster:
    """Adapter double: records verb calls, serves configurable target
    pools and probes."""

    def __init__(self, servers=("Server_0", "Server_1", "Server_2"),
                 probe_results=None, raise_on=()):
        self.servers = list(servers)
        self.calls = []
        self.cleared = []
        self.probe_results = dict(probe_results or {})
        self.raise_on = set(raise_on)

    def targets(self, kind):
        if kind in ("kill_server", "drain_server", "net_latency",
                    "net_drop"):
            return list(self.servers)
        return []

    def _verb(self, kind, target, **params):
        if kind in self.raise_on:
            raise RuntimeError(f"boom in {kind}")
        self.calls.append((kind, target, params))
        return target

    def __getattr__(self, name):
        if name.startswith(("kill_", "drain_", "fail_", "start_",
                            "net_")):
            return lambda target=None, **p: self._verb(name, target, **p)
        raise AttributeError(name)

    def clear_fault(self, target):
        self.cleared.append(target)

    def recovery_probe(self, event, target):
        result = self.probe_results.get(event.kind)
        if result is None:
            return None
        return result


def drive(coordinator, clock, until_s, dt=0.25):
    while clock.t < until_s:
        clock.advance(dt)
        coordinator.step()


# -- tier 1: determinism ------------------------------------------------------

SCHEDULE = [
    {"at_s": 1.0, "kind": "net_latency", "duration_s": 2.0,
     "params": {"latency_s": 0.1}},
    {"at_s": 3.0, "kind": "kill_server", "recovery_deadline_s": 5.0},
    {"at_s": 6.0, "kind": "drain_server", "target": "Server_1"},
]


def run_once(seed):
    clock = FakeClock()
    recovered = {"n": 0}

    def probe():
        recovered["n"] += 1
        return recovered["n"] >= 3      # recovers on the third poll

    cluster = FakeCluster(probe_results={"kill_server": probe})
    coord = ChaosCoordinator(cluster, SCHEDULE, seed=seed, clock=clock,
                             sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 12.0)
    assert coord.done()
    return coord.timeline_json(), cluster


def test_same_seed_byte_identical_timeline():
    a, _ = run_once(seed=7)
    b, _ = run_once(seed=7)
    assert a == b


def test_different_seed_changes_seeded_target():
    targets = set()
    for seed in range(12):
        _, cluster = run_once(seed=seed)
        kills = [t for k, t, _ in cluster.calls if k == "kill_server"]
        targets.update(kills)
    assert len(targets) > 1, "seed never changed the chosen target"


def test_explicit_target_wins_over_rng():
    _, cluster = run_once(seed=3)
    drains = [t for k, t, _ in cluster.calls if k == "drain_server"]
    assert drains == ["Server_1"]


# -- tier 2: fault windows on the fake clock ---------------------------------

def test_window_arms_then_disarms_at_duration():
    clock = FakeClock()
    cluster = FakeCluster()
    coord = ChaosCoordinator(
        cluster,
        [{"at_s": 2.0, "kind": "net_latency", "duration_s": 3.0}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 1.5)
    assert not cluster.calls, "fired before at_s"
    drive(coord, clock, 4.5)
    assert [k for k, _, _ in cluster.calls] == ["net_latency"]
    assert not cluster.cleared, "disarmed before at_s + duration_s"
    drive(coord, clock, 5.5)
    target = cluster.calls[0][1]
    assert cluster.cleared == [target]
    assert coord.done()
    actions = [e["action"] for e in coord.timeline]
    assert actions == ["fired", "disarmed"]


# -- tier 3: recovery deadlines ----------------------------------------------

def test_recovery_inside_deadline_records_recovery_time():
    clock = FakeClock()
    state = {"ok": False}
    cluster = FakeCluster(
        probe_results={"kill_server": lambda: state["ok"]})
    coord = ChaosCoordinator(
        cluster,
        [{"at_s": 1.0, "kind": "kill_server", "target": "Server_0",
          "recovery_deadline_s": 10.0}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 3.0)
    assert not coord.done(), "recovery pending must keep the run open"
    state["ok"] = True
    drive(coord, clock, 3.5)
    assert coord.done()
    rec = [e for e in coord.timeline if e["action"] == "recovered"]
    assert len(rec) == 1
    assert rec[0]["recoveryS"] == pytest.approx(2.5, abs=0.3)
    assert coord.recoveries() == {"kill_server": rec[0]["recoveryS"]}
    assert coord.violations() == []


def test_recovery_deadline_violation_reported_once():
    clock = FakeClock()
    cluster = FakeCluster(
        probe_results={"kill_server": lambda: False})
    coord = ChaosCoordinator(
        cluster,
        [{"at_s": 1.0, "kind": "kill_server", "target": "Server_0",
          "recovery_deadline_s": 4.0}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 20.0)
    assert coord.done()
    viols = coord.violations()
    assert len(viols) == 1
    assert viols[0]["kind"] == "kill_server"
    assert viols[0]["deadlineS"] == 4.0
    assert not coord.report()["recoveries"]


# -- tier 4: robustness -------------------------------------------------------

def test_raising_verb_becomes_timeline_error():
    clock = FakeClock()
    cluster = FakeCluster(raise_on={"kill_server"})
    coord = ChaosCoordinator(
        cluster,
        [{"at_s": 1.0, "kind": "kill_server", "target": "Server_0"},
         {"at_s": 2.0, "kind": "drain_server", "target": "Server_1"}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 5.0)
    assert coord.done()
    errors = [e for e in coord.timeline if e["action"] == "error"]
    assert len(errors) == 1 and "boom" in errors[0]["error"]
    # the later event still fired: chaos tooling never dies mid-soak
    assert ("drain_server", "Server_1", {}) in cluster.calls


def test_empty_target_pool_skips():
    clock = FakeClock()
    cluster = FakeCluster(servers=())
    coord = ChaosCoordinator(
        cluster, [{"at_s": 1.0, "kind": "kill_server"}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 3.0)
    assert coord.done()
    assert [e["action"] for e in coord.timeline] == ["skipped"]


def test_stop_aborts_pending_work():
    clock = FakeClock()
    cluster = FakeCluster(
        probe_results={"kill_server": lambda: False})
    coord = ChaosCoordinator(
        cluster,
        [{"at_s": 1.0, "kind": "kill_server", "target": "Server_0",
          "recovery_deadline_s": 100.0},
         {"at_s": 50.0, "kind": "drain_server", "target": "Server_1"}],
        seed=0, clock=clock, sleep=lambda s: clock.advance(s))
    coord.begin()
    drive(coord, clock, 2.0)
    assert not coord.done()
    coord.stop()
    assert coord.done()
    # the not-yet-fired drain never ran
    assert all(k != "drain_server" for k, _, _ in cluster.calls)


def test_coerce_schedule_accepts_both_forms():
    evs = coerce_schedule([
        ChaosEvent(at_s=1.0, kind="kill_server"),
        {"atS": 2.0, "kind": "net_drop", "durationS": 3.0,
         "recoveryDeadlineS": 4.0, "params": {"probability": 0.5}},
    ])
    assert evs[1].at_s == 2.0 and evs[1].duration_s == 3.0
    assert evs[1].recovery_deadline_s == 4.0
    assert evs[1].params == {"probability": 0.5}


# -- tier 5: leak-flatness detector ------------------------------------------

def test_flat_series_is_flat():
    s = GaugeSeries("rss")
    for i in range(40):
        s.add(float(i), 1e9 + (1e6 if i % 2 else -1e6))   # jitter only
    v = s.verdict()
    assert v.flat, v.reason


def test_linear_growth_trips():
    s = GaugeSeries("rss", rel_tol=0.10)
    for i in range(40):
        s.add(float(i), 1e9 + i * 2e7)       # +2e7/sample ⇒ ~78% growth
    v = s.verdict()
    assert not v.flat
    assert v.projected_growth > 0


def test_step_inside_settle_window_passes():
    """Churn settling (cache fill, key-map build) lives in the first
    quarter of the window — the detector must not flag it."""
    s = GaugeSeries("keyMap", settle_frac=0.25, rel_tol=0.10)
    for i in range(40):
        s.add(float(i), 0.0 if i < 8 else 2000.0)   # step at 20%
    v = s.verdict()
    assert v.flat, v.reason


def test_step_after_settle_trips():
    s = GaugeSeries("held", settle_frac=0.25, rel_tol=0.05,
                    abs_tol=0.0)
    for i in range(40):
        s.add(float(i), 1000.0 if i < 30 else 4000.0)  # step at 75%
    v = s.verdict()
    assert not v.flat


def test_insufficient_samples_defaults_flat():
    s = GaugeSeries("x")
    s.add(0.0, 5.0)
    s.add(1.0, 500.0)
    v = s.verdict()
    assert v.flat and "insufficient" in v.reason


def test_bounded_mode_tolerates_chaos_wobble():
    """A kill -9 wipes one server's key map and the healed replica
    rebuilds it — a positive slope that is NOT a leak. Bounded mode
    passes any wobble that stays under the structural cap."""
    s = GaugeSeries("keyMap", bound=1200.0)
    for i in range(40):
        # dip to 200 mid-window (kill), rebuild toward 400 (heal)
        v = 400.0 if i < 15 else (200.0 + (i - 15) * 10.0)
        s.add(float(i), min(v, 450.0))
    v = s.verdict()
    assert v.flat, v.reason
    assert "bounded" in v.reason


def test_bounded_mode_trips_past_cap():
    """A real key-map leak grows with publish churn and crosses the
    keyspace x replicas cap; bounded mode must trip on it."""
    s = GaugeSeries("keyMap", bound=1200.0)
    for i in range(40):
        s.add(float(i), 300.0 + i * 40.0)     # churn-proportional growth
    v = s.verdict()
    assert not v.flat
    assert "cap" in v.reason


def test_bounded_mode_ignores_settle_spike():
    """A pre-settle excursion above the cap (startup backfill racing
    compaction GC) is startup, not a leak — only post-settle samples
    are judged against the bound."""
    s = GaugeSeries("keyMap", settle_frac=0.25, bound=1000.0)
    for i in range(40):
        s.add(float(i), 5000.0 if i < 8 else 800.0)   # spike at <20%
    v = s.verdict()
    assert v.flat, v.reason


# -- tier 6: flagged-vs-unflagged classifier ---------------------------------

def test_classify_exception_prefix_table():
    assert classify_exception(
        "QuotaExceededError: tenant over limit") == (429,
                                                     "quotaExceeded")
    assert classify_exception("PQLParsingError: bad token") == \
        (150, "parse")
    assert classify_exception("SomeNovelError: what") is None


def test_exception_entry_explicit_args_win():
    e = exception_entry("QueryTimeoutError: 10s", error_code=123,
                        cause="custom")
    assert e == {"message": "QueryTimeoutError: 10s", "errorCode": 123,
                 "cause": "custom"}
    e2 = exception_entry("QueryTimeoutError: 10s")
    assert e2["errorCode"] == 250 and e2["cause"] == "timeout"


def test_classify_response_ok_flagged_unflagged():
    ok, _ = classify_response({"exceptions": [],
                               "partialResponse": False})
    assert ok == "ok"
    flagged, causes = classify_response(
        {"exceptions": [{"message": "x", "errorCode": 425,
                         "cause": "exchange"}],
         "partialResponse": True})
    assert flagged == "flagged" and "exchange" in causes
    un, causes = classify_response(
        {"exceptions": [{"message": "mystery failure"}],
         "partialResponse": True})
    assert un == "unflagged" and "unclassified" in causes


def test_slo_tracker_gates():
    t = SLOTracker(p99_bounds_ms={"ssb": 100.0})
    for _ in range(50):
        t.record("ssb", 10.0, {"exceptions": [],
                               "partialResponse": False})
    assert t.violations() == []
    t.record("ssb", 10.0, {"exceptions": [{"message": "mystery"}],
                           "partialResponse": True})
    assert t.unflagged_total() == 1
    assert any("unflagged" in v.lower() for v in t.violations())
    t2 = SLOTracker(p99_bounds_ms={"ssb": 100.0})
    for _ in range(100):
        t2.record("ssb", 500.0, {"exceptions": [],
                                 "partialResponse": False})
    assert any("p99" in v for v in t2.violations())


def test_fault_wrapper_exposes_inner_endpoints():
    """Soak-surfaced regression: with the broker's data plane wrapped
    in FaultInjectingTransport, the multi-stage planner reads
    ``transport.endpoints`` to address exchange peers — the wrapper
    hiding the inner TCP map made EVERY cross-server join/window query
    fail with 'exchange source neither local nor TCP-addressable'."""
    from pinot_tpu.common.faults import FaultInjectingTransport

    class InnerTcp:
        def __init__(self):
            self.endpoints = {}

        def set_endpoint(self, server, host, port):
            self.endpoints[server] = (host, port)

    inner = InnerTcp()
    wrapped = FaultInjectingTransport(inner, seed=0)
    wrapped.set_endpoint("Server_0", "127.0.0.1", 4242)
    assert wrapped.endpoints == {"Server_0": ("127.0.0.1", 4242)}
    inner.set_endpoint("Server_1", "127.0.0.1", 4243)
    assert "Server_1" in wrapped.endpoints


def test_tracker_snapshot_shape():
    t = SLOTracker()
    t.record("join", 5.0, {"exceptions": [], "partialResponse": False})
    snap = t.snapshot()
    assert snap["join"]["count"] == 1
    assert snap["join"]["ok"] == 1
    assert json.dumps(snap)        # artifact-serializable
