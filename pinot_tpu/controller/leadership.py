"""Controller leader election on the property store.

Parity: controller/ControllerLeadershipManager.java — the reference
elects a lead controller through Helix so periodic tasks (retention,
validation, task generation) run exactly once across controllers. Here
the election is a lease record at /CONTROLLER/LEADER claimed with the
property store's atomic read-modify-write; the holder refreshes the
lease, others take over when it expires.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

LEADER_PATH = "/CONTROLLER/LEADER"
DEFAULT_LEASE_S = 10.0


class ControllerLeadershipManager:
    def __init__(self, store, instance_id: str,
                 lease_s: float = DEFAULT_LEASE_S,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.instance_id = instance_id
        self.lease_s = lease_s
        self._clock = clock
        self._listeners: List[Callable[[bool], None]] = []
        self._was_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- election ----------------------------------------------------------

    def try_acquire(self) -> bool:
        """Claim (or refresh) the lease; returns leadership state.

        The expired-lease takeover is a single compare-and-set against
        the exact record we read: two controllers racing the same
        expired lease can both pass the read check, but only one CAS
        applies — the loser observes the failure instead of blindly
        overwriting the winner's claim (a remote store's update() loop
        would have let both believe they won)."""
        for _ in range(2):
            now = self._clock()
            cur = self.store.get(LEADER_PATH)
            holder = (cur or {}).get("instance")
            expired = (cur or {}).get("leaseUntil", 0) < now
            if holder not in (None, self.instance_id) and not expired:
                # someone else holds an unexpired lease: no write, no
                # spurious watcher churn from heartbeat polls
                self._notify(False)
                return False
            rec = dict(cur or {})
            rec["instance"] = self.instance_id
            rec["leaseUntil"] = now + self.lease_s
            if self.store.cas(LEADER_PATH, cur, rec):
                self._notify(True)
                return True
            # CAS lost: someone moved the record under us — one re-read
            # settles whether the winner was us (our own refresh racing)
            # or a peer
        leader = self.is_leader()
        self._notify(leader)
        return leader

    def is_leader(self) -> bool:
        rec = self.store.get(LEADER_PATH) or {}
        return rec.get("instance") == self.instance_id and \
            rec.get("leaseUntil", 0) >= self._clock()

    def resign(self) -> None:
        def drop(rec):
            rec = dict(rec or {})
            if rec.get("instance") == self.instance_id:
                rec["instance"] = None
                rec["leaseUntil"] = 0
            return rec

        self.store.update(LEADER_PATH, drop)
        self._notify(False)

    # -- listeners (parity: onBecomeLeader/onBecomeNotLeader) --------------

    def add_listener(self, fn: Callable[[bool], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, leader: bool) -> None:
        if leader != self._was_leader:
            self._was_leader = leader
            for fn in self._listeners:
                fn(leader)

    # -- background heartbeat ---------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        interval = interval_s if interval_s is not None else \
            self.lease_s / 3

        def loop():
            while not self._stop.is_set():
                self.try_acquire()
                self._stop.wait(interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"leader-{self.instance_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.resign()
