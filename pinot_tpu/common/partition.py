"""Partition functions (parity: core/data/partition/).

Java-compatible hash semantics so data partitioned by the reference's
functions (Kafka-producer murmur2, Java String.hashCode, modulo) maps to
the same partition ids here — partition-aware routing/pruning depends on
cross-system agreement.
"""
from __future__ import annotations

from typing import Dict, List, Optional

_I32 = 0xFFFFFFFF


def _i32(x: int) -> int:
    """Wrap to Java int (signed 32-bit) semantics."""
    x &= _I32
    return x - (1 << 32) if x >= (1 << 31) else x


def murmur2(data: bytes) -> int:
    """Kafka's murmur2 (MurmurPartitionFunction.java:66-105), exact."""
    length = len(data)
    seed = 0x9747B28C
    m = 0x5BD1E995
    r = 24
    h = _i32(seed ^ length)
    length4 = length // 4
    for i in range(length4):
        i4 = i * 4
        k = (data[i4] & 0xFF) + ((data[i4 + 1] & 0xFF) << 8) + \
            ((data[i4 + 2] & 0xFF) << 16) + ((data[i4 + 3] & 0xFF) << 24)
        k = _i32(k * m)
        k = _i32(k ^ ((k & _I32) >> r))
        k = _i32(k * m)
        h = _i32(h * m)
        h = _i32(h ^ k)
    rem = length % 4
    base = length & ~3
    if rem == 3:
        h = _i32(h ^ ((data[base + 2] & 0xFF) << 16))
    if rem >= 2:
        h = _i32(h ^ ((data[base + 1] & 0xFF) << 8))
    if rem >= 1:
        h = _i32(h ^ (data[base] & 0xFF))
        h = _i32(h * m)
    h = _i32(h ^ ((h & _I32) >> 13))
    h = _i32(h * m)
    h = _i32(h ^ ((h & _I32) >> 15))
    return h


def java_string_hash(s: str) -> int:
    """Java String.hashCode, exact."""
    h = 0
    for ch in s:
        h = _i32(h * 31 + ord(ch))
    return h


def java_bytes_hash(data: bytes) -> int:
    """Java Arrays.hashCode(byte[]), exact (signed bytes)."""
    h = 1
    for b in data:
        sb = b - 256 if b >= 128 else b
        h = _i32(h * 31 + sb)
    return h


class PartitionFunction:
    name = ""

    def __init__(self, num_partitions: int):
        assert num_partitions > 0, "Number of partitions must be > 0"
        self.num_partitions = num_partitions

    def get_partition(self, value) -> int:
        raise NotImplementedError

    def __str__(self):
        return self.name


class MurmurPartitionFunction(PartitionFunction):
    name = "Murmur"

    def get_partition(self, value) -> int:
        s = value if isinstance(value, str) else str(value)
        return (murmur2(s.encode("utf-8")) & 0x7FFFFFFF) % \
            self.num_partitions


class ModuloPartitionFunction(PartitionFunction):
    name = "Modulo"

    def get_partition(self, value) -> int:
        # parity: ModuloPartitionFunction — integer value % N (Java %
        # keeps the dividend's sign; ids here are parsed longs)
        v = int(value)
        r = abs(v) % self.num_partitions
        return -r if v < 0 else r


class HashCodePartitionFunction(PartitionFunction):
    name = "HashCode"

    def get_partition(self, value) -> int:
        h = java_string_hash(value) if isinstance(value, str) \
            else _i32(int(value))
        return abs(h) % self.num_partitions


class ByteArrayPartitionFunction(PartitionFunction):
    name = "ByteArray"

    def get_partition(self, value) -> int:
        s = value if isinstance(value, str) else str(value)
        return abs(java_bytes_hash(s.encode("utf-8"))) % self.num_partitions


_FUNCTIONS = {
    "murmur": MurmurPartitionFunction,
    "modulo": ModuloPartitionFunction,
    "hashcode": HashCodePartitionFunction,
    "bytearray": ByteArrayPartitionFunction,
}


def make_partition_function(name: str, num_partitions: int
                            ) -> PartitionFunction:
    """Parity: PartitionFunctionFactory.getPartitionFunction."""
    cls = _FUNCTIONS.get(name.lower())
    if cls is None:
        raise ValueError(f"unknown partition function {name}")
    return cls(num_partitions)


class ColumnPartitionConfig:
    """Per-column partitioning in the table config (parity:
    SegmentPartitionConfig entries)."""

    def __init__(self, function_name: str, num_partitions: int):
        self.function_name = function_name
        self.num_partitions = num_partitions

    def to_json(self) -> dict:
        return {"functionName": self.function_name,
                "numPartitions": self.num_partitions}

    @classmethod
    def from_json(cls, d: dict) -> "ColumnPartitionConfig":
        return cls(d["functionName"], int(d["numPartitions"]))


def coerce_partition_value(np_dtype, value):
    """Canonical hashing representation for one partition-column value.

    BOTH the segment builder and the query-side pruners must hash the
    same string for the same logical value (str(np.float32(0.1)) is
    '0.1' but str(float(np.float32(0.1))) is '0.10000000149011612'), so
    everything funnels through the column's numpy scalar type — the same
    normalization the bloom-filter key uses.
    """
    if np_dtype is None:
        return value
    try:
        if np_dtype.kind in "iu":
            return np_dtype.type(int(str(value)))
        if np_dtype.kind == "f":
            return np_dtype.type(float(value))
    except (ValueError, OverflowError):
        pass
    return value


def partition_of_value(function_name: str, num_partitions: int,
                       np_dtype, value) -> int:
    """Shared build/query partition mapping (single source of truth for
    the coercion + hash, used by the creator and both pruners)."""
    fn = make_partition_function(function_name, num_partitions)
    return fn.get_partition(coerce_partition_value(np_dtype, value))
