"""Segment assignment strategies.

Parity: pinot-controller/.../helix/core/sharding/ SegmentAssignmentStrategy
SPI — balanced-num-segments (least loaded instances first), random, and
replica-group assignment (ReplicaGroupSegmentAssignmentStrategy).
"""
from __future__ import annotations

import random
from typing import Dict, List


class SegmentAssignmentStrategy:
    def assign(self, segment: str, instances: List[str], replicas: int,
               current: Dict[str, Dict[str, str]],
               partition_ids=None) -> List[str]:
        """→ the instances that should host `segment`. `partition_ids`:
        the segment's recorded partition-id set (None/empty when the
        table is unpartitioned); only partition-aware strategies use it.
        """
        raise NotImplementedError


class BalancedNumSegmentAssignment(SegmentAssignmentStrategy):
    """Pick the `replicas` least-loaded instances (segment count)."""

    def assign(self, segment: str, instances: List[str], replicas: int,
               current: Dict[str, Dict[str, str]],
               partition_ids=None) -> List[str]:
        if not instances:
            raise ValueError("no live server instances to assign to")
        load = {inst: 0 for inst in instances}
        for seg, m in current.items():
            for inst in m:
                if inst in load:
                    load[inst] += 1
        ordered = sorted(instances, key=lambda i: (load[i], i))
        return ordered[: min(replicas, len(ordered))]


class RandomSegmentAssignment(SegmentAssignmentStrategy):
    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def assign(self, segment: str, instances: List[str], replicas: int,
               current: Dict[str, Dict[str, str]],
               partition_ids=None) -> List[str]:
        if not instances:
            raise ValueError("no live server instances to assign to")
        k = min(replicas, len(instances))
        return sorted(self._rng.sample(instances, k))


class ReplicaGroupSegmentAssignment(SegmentAssignmentStrategy):
    """Partition instances into `replicas` groups; each group hosts every
    segment once, spread within the group by least-load."""

    def assign(self, segment: str, instances: List[str], replicas: int,
               current: Dict[str, Dict[str, str]],
               partition_ids=None) -> List[str]:
        if not instances:
            raise ValueError("no live server instances to assign to")
        instances = sorted(instances)
        replicas = min(replicas, len(instances))
        groups = [instances[i::replicas] for i in range(replicas)]
        load = {inst: 0 for inst in instances}
        for seg, m in current.items():
            for inst in m:
                if inst in load:
                    load[inst] += 1
        return sorted(min(g, key=lambda i: (load[i], i)) for g in groups)


class PartitionAwareSegmentAssignment(SegmentAssignmentStrategy):
    """Same-partition segments land on the same `replicas`-sized instance
    subset (instance index = (partition + r) % n over the sorted live
    list), so the broker's PartitionAwareRoutingTableBuilder can route a
    partition-pruned query to exactly one server per partition.

    Parity: ReplicaGroupSegmentAssignmentStrategy with partition-level
    replica groups (ReplicaGroupStrategyConfig.partitionColumn) — the
    assignment half of the reference's partition-aware routing.
    Unpartitioned segments fall back to balanced assignment."""

    def __init__(self):
        self._fallback = BalancedNumSegmentAssignment()

    def assign(self, segment: str, instances: List[str], replicas: int,
               current: Dict[str, Dict[str, str]],
               partition_ids=None) -> List[str]:
        if not instances:
            raise ValueError("no live server instances to assign to")
        if not partition_ids:
            return self._fallback.assign(segment, instances, replicas,
                                         current)
        inst = sorted(instances)
        p = min(partition_ids)
        k = min(replicas, len(inst))
        return sorted(inst[(p + r) % len(inst)] for r in range(k))


def make_assignment(name: str = "balanced") -> SegmentAssignmentStrategy:
    return {
        "balanced": BalancedNumSegmentAssignment,
        "random": RandomSegmentAssignment,
        "replicagroup": ReplicaGroupSegmentAssignment,
        "partitionaware": PartitionAwareSegmentAssignment,
    }[name]()
