"""Embedded cluster: controller + servers + broker in one process.

Parity: the reference's ClusterTest harness (pinot-integration-tests/.../
ClusterTest.java:85 — real Controller/Broker/Server instances in one JVM)
and the Quickstart wiring (tools/Quickstart.java:125-144). The full
production plumbing runs: property store, state transitions, deep store,
scatter-gather (in-process or TCP), broker reduce.

Membership churn is programmable — ``add_server()`` / ``remove_server()``
/ ``drain_server()`` — so chaos suites and scale-out benchmarks can grow,
kill and drain servers mid-workload (the ClusterTest analogue of the
reference's ChaosMonkey-style integration tests).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
from pinot_tpu.broker.request_handler import (BrokerRequestHandler,
                                              InProcessTransport,
                                              TcpTransport)
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.participant import ServerParticipant


class EmbeddedCluster:
    """controller + num_servers query servers + one broker."""

    def __init__(self, work_dir: str, num_servers: int = 2,
                 tcp: bool = False, mesh=None, scheduler: str = "fcfs",
                 http: bool = False, store_dir: str = None,
                 server_max_pending: int = None,
                 cache_freshness_ms: float = None):
        """`store_dir`: persist cluster state (property-store WAL +
        snapshots) under this directory — a cluster rebuilt over the
        same work_dir/store_dir recovers its tables and segments."""
        from pinot_tpu.broker.quota import QueryQuotaManager
        self.work_dir = work_dir
        self._tcp = tcp
        self._mesh = mesh
        self._scheduler = scheduler
        self._http = http
        self._server_max_pending = server_max_pending
        self.controller = Controller(os.path.join(work_dir, "deepstore"),
                                     store_dir=store_dir)
        self.servers: Dict[str, ServerInstance] = {}
        self.participants: Dict[str, ServerParticipant] = {}
        if tcp:
            self.transport = TcpTransport({})
        else:
            # InProcessTransport shares the live server dict, so
            # add_server/remove_server mutate its view too
            self.transport = InProcessTransport(self.servers)
        # ONE quota manager shared by the watcher (which converges
        # table-config quotas into it) and the broker (which enforces)
        self.quota = QueryQuotaManager()
        self.watcher = BrokerClusterWatcher(self.controller.coordinator,
                                            self.controller.manager,
                                            quota=self.quota)
        self.broker = BrokerRequestHandler(
            self.watcher.routing, self.transport,
            time_boundary=self.watcher.time_boundary,
            quota=self.quota,
            segment_pruner=self.watcher.partition_pruner,
            cache_freshness_ms=cache_freshness_ms)
        # segment lifecycle (upload/replace/drop) flushes the broker
        # result cache — the freshness bound only covers consuming-
        # ingestion staleness, not an offline backfill
        self.watcher.register_result_cache(self.broker.result_cache)
        # a deregistered server's breaker/health state drops in the
        # same watch event as its live record
        self.watcher.attach_fault_tolerance(self.broker.fault_tolerance)
        self.broker_api = None
        self.controller_api = None
        self.server_apis: Dict[str, object] = {}
        self.broker_port: Optional[int] = None
        self.controller_port: Optional[int] = None
        self.server_http_ports: Dict[str, int] = {}
        for i in range(num_servers):
            self.add_server(f"Server_{i}")
        if http:
            from pinot_tpu.broker.http_api import BrokerApiServer
            from pinot_tpu.controller.http_api import ControllerApiServer
            self.broker_api = BrokerApiServer(self.broker)
            self.broker_port = self.broker_api.start()
            self.controller_api = ControllerApiServer(self.controller)
            self.controller_port = self.controller_api.start()

    # -- membership churn ---------------------------------------------------
    def add_server(self, name: Optional[str] = None) -> str:
        """Start a new query server, join it to the cluster (live
        record + state transitions), and wire it into the broker's
        data plane. Returns its instance id."""
        if name is None:
            i = len(self.servers)
            while f"Server_{i}" in self.servers:
                i += 1
            name = f"Server_{i}"
        if name in self.servers:
            raise ValueError(f"server {name} already exists")
        server = ServerInstance(name, scheduler=self._scheduler,
                                mesh=self._mesh,
                                max_pending=self._server_max_pending)
        participant = ServerParticipant(
            server, self.controller.manager,
            completion=self.controller.realtime,
            work_dir=os.path.join(self.work_dir, "server_work", name))
        self.servers[name] = server
        self.participants[name] = participant
        if self._tcp:
            port = server.start(port=0)
            self.transport.set_endpoint(name, "127.0.0.1", port)
        # registration LAST: the reconcile it triggers may immediately
        # assign segments / consuming partitions to the new server
        self.controller.coordinator.register_participant(name, participant)
        if self._http:
            from pinot_tpu.server.http_api import ServerApiServer
            api = ServerApiServer(server)
            self.server_apis[name] = api
            self.server_http_ports[name] = api.start()
        return name

    def remove_server(self, name: str) -> None:
        """Abrupt death (the embedded analogue of kill -9 / session
        expiry): the live record and current states vanish with no
        drain and no seal — the self-healing plane must repair."""
        server = self.servers.pop(name)
        participant = self.participants.pop(name)
        # ephemeral-loss first: views, routing, broker ft state all
        # react to the membership event while the "process" disappears
        self.controller.coordinator.deregister_participant(name)
        participant.shutdown()
        server.stop()
        api = self.server_apis.pop(name, None)
        if api is not None:
            api.stop()
        self.server_http_ports.pop(name, None)

    def drain_server(self, name: str, seal_timeout_s: float = 20.0,
                     settle_s: float = 0.3) -> bool:
        """Planned departure: seal consuming segments where possible,
        deregister (brokers reroute on the watch event), let in-flight
        work finish, then stop — zero query errors by construction.
        Returns whether every sealable consumer actually sealed."""
        import time
        server = self.servers[name]
        participant = self.participants[name]
        sealed = participant.seal_consuming(seal_timeout_s)
        self.controller.coordinator.deregister_participant(name)
        # the embedded watch chain is synchronous, but the broker's
        # in-flight scatters are not: hold the FULL settle window. A
        # depth()==0 early exit raced queries already scattered but not
        # yet admitted (in transit they hold no admission slot), so the
        # stop below turned them into execution errors on a loaded box.
        deadline = time.monotonic() + max(settle_s, 0.05)
        while time.monotonic() < deadline:
            time.sleep(0.02)
        while server.admission.depth() > 0 and \
                time.monotonic() < deadline + seal_timeout_s:
            time.sleep(0.02)
        # only NOW leave the transport's server map: the seal and the
        # settle window above still serve queries, and the in-process
        # transport shares self.servers — popping first turned routed
        # dispatches into KeyErrors during the seal
        self.servers.pop(name, None)
        self.participants.pop(name, None)
        participant.shutdown()
        server.stop()
        api = self.server_apis.pop(name, None)
        if api is not None:
            api.stop()
        self.server_http_ports.pop(name, None)
        return sealed

    # -- admin facade (parity: controller REST) ----------------------------
    def add_schema(self, schema: Schema) -> None:
        self.controller.manager.add_schema(schema)

    def add_table(self, config: TableConfig, **kw) -> str:
        from pinot_tpu.common.table_config import TableType
        if config.table_type == TableType.REALTIME:
            return self.controller.realtime.setup_table(config, **kw)
        return self.controller.manager.add_table(config, **kw)

    def upload_segment(self, table: str, segment_dir: str) -> str:
        return self.controller.manager.add_segment(table, segment_dir)

    def query(self, pql: str) -> BrokerResponse:
        return self.broker.handle(pql)

    def stop(self) -> None:
        if self.broker_api is not None:
            self.broker_api.stop()
        if self.controller_api is not None:
            self.controller_api.stop()
        for api in self.server_apis.values():
            api.stop()
        self.controller.stop()
        self.watcher.close()
        self.broker.close()
        for participant in self.participants.values():
            participant.shutdown()
        for server in self.servers.values():
            server.stop()
