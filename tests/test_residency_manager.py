"""Tiered segment residency (server/residency_manager.py): the staged
HBM ↔ host ↔ disk swaps under a device budget.

Three acceptance-critical families:

1. **kill -9 at every `residency.*` crash point** — the swap dies at
   each armed stage; a "restarted" server (fresh load from the local
   artifact dir, exactly what cold-start recovery serves) answers
   COUNT/SUM and vector-top-k with bit-identical results, and the LIVE
   process that caught the crash keeps serving correct answers too
   (the staged order means every interrupted state is still readable).
2. **query-vs-demotion pin race** — an in-flight query's pin must hold
   the lane release until end_query; the tier publishes immediately
   (fresh queries route off-device) but no lane disappears under a
   reader.
3. **demote → promote round-trip bit-parity** — host, device and
   sharded execution paths return byte-identical results after a full
   device→host→disk→host→device cycle versus a never-evicted twin.

Plus the admission/eviction policy: over-budget attaches land
host-tier, hotter segments evict strictly-colder victims only, and the
promotion backlog drives the admission brownout.
"""
import os
import threading
import time

import pytest

from fixtures import build_segment

from pinot_tpu.common.faults import InjectedCrash, crash_points
from pinot_tpu.common.metrics import MetricsRegistry, ServerGauge, ServerMeter
from pinot_tpu.engine import QueryEngine
from pinot_tpu.obs.residency import LEDGER
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.server.residency_manager import (ResidencyError,
                                                ResidencyManager, TIER_DEVICE,
                                                TIER_DISK, TIER_HOST)

COUNT_SUM = ("SELECT COUNT(*), SUM(runs) FROM baseballStats "
             "WHERE yearID >= 2000")


@pytest.fixture(autouse=True)
def _clean_crash_points():
    crash_points.clear()
    yield
    crash_points.clear()


def expected_count_sum(cols):
    m = cols["yearID"] >= 2000
    return int(m.sum()), float(cols["runs"][m].sum())


def count_sum(engine):
    resp = engine.query(COUNT_SUM)
    assert not resp.exceptions, resp.exceptions
    return (int(resp.aggregation_results[0].value),
            float(resp.aggregation_results[1].value))


def make_manager(budget=None, host_budget=None):
    """A standalone manager with a controllable clock; budgets are
    relative to the CURRENT process-global ledger occupancy so the test
    is insensitive to lanes other tests left resident."""
    clk = [0.0]
    base = LEDGER.total_bytes()
    mgr = ResidencyManager(
        None if budget is None else base + budget,
        host_budget, clock=lambda: clk[0])
    return mgr, clk


def tracked_segment(tmp_path, mgr, name="res_seg", n=2048, seed=11):
    d = str(tmp_path / name)
    seg, cols = build_segment(d, n=n, seed=seed, name=name)
    mgr.track("baseballStats", seg, seg_dir=d)
    seg.warm_device()
    return seg, cols, d


# ---------------------------------------------------------------------------
# 1. kill -9 at every staged-swap crash point
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", ["residency.demote_staged",
                                   "residency.pre_publish",
                                   "residency.pre_release"])
def test_crash_mid_demotion_recovers_with_exact_results(tmp_path, point):
    """The demotion dies at each stage; recovery = reload the verified
    local artifact (what a restarted server's cold-start scan serves)
    → COUNT/SUM parity. The live survivor keeps answering correctly
    too: every interrupted swap state is readable because the fallback
    publishes before anything releases."""
    mgr, _clk = make_manager()
    seg, cols, d = tracked_segment(tmp_path, mgr, name=f"c_{point[10:]}")
    exp = expected_count_sum(cols)
    try:
        crash_points.arm(point)
        with pytest.raises(InjectedCrash):
            mgr.demote_segment(seg.segment_name, TIER_DISK)

        # the surviving process: no torn lanes, both paths still exact
        live = QueryEngine([seg])
        live.executor.device_gate = mgr.device_allowed
        assert count_sum(live) == exp
        assert count_sum(QueryEngine([seg], use_device=False)) == exp

        # the restarted process: fresh load from the artifact dir
        fresh = ImmutableSegmentLoader.load(d)
        try:
            assert count_sum(QueryEngine([fresh])) == exp
        finally:
            fresh.destroy()

        # the interrupted swap retries cleanly (crash-once semantics)
        assert mgr.demote_segment(seg.segment_name, TIER_DISK) or \
            mgr.tracked(seg.segment_name) == TIER_DISK
        mgr.ensure_host(seg.segment_name)
        assert count_sum(QueryEngine([seg], use_device=False)) == exp
    finally:
        seg.destroy()


@pytest.mark.parametrize("point", ["residency.demote_staged",
                                   "residency.pre_release"])
def test_crash_mid_demotion_vector_topk_parity(tmp_path, point):
    """Same kill -9 drill on a vector segment: top-k neighbours after
    recovery are bit-identical to the never-crashed oracle."""
    from test_vector import build_vec_segments, pql_for, result_rows
    segs, cols_list = build_vec_segments(str(tmp_path), n_segs=1, n=512)
    seg = segs[0]
    d = os.path.join(str(tmp_path), "v0")
    q = cols_list[0]["emb"][17]
    pql = pql_for(q, k=9)
    baseline = result_rows(QueryEngine([seg]).query(pql))
    assert len(baseline) == 9

    mgr, _clk = make_manager()
    mgr.track("vectab", seg, seg_dir=d)
    seg.warm_device()
    try:
        crash_points.arm(point)
        with pytest.raises(InjectedCrash):
            mgr.demote_segment(seg.segment_name, TIER_DISK)
        fresh = ImmutableSegmentLoader.load(d)
        try:
            assert result_rows(QueryEngine([fresh]).query(pql)) == baseline
        finally:
            fresh.destroy()
        assert result_rows(QueryEngine([seg], use_device=False)
                           .query(pql)) == baseline
    finally:
        seg.destroy()


# ---------------------------------------------------------------------------
# 2. query-vs-demotion pin race
# ---------------------------------------------------------------------------


def test_inflight_pin_blocks_lane_release_until_end_query(tmp_path):
    mgr, _clk = make_manager()
    released = []
    mgr.add_release_hook(released.append)
    seg, cols, _d = tracked_segment(tmp_path, mgr, name="pin_race")
    exp = expected_count_sum(cols)
    try:
        token = mgr.begin_query([seg])
        assert len(token) == 1

        done = threading.Event()
        result = {}

        def demoter():
            result["ok"] = mgr.demote_segment(seg.segment_name,
                                              TIER_HOST)
            done.set()

        t = threading.Thread(target=demoter, daemon=True)
        t.start()
        # the tier publishes promptly (fresh queries route host-side)
        # but the release MUST wait on the pin
        deadline = time.monotonic() + 5.0
        while mgr.tracked(seg.segment_name) != TIER_HOST:
            assert time.monotonic() < deadline, "publish never happened"
            time.sleep(0.01)
        assert not done.wait(0.15), "release did not wait for the pin"
        assert released == []
        # the pinned reader still sees intact lanes mid-swap
        assert count_sum(QueryEngine([seg], use_device=False)) == exp

        mgr.end_query(token)
        assert done.wait(5.0), "demotion wedged after pins drained"
        t.join(5.0)
        assert result["ok"] is True
        assert released == [seg.segment_name]
        assert count_sum(QueryEngine([seg], use_device=False)) == exp
    finally:
        seg.destroy()


# ---------------------------------------------------------------------------
# 3. demote → promote round-trip bit-parity
# ---------------------------------------------------------------------------


def test_full_tier_cycle_bit_parity_on_all_execution_paths(tmp_path):
    """device→host→disk→host→device round trip, then the same query on
    the host, device and sharded paths versus a never-evicted twin
    built from identical inputs — results must be bit-identical."""
    from pinot_tpu.parallel import make_mesh
    mgr, _clk = make_manager()
    segs, twins, cols_all = [], [], []
    for i in range(2):
        d = str(tmp_path / f"cyc{i}")
        seg, cols = build_segment(d, n=2048, seed=40 + i,
                                  name=f"cyc_{i}")
        mgr.track("baseballStats", seg, seg_dir=d)
        seg.warm_device()
        segs.append(seg)
        cols_all.append(cols)
        td = str(tmp_path / f"twin{i}")
        twin, _ = build_segment(td, n=2048, seed=40 + i,
                                name=f"cyc_{i}")
        twins.append(twin)
    try:
        for seg in segs:
            assert mgr.demote_segment(seg.segment_name, TIER_DISK)
            assert mgr.tracked(seg.segment_name) == TIER_DISK
            assert mgr.promote_segment(seg.segment_name)
            assert mgr.tracked(seg.segment_name) == TIER_DEVICE

        pql = ("SELECT COUNT(*), SUM(hits) FROM baseballStats "
               "WHERE league = 'AL' GROUP BY teamID TOP 1000")

        def groups(resp, i):
            return {tuple(g["group"]): g["value"]
                    for g in resp.aggregation_results[i].group_by_result}

        for engines in [(QueryEngine(segs, use_device=False),
                         QueryEngine(twins, use_device=False)),
                        (QueryEngine(segs), QueryEngine(twins)),
                        (QueryEngine(segs, mesh=make_mesh()),
                         QueryEngine(twins, mesh=make_mesh()))]:
            got = engines[0].query(pql)
            want = engines[1].query(pql)
            assert not got.exceptions and not want.exceptions
            assert groups(got, 0) == groups(want, 0)
            assert groups(got, 1) == groups(want, 1)
            assert count_sum(engines[0]) == count_sum(engines[1])
    finally:
        for s in segs + twins:
            s.destroy()


def test_cold_hit_reload_is_metered_and_exact(tmp_path):
    """Disk-tier first read: begin_query reloads through ensure_host
    (a metered cold hit), the segment lands host-tier, and the answer
    is exact."""
    metrics = MetricsRegistry("server")
    mgr, _clk = make_manager()
    mgr.bind_metrics(metrics)
    seg, cols, _d = tracked_segment(tmp_path, mgr, name="cold_hit")
    try:
        assert mgr.demote_segment(seg.segment_name, TIER_DISK)
        token = mgr.begin_query([seg])
        try:
            assert mgr.tracked(seg.segment_name) in (TIER_HOST,
                                                     TIER_DEVICE)
            assert count_sum(QueryEngine([seg], use_device=False)) == \
                expected_count_sum(cols)
        finally:
            mgr.end_query(token)
        assert metrics.meter(ServerMeter.RESIDENCY_COLD_HITS,
                             table="baseballStats").count == 1
        snap = mgr.snapshot()
        (entry,) = [s for s in snap["segments"]
                    if s["segment"] == seg.segment_name]
        assert entry["coldHits"] == 1
    finally:
        seg.destroy()


# ---------------------------------------------------------------------------
# admission, eviction policy, degradation ladder
# ---------------------------------------------------------------------------


def test_over_budget_attach_lands_host_tier_not_a_crash(tmp_path):
    mgr, _clk = make_manager(budget=0)
    d = str(tmp_path / "over_budget")
    seg, cols = build_segment(d, n=2048, seed=11, name="over_budget")
    mgr.track("baseballStats", seg, seg_dir=d)
    try:
        assert mgr.tracked(seg.segment_name) == TIER_HOST
        # the routed warm-up refuses (the raw seg.warm_device() bypass
        # is exactly what serving paths must not call)
        assert mgr.warm_device(seg.segment_name) is False
        # the execution gate routes it off-device; results stay exact
        assert not mgr.device_allowed(seg)
        eng = QueryEngine([seg])
        eng.executor.device_gate = mgr.device_allowed
        assert count_sum(eng) == expected_count_sum(cols)
    finally:
        seg.destroy()


def test_hotter_segment_evicts_strictly_colder_victim(tmp_path):
    mgr, clk = make_manager()               # attach both unbudgeted
    cold, _cc, _d0 = tracked_segment(tmp_path, mgr, name="victim_cold",
                                     seed=1)
    hot, _hc, _d1 = tracked_segment(tmp_path, mgr, name="asker_hot",
                                    seed=2)
    try:
        # make `hot` much hotter than `cold`, then let cold decay
        for _ in range(6):
            mgr.end_query(mgr.begin_query([hot]))
        clk[0] += 120.0                     # cold loses 4 half-lives
        mgr.end_query(mgr.begin_query([hot]))
        # budget: one byte less than full residency — re-promoting hot
        # cannot fit without claiming a victim
        full = LEDGER.total_bytes()
        assert mgr.demote_segment(hot.segment_name, TIER_HOST)
        mgr.configure(full - 1)

        # promotion of the hot segment claims the cold victim's lanes
        assert mgr.promote_segment(hot.segment_name)
        assert mgr.tracked(hot.segment_name) == TIER_DEVICE
        assert mgr.tracked(cold.segment_name) == TIER_HOST
        # the converse never happens: a colder asker cannot evict a
        # hotter resident
        assert not mgr.promote_segment(cold.segment_name)
        assert mgr.tracked(hot.segment_name) == TIER_DEVICE
    finally:
        cold.destroy()
        hot.destroy()


def test_disk_demotion_without_artifact_is_refused(tmp_path):
    mgr, _clk = make_manager()
    d = str(tmp_path / "no_art")
    seg, _cols = build_segment(d, n=512, seed=5, name="no_art")
    mgr.track("baseballStats", seg)          # no seg_dir recorded
    seg.warm_device()
    try:
        with pytest.raises(ResidencyError, match="artifact"):
            mgr.demote_segment(seg.segment_name, TIER_DISK)
        # host demotion (no artifact needed) still works
        assert mgr.demote_segment(seg.segment_name, TIER_HOST)
    finally:
        seg.destroy()


def test_promotion_backlog_drives_admission_brownout(tmp_path):
    from pinot_tpu.server.admission import AdmissionController
    mgr, _clk = make_manager(budget=0)
    segs = []
    try:
        for i in range(AdmissionController.PROMOTION_BACKLOG_WATERMARK):
            d = str(tmp_path / f"bk{i}")
            seg, _ = build_segment(d, n=512, seed=60 + i,
                                   name=f"bk_{i}")
            mgr.track("baseballStats", seg, seg_dir=d)
            segs.append(seg)
        # every attach landed off-device with seed heat ≥ the
        # promotion threshold → all of them back up behind the budget
        backlog = mgr.promotion_backlog()
        assert backlog >= AdmissionController.PROMOTION_BACKLOG_WATERMARK
        ac = AdmissionController(backlog_fn=mgr.promotion_backlog)
        d = ac.admit("baseballStats", "tenantA")
        assert d.admitted and d.brownout    # brownout on an IDLE queue
        ac.release("tenantA")
        idle = AdmissionController(backlog_fn=lambda: 0)
        d2 = idle.admit("baseballStats", "tenantA")
        assert d2.admitted and not d2.brownout
    finally:
        for s in segs:
            s.destroy()


def test_gauges_and_debug_snapshot_expose_tiers(tmp_path):
    metrics = MetricsRegistry("server")
    mgr, _clk = make_manager()
    mgr.bind_metrics(metrics)
    seg, _cols, _d = tracked_segment(tmp_path, mgr, name="gauged")
    try:
        dev_gauge = metrics.gauge(ServerGauge.RESIDENCY_TIER_BYTES,
                                  table="|tier:device")
        host_gauge = metrics.gauge(ServerGauge.RESIDENCY_TIER_BYTES,
                                   table="|tier:host")
        assert dev_gauge.value > 0 and host_gauge.value == 0
        assert mgr.demote_segment(seg.segment_name, TIER_HOST)
        assert dev_gauge.value == 0 and host_gauge.value > 0
        # ledger snapshot rows carry the residency annotations; note
        # the demotion released the device lanes, so the manager's own
        # snapshot is the authoritative tier view
        snap = mgr.snapshot()
        assert snap["tiers"]["host"]["segments"] == 1
        (entry,) = [s for s in snap["segments"]
                    if s["segment"] == seg.segment_name]
        assert entry["tier"] == TIER_HOST and entry["heat"] > 0
    finally:
        seg.destroy()
        mgr.shutdown()
