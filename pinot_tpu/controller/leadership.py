"""Controller leader election on the property store.

Parity: controller/ControllerLeadershipManager.java — the reference
elects a lead controller through Helix so periodic tasks (retention,
validation, task generation) run exactly once across controllers. Here
the election is a lease record at /CONTROLLER/LEADER claimed with the
property store's atomic read-modify-write; the holder refreshes the
lease, others take over when it expires.

Standby failover adds **fencing**: every successful takeover bumps a
monotonic ``epoch`` in the lease record, and the holder remembers the
epoch it acquired. A ``FencedStore`` wraps the cluster store for a HA
controller's mutation paths and verifies holder+epoch+TTL before every
write, so a deposed leader's in-flight mutations (a periodic task or a
segment commit that was mid-flight when the lease expired) are rejected
instead of clobbering the new leader's state — the ZK-style fencing
token, enforced at the store client.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

LEADER_PATH = "/CONTROLLER/LEADER"
DEFAULT_LEASE_S = 10.0


class ControllerLeadershipManager:
    def __init__(self, store, instance_id: str,
                 lease_s: float = DEFAULT_LEASE_S,
                 clock: Callable[[], float] = time.time,
                 metrics=None):
        """`metrics`: optional controller MetricsRegistry — takeovers
        from a different previous holder mark `leaderFailovers`."""
        self.store = store
        self.instance_id = instance_id
        self.lease_s = lease_s
        self._clock = clock
        self.metrics = metrics
        self._listeners: List[Callable[[bool], None]] = []
        self._was_leader = False
        #: fencing token: the lease epoch THIS instance acquired (None
        #: until first acquisition). Compared against the live record by
        #: FencedStore so a deposed-then-reacquired leader's writes from
        #: its OLD incarnation still fence out.
        self._epoch: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- election ----------------------------------------------------------

    def try_acquire(self) -> bool:
        """Claim (or refresh) the lease; returns leadership state.

        The expired-lease takeover is a single compare-and-set against
        the exact record we read: two controllers racing the same
        expired lease can both pass the read check, but only one CAS
        applies — the loser observes the failure instead of blindly
        overwriting the winner's claim (a remote store's update() loop
        would have let both believe they won)."""
        for _ in range(2):
            now = self._clock()
            cur = self.store.get(LEADER_PATH)
            holder = (cur or {}).get("instance")
            expired = (cur or {}).get("leaseUntil", 0) < now
            if holder not in (None, self.instance_id) and not expired:
                # someone else holds an unexpired lease: no write, no
                # spurious watcher churn from heartbeat polls
                self._notify(False)
                return False
            rec = dict(cur or {})
            takeover = holder != self.instance_id
            if takeover:
                # fencing token: every change of holder bumps the epoch,
                # invalidating the previous holder's FencedStore writes
                rec["epoch"] = int(rec.get("epoch", 0)) + 1
            rec["instance"] = self.instance_id
            rec["leaseUntil"] = now + self.lease_s
            if self.store.cas(LEADER_PATH, cur, rec):
                self._epoch = int(rec.get("epoch", 0))
                if takeover and holder is not None and \
                        self.metrics is not None:
                    from pinot_tpu.common.metrics import ControllerMeter
                    self.metrics.meter(
                        ControllerMeter.LEADER_FAILOVERS).mark()
                self._notify(True)
                return True
            # CAS lost: someone moved the record under us — one re-read
            # settles whether the winner was us (our own refresh racing)
            # or a peer
        leader = self.is_leader()
        self._notify(leader)
        return leader

    def is_leader(self) -> bool:
        rec = self.store.get(LEADER_PATH) or {}
        return rec.get("instance") == self.instance_id and \
            rec.get("leaseUntil", 0) >= self._clock()

    def fencing_token(self) -> Optional[int]:
        """The lease epoch this instance acquired (None = never led)."""
        return self._epoch

    def holds_fenced_lease(self) -> bool:
        """True only while the live lease record names THIS instance,
        is unexpired, AND still carries the epoch this incarnation
        acquired — the write-side fencing check. A deposed leader fails
        the instance/TTL check; a deposed-then-reacquired one fails the
        epoch check for writes issued under its old token."""
        if self._epoch is None:
            return False
        rec = self.store.get(LEADER_PATH) or {}
        return rec.get("instance") == self.instance_id and \
            rec.get("leaseUntil", 0) >= self._clock() and \
            int(rec.get("epoch", 0)) == self._epoch

    def resign(self) -> None:
        def drop(rec):
            rec = dict(rec or {})
            if rec.get("instance") == self.instance_id:
                rec["instance"] = None
                rec["leaseUntil"] = 0
            return rec

        self.store.update(LEADER_PATH, drop)
        self._notify(False)

    # -- listeners (parity: onBecomeLeader/onBecomeNotLeader) --------------

    def add_listener(self, fn: Callable[[bool], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, leader: bool) -> None:
        if leader != self._was_leader:
            self._was_leader = leader
            for fn in self._listeners:
                fn(leader)

    # -- background heartbeat ---------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        interval = interval_s if interval_s is not None else \
            self.lease_s / 3

        def loop():
            while not self._stop.is_set():
                self.try_acquire()
                self._stop.wait(interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"leader-{self.instance_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.resign()

    def abort(self) -> None:
        """Crash simulation: stop the heartbeat WITHOUT resigning — the
        lease record stays and must expire on its own TTL before a
        standby can take over (exactly what a kill -9 leaves behind)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class FencedWriteError(RuntimeError):
    """A store mutation was attempted without a valid fenced lease — the
    writer was deposed (or never led). The mutation was NOT applied."""


class FencedStore:
    """PropertyStore proxy that fences every mutation on the owner's
    leader lease (instance + TTL + epoch).

    Reads, watches and children pass through untouched — a standby
    controller must see cluster state to stay hot. Mutations verify
    ``leadership.holds_fenced_lease()`` immediately before delegating,
    so a deposed leader's delayed write (periodic task mid-run, segment
    commit mid-flight when the lease expired) raises FencedWriteError
    instead of overwriting the new leader's state. The check-then-write
    is not atomic against a concurrent deposition — the residual window
    is one store round-trip, the same guarantee ZK fencing tokens give
    when the resource itself doesn't validate them transactionally; the
    crash-pointed rebalance/takeover steps are idempotent under exactly
    that window.
    """

    def __init__(self, inner, leadership: ControllerLeadershipManager):
        self.inner = inner
        self.leadership = leadership

    @property
    def compose_lock(self):
        # compose_view serializes on the UNDERLYING store's lock so a
        # fenced and an unfenced composer over the same store still
        # exclude each other
        return self.inner.compose_lock

    def _fence(self, op: str, path: str) -> None:
        if not self.leadership.holds_fenced_lease():
            raise FencedWriteError(
                f"{op} {path}: {self.leadership.instance_id} does not "
                f"hold the leader lease (fencing token "
                f"{self.leadership.fencing_token()})")

    # -- mutations (fenced) -------------------------------------------------
    def set(self, path: str, record: dict, **kw) -> None:
        self._fence("set", path)
        return self.inner.set(path, record, **kw)

    def update(self, path: str, fn):
        self._fence("update", path)
        return self.inner.update(path, fn)

    def cas(self, path: str, expected, record, **kw) -> bool:
        self._fence("cas", path)
        return self.inner.cas(path, expected, record, **kw)

    def remove(self, path: str) -> bool:
        self._fence("remove", path)
        return self.inner.remove(path)

    # -- reads / watches (pass-through) -------------------------------------
    def get(self, path: str):
        return self.inner.get(path)

    def children(self, prefix: str):
        return self.inner.children(prefix)

    def list_paths(self, prefix: str):
        return self.inner.list_paths(prefix)

    def watch(self, prefix: str, callback) -> None:
        self.inner.watch(prefix, callback)

    def unwatch(self, callback) -> None:
        self.inner.unwatch(callback)

    def close(self) -> None:
        # lifecycle belongs to the inner store's owner; fenced views
        # never close the shared session
        pass
