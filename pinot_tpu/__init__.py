"""pinot_tpu — a TPU-native realtime distributed OLAP datastore.

A from-scratch framework with the capabilities of Apache Pinot (incubating):
columnar immutable segments with dictionary / forward / inverted-bitmap /
bloom / star-tree indexes, a PQL-style query language compiled to per-segment
execution plans, scatter-gather distributed execution with broker-side reduce,
batch + streaming ingestion, and a controller plane for segment assignment.

Unlike the Java reference (see SURVEY.md), the per-segment execution engine is
built TPU-first: filters are vectorized mask kernels over HBM-resident
dictionary-encoded columns, aggregations are masked reductions, group-by is a
mixed-radix scatter-add, and multi-segment combine rides `shard_map`/`psum`
over a `jax.sharding.Mesh`.
"""

__version__ = "0.1.0"
