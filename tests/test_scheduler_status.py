"""Bounded-FCFS scheduler + ServiceStatus readiness tests.

Parity: BoundedFCFSScheduler/ResourceLimitPolicy (per-group caps,
OutOfCapacity rejection) and ServiceStatus.java convergence gating.
"""
import os
import tempfile
import threading
import time

import pytest

from fixtures import make_schema, make_table_config, make_shared_columns

from pinot_tpu.common.service_status import (Status, get_service_status)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.server.scheduler import (BoundedFCFSScheduler,
                                        ResourceLimitPolicy,
                                        SchedulerOutOfCapacityError,
                                        make_scheduler)
from pinot_tpu.tools.cluster import EmbeddedCluster


def test_bounded_fcfs_limits_per_group_concurrency():
    sched = BoundedFCFSScheduler(
        num_workers=4, policy=ResourceLimitPolicy(4,
                                                  max_threads_per_group_pct=0.25))
    assert sched.policy.table_threads_hard_limit == 1
    running = []
    peak = []
    gate = threading.Event()

    def job(i):
        def run():
            running.append(i)
            peak.append(len(running))
            gate.wait(2)
            running.remove(i)
            return i
        return run

    futures = [sched.submit("t1", job(i)) for i in range(4)]
    time.sleep(0.2)
    # hard limit 1: only one t1 query may run at a time
    assert max(peak) == 1
    gate.set()
    assert sorted(f.result(timeout=5) for f in futures) == [0, 1, 2, 3]
    assert max(peak) == 1
    sched.shutdown()


def test_bounded_fcfs_rejects_over_capacity():
    sched = BoundedFCFSScheduler(
        num_workers=2, policy=ResourceLimitPolicy(
            2, max_threads_per_group_pct=0.5, max_pending_per_group=2))
    gate = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        gate.wait(2)
        return True

    first = sched.submit("t", blocker)
    assert started.wait(2)
    # first is RUNNING; queue bound 2 admits two more, rejects the rest
    futures = [sched.submit("t", lambda: True) for _ in range(4)]
    gate.set()
    results = []
    rejected = 0
    for f in [first] + futures:
        try:
            results.append(f.result(timeout=5))
        except SchedulerOutOfCapacityError:
            rejected += 1
    assert rejected == 2 and len(results) == 3
    sched.shutdown()


def test_make_scheduler_bounded_fcfs():
    s = make_scheduler("bounded_fcfs", 2)
    assert isinstance(s, BoundedFCFSScheduler)
    assert s.submit("g", lambda: 7).result(timeout=5) == 7
    s.shutdown()


def test_service_status_converges_with_cluster():
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        d = os.path.join(base, "seg")
        SegmentCreator(make_schema(), make_table_config(),
                       segment_name="ss_0").build(
            make_shared_columns(1024, 1), d)
        cluster.upload_segment("baseballStats_OFFLINE", d)
        # the embedded coordinator applies transitions synchronously:
        # every server must now report GOOD
        for name in cluster.servers:
            status, desc = get_service_status(name)
            assert status == Status.GOOD, (name, desc)
        # an unknown instance has no callback → STARTING
        assert get_service_status("nope")[0] == Status.STARTING
    finally:
        cluster.stop()


def test_service_status_detects_divergence():
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        coord = cluster.controller.coordinator
        # fabricate an ideal-state entry the server never applied
        coord.store.update(
            "/IDEALSTATES/baseballStats_OFFLINE",
            lambda old: {"segments": {"ghost_seg": {"Server_0": "ONLINE"}}})
        status, desc = get_service_status("Server_0")
        assert status == Status.STARTING and "ghost_seg" in desc
    finally:
        cluster.stop()


def test_instance_config_layering(tmp_path):
    from pinot_tpu.common.instance_config import InstanceConfig
    props = tmp_path / "server.properties"
    props.write_text("# comment\n"
                     "pinot.server.query.scheduler.algorithm=tokenbucket\n"
                     "custom.key = hello\n")
    cfg = InstanceConfig(
        overrides={"pinot.server.query.scheduler.workers": "8"},
        properties_file=str(props),
        env={"PINOT_TPU_PINOT__BROKER__TIMEOUT__MS": "9000"})
    # default
    assert cfg.get("pinot.broker.routing.table.builder") == "balanced"
    # file beats default
    assert cfg.get("pinot.server.query.scheduler.algorithm") == "tokenbucket"
    # env beats file/default
    assert cfg.get_int("pinot.broker.timeout.ms") == 9000
    # override beats everything
    assert cfg.get_int("pinot.server.query.scheduler.workers") == 8
    assert cfg.get("custom.key") == "hello"
    assert cfg.get("missing.key", "fallback") == "fallback"
    assert cfg.get_bool("missing.flag", True) is True
    sub = cfg.subset("pinot.server.query.")
    assert sub["pinot.server.query.scheduler.workers"] == "8"
