"""Shared-memory reply transport for colocated broker↔server processes.

The multiplexed TCP data plane (transport/tcp.py) copies every reply
payload twice through kernel socket buffers. For processes on the SAME
host — the multi-process serving shapes `scripts/qps_curve.py` drives —
a large DataTable can instead travel as a tiny reference to a
shared-memory segment: the server memcpy's the payload into a fresh
`multiprocessing.shared_memory` block and sends a control frame naming
it; the broker attaches, hands the segment's memoryview STRAIGHT to the
zero-copy DataTable decoder, then closes and unlinks.

Correctness notes:

- **Negotiation**: the broker announces shm support with a hello frame
  (correlation id 0) on each connection it opens to a loopback
  address. A server never sends shm references to a peer that did not
  announce — remote brokers keep getting inline payloads.
- **Threshold**: only replies of at least `min_bytes()` ride shm
  (segment create/attach costs two syscalls — a losing trade for the
  small aggregation replies that dominate steady traffic). The env
  knob PINOT_TPU_SHM_MIN_BYTES enables the path (0 = disabled).
- **Aliasing**: a shm buffer is writable and unlinked right after
  decode, so the DataTable decoder's aliasing rule (datatable.py:
  writable sources are copied block-wise) is what makes the immediate
  unlink safe — decoded tables never reference the segment.
- **Ownership**: the broker (consumer) unlinks after reading. If a
  reply is abandoned (per-request timeout) the connection's read loop
  still attaches and unlinks it when the late control frame lands. The
  server keeps the names it created per connection and sweeps them on
  connection close, tolerating already-unlinked names — so a broker
  that dies mid-flight leaks nothing past the connection teardown.
"""
from __future__ import annotations

import os
from typing import List, Optional

#: control-frame magic. A real DataTable payload starts with its u32
#: version tag (0x00 0x00 0x00 vv), so a 0xFF first byte can never be
#: confused with an inline payload.
SHM_MAGIC = b"\xffSHM1"
#: broker→server hello payload announcing shm support (corr id 0)
SHM_HELLO = b"\xffSHMHELLO"
#: the reserved correlation id hello frames travel under
HELLO_CORR = b"\x00" * 8

_U32_LEN = 4

#: names THIS process currently holds registered with the multiprocessing
#: resource tracker — create and attach both register, unlink
#: unregisters, and the tracker's books must balance or it prints
#: KeyError noise / spurious leak warnings at interpreter exit. The
#: creator and consumer may be the SAME process (embedded clusters,
#: tests), so the set is shared module state, not per-role.
_registered: set = set()


def min_bytes() -> int:
    """Reply-size floor for the shm path; 0 disables it entirely."""
    try:
        return int(os.environ.get("PINOT_TPU_SHM_MIN_BYTES", "0"))
    except ValueError:
        return 0


def is_loopback(host: str) -> bool:
    return host in ("127.0.0.1", "::1", "localhost")


def encode_reply(payload: bytes, created: List[str]) -> bytes:
    """Server side: move `payload` into a fresh shm segment and return
    the control frame referencing it; appends the segment name to
    `created` (the connection's sweep list)."""
    from multiprocessing import shared_memory

    seg = shared_memory.SharedMemory(create=True, size=len(payload))
    try:
        seg.buf[:len(payload)] = payload
        name = seg.name
        created.append(name)
        _registered.add(name)
        nb = name.encode("utf-8")
        return SHM_MAGIC + len(payload).to_bytes(_U32_LEN, "big") + nb
    finally:
        seg.close()    # the mapping; the named segment itself persists


def is_shm_frame(payload) -> bool:
    return bytes(payload[:len(SHM_MAGIC)]) == SHM_MAGIC


class ShmReply:
    """An attached shm reply: expose the payload view, then `close()`
    unlinks (consumer-side ownership transfer)."""

    __slots__ = ("_seg", "size")

    def __init__(self, name: str, size: int):
        from multiprocessing import shared_memory
        self._seg = shared_memory.SharedMemory(name=name)
        self.size = size
        # attach does not register with the resource tracker, but the
        # unlink in close() UNregisters — pre-register so the tracker's
        # books balance (and so a consumer that dies before close()
        # still gets the segment reclaimed at interpreter exit). The
        # tracker's cache is a set, so a same-process creator having
        # registered already is harmless.
        try:
            from multiprocessing import resource_tracker
            resource_tracker.register(self._seg._name, "shared_memory")
            _registered.add(name)
        except Exception:  # noqa: BLE001 — tracker bookkeeping is best-effort
            pass

    @property
    def view(self) -> memoryview:
        return self._seg.buf[:self.size]

    def close(self) -> None:
        seg, self._seg = self._seg, None
        if seg is None:
            return
        name = seg.name
        try:
            try:
                seg.close()
            except BufferError:
                # a decode error's traceback can pin numpy views over
                # the buffer; the mapping then closes at GC — unlink
                # the NAME regardless so the segment cannot leak, and
                # never let this mask the original decode exception
                pass
            seg.unlink()           # unregisters on success
            _registered.discard(name)
        except FileNotFoundError:
            _untrack(name)         # raced: unlink skipped unregister

    def __len__(self) -> int:
        return self.size


def datatable_from_reply(raw):
    """Decode a data-plane reply — inline bytes/memoryview OR an
    ShmReply — into a DataTable, closing the shm segment either way.

    The ONE place the reply-wrapper contract lives: the broker's
    _call_once, the stage orchestration dispatches and the exchange
    fetch client all consume replies through here, so a new reply
    wrapper type changes exactly one decode site."""
    from pinot_tpu.common.datatable import DataTable
    if isinstance(raw, ShmReply):
        try:
            return DataTable.from_bytes(raw.view)
        finally:
            raw.close()
    return DataTable.from_bytes(raw)


def decode_reply(payload) -> Optional[ShmReply]:
    """Broker side: resolve a control frame into an attached ShmReply
    (None if the segment vanished — surfaces as a decode error)."""
    size = int.from_bytes(bytes(
        payload[len(SHM_MAGIC):len(SHM_MAGIC) + _U32_LEN]), "big")
    name = str(payload[len(SHM_MAGIC) + _U32_LEN:], "utf-8")
    try:
        return ShmReply(name, size)
    except FileNotFoundError:
        return None


def discard_reply(payload) -> None:
    """Attach-and-unlink a control frame nobody will consume (late
    reply to a timed-out request)."""
    reply = decode_reply(payload)
    if reply is not None:
        reply.close()


#: created-list length at which the serving path opportunistically
#: prunes names the broker already consumed (one shm-open syscall per
#: historical name, so it must run rarely, not per reply)
PRUNE_AT = 128


def prune_consumed(created: List[str]) -> None:
    """Drop names the consumer has already unlinked from the sweep
    list (and this process's tracker books) — without this, a
    long-lived connection's created-list grows by one name per
    over-threshold reply forever."""
    from multiprocessing import shared_memory
    still: List[str] = []
    for name in created:
        try:
            seg = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            _untrack(name)          # consumed: forget it
            continue
        seg.close()                 # probe only; still unconsumed
        still.append(name)
    created[:] = still


def sweep(created: List[str]) -> None:
    """Server side, at connection close: unlink any segment the broker
    never consumed. Already-unlinked names are the normal case."""
    from multiprocessing import shared_memory
    for name in created:
        try:
            seg = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            _untrack(name)
            continue
        try:
            seg.close()
            seg.unlink()
            _registered.discard(name)
        except FileNotFoundError:
            _untrack(name)
    created.clear()


def _untrack(name: str) -> None:
    """Drop an already-unlinked segment from this process's resource
    tracker — but ONLY if this process still has it registered
    (unregistering a name the tracker never saw, or saw unregistered by
    the consumer in the same process, prints KeyError noise from the
    tracker at exit)."""
    if name not in _registered:
        return
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(f"/{name}", "shared_memory")
        _registered.discard(name)
    except Exception:  # noqa: BLE001 — tracker bookkeeping is best-effort
        pass
