"""Multi-stage query engine: distributed joins, window functions, and
the exchange plane that ships columnar blocks server↔server.

Layout (submodules import explicitly — this package init stays empty so
`query/plan.py` can import `stages.errors` without cycles):

- errors.py    typed stage compile/execution errors (→ 4xx at the broker)
- exchange.py  ExchangeManager + fetch client over the TCP data plane
- join.py      JoinContext: dim-side blocks → probe/gather tables
- window.py    stage-2 window executor (device kernel + host oracle)
- broker.py    broker-side two-stage orchestration

See docs/QUERYENGINE.md for the stage model and exactness contracts.
"""
