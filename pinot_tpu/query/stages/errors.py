"""Typed multi-stage errors.

A StageCompileError is a property of the QUERY against the current
schemas/data contracts (unknown dim table, non-integer join keys,
duplicate dim join keys, window sum overflow): the broker surfaces it as
a 4xx-class error code — clients must not retry — and the server stamps
it as a structured DataTable metadata marker
(common/datatable.STAGE_ERROR_KEY) so classification never depends on
exception message wording.
"""
from __future__ import annotations

#: errorCode the broker attaches to stage compile errors (4xx class —
#: distinct from 425 server faults and 503 overload sheds)
STAGE_COMPILE_ERROR_CODE = 422


class StageCompileError(ValueError):
    """The multi-stage query cannot execute against the current tables —
    a deterministic property of the query, never a transient fault."""


class ExchangeError(RuntimeError):
    """A stage-1 block could not be fetched (expired, peer gone) — a
    transient execution fault, retriable like any server error."""


def stage_error_datatable(request_id, kind: str, message: str):
    """Typed stage-error reply: STAGE_ERROR_KEY carries the machine
    kind, exceptions the human message."""
    from pinot_tpu.common.datatable import DataTable, STAGE_ERROR_KEY
    dt = DataTable()
    dt.metadata["requestId"] = str(request_id)
    dt.metadata[STAGE_ERROR_KEY] = kind
    dt.exceptions.append(f"StageCompileError: {message}")
    return dt

