"""Dependency-free Thrift TBinaryProtocol record reader (+ writer for
round-trip tests).

Parity: pinot-core/.../core/data/readers/ThriftRecordReader.java — the
reference deserializes a file of back-to-back TBinaryProtocol-serialized
structs using a generated Thrift class and maps field NAMES to field IDS
by probing `tObject.fieldForId(index)` for index = 1, 2, ... There is no
Thrift runtime (or code generator) in this environment, so the TPU build
decodes the binary protocol directly — the wire format is a simple tagged
field list — and takes the name→id mapping from the reader config
(ThriftRecordReaderConfig.java's `thriftClass` becomes an explicit
field-name list / map, ids defaulting to 1-based order exactly like the
reference's probing loop).

Wire format (struct, non-strict binary protocol):
    repeat:  [ttype: i8] [field-id: i16 BE] [value]
    until    ttype == 0 (STOP)
value encodings: BOOL 1B, BYTE i8, I16/I32/I64 BE, DOUBLE 8B BE,
STRING [len: i32 BE][utf-8 bytes], STRUCT nested field list,
LIST/SET [etype: i8][count: i32 BE][elements], MAP [kt][vt][count][pairs].
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Sequence, Union

from pinot_tpu.ingestion.record_reader import RecordReader

# TType constants (thrift/protocol/TType)
STOP, VOID, BOOL, BYTE, DOUBLE = 0, 1, 2, 3, 4
I16, I32, I64, STRING, STRUCT, MAP, SET, LIST = 6, 8, 10, 11, 12, 13, 14, 15


class ThriftRecordReaderConfig:
    """Field-id mapping for a Thrift struct.

    `fields` is either an ordered name sequence (ids 1..N, matching the
    reference's fieldForId(1..) probing) or an explicit {name: id} map.

    `bytes_fields` names the fields whose wire STRING payload is
    BINARY: thrift's binary protocol cannot distinguish `string` from
    `binary` (both are type 11), so a binary payload that happens to be
    valid UTF-8 would silently decode to `str` — per-row type
    instability for a bytes column. Declaring the field here (or giving
    the reader a schema whose column is BYTES) skips the decode
    attempt entirely.
    """

    def __init__(self, fields: Union[Sequence[str], Dict[str, int]],
                 bytes_fields: Sequence[str] = ()):
        if isinstance(fields, dict):
            self.field_ids = dict(fields)
        else:
            self.field_ids = {name: i + 1 for i, name in enumerate(fields)}
        self.bytes_fields = set(bytes_fields)


class _BinaryProtocolReader:
    def __init__(self, buf: bytes, binary_fids: frozenset = frozenset()):
        self.buf = buf
        self.pos = 0
        # top-level field ids whose STRING payload is declared BINARY:
        # returned as raw bytes, never utf-8 decoded
        self.binary_fids = binary_fids

    def _take(self, n: int) -> bytes:
        b = self.buf[self.pos: self.pos + n]
        if len(b) != n:
            raise EOFError("truncated thrift data")
        self.pos += n
        return b

    def read_value(self, ttype: int, binary: bool = False):
        if ttype == BOOL:
            return self._take(1)[0] != 0
        if ttype == BYTE:
            return struct.unpack(">b", self._take(1))[0]
        if ttype == I16:
            return struct.unpack(">h", self._take(2))[0]
        if ttype == I32:
            return struct.unpack(">i", self._take(4))[0]
        if ttype == I64:
            return struct.unpack(">q", self._take(8))[0]
        if ttype == DOUBLE:
            return struct.unpack(">d", self._take(8))[0]
        if ttype == STRING:
            n = struct.unpack(">i", self._take(4))[0]
            raw = self._take(n)
            if binary:
                return raw                      # declared BYTES field
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError:
                return raw                      # undeclared binary blob
        if ttype == STRUCT:
            return self.read_struct(top=False)
        if ttype in (LIST, SET):
            etype = self._take(1)[0]
            n = struct.unpack(">i", self._take(4))[0]
            return [self.read_value(etype) for _ in range(n)]
        if ttype == MAP:
            kt, vt = self._take(1)[0], self._take(1)[0]
            n = struct.unpack(">i", self._take(4))[0]
            return {self.read_value(kt): self.read_value(vt)
                    for _ in range(n)}
        raise ValueError(f"unsupported thrift type {ttype}")

    def read_struct(self, top: bool = True) -> Dict[int, object]:
        """field-id → decoded value (ids keep the wire numbering).
        BYTES declarations apply to TOP-LEVEL record fields only — a
        nested struct's field ids are a different numbering space."""
        out: Dict[int, object] = {}
        while True:
            ttype = self._take(1)[0]
            if ttype == STOP:
                return out
            fid = struct.unpack(">h", self._take(2))[0]
            out[fid] = self.read_value(
                ttype, binary=top and fid in self.binary_fids)

    @property
    def exhausted(self) -> bool:
        return self.pos >= len(self.buf)


class ThriftRecordReader(RecordReader):
    """Reads back-to-back TBinaryProtocol structs into row dicts.

    Parity: ThriftRecordReader.java — next() deserializes one struct and
    projects the configured fields by id; unknown wire fields are skipped
    (decoded and dropped), absent fields yield None.
    """

    def __init__(self, path: str, config: ThriftRecordReaderConfig,
                 schema=None):
        self.path = path
        self.config = config
        self.schema = schema

    def _rows(self) -> Iterator[dict]:
        names = self.config.field_ids
        # BYTES fields: declared on the reader config, or derived from
        # the target schema's column data type (ADVICE.md — a binary
        # payload that is accidentally valid UTF-8 must stay bytes)
        bytes_names = set(self.config.bytes_fields)
        if self.schema is not None:
            from pinot_tpu.common.datatype import DataType
            bytes_names |= {f.name for f in self.schema.fields
                            if f.data_type is DataType.BYTES}
        binary_fids = frozenset(fid for name, fid in names.items()
                                if name in bytes_names)
        with open(self.path, "rb") as fh:
            proto = _BinaryProtocolReader(fh.read(), binary_fids)
        wanted = (set(names) if self.schema is None
                  else {f.name for f in self.schema.fields} & set(names))
        while not proto.exhausted:
            rec = proto.read_struct()
            yield {name: rec.get(names[name]) for name in wanted}


# ---------------------------------------------------------------------------
# Writer (tests / datagen): encode rows as TBinaryProtocol structs
# ---------------------------------------------------------------------------


def _ttype_of(v) -> int:
    if isinstance(v, bool):
        return BOOL
    if isinstance(v, int):
        return I64
    if isinstance(v, float):
        return DOUBLE
    if isinstance(v, (str, bytes)):
        return STRING
    if isinstance(v, (list, tuple)):
        return LIST
    if isinstance(v, dict):
        return MAP
    raise TypeError(f"unsupported thrift value {type(v)}")


def _encode_value(v, out: List[bytes]) -> None:
    t = _ttype_of(v)
    if t == BOOL:
        out.append(b"\x01" if v else b"\x00")
    elif t == I64:
        out.append(struct.pack(">q", v))
    elif t == DOUBLE:
        out.append(struct.pack(">d", v))
    elif t == STRING:
        raw = v.encode("utf-8") if isinstance(v, str) else v
        out.append(struct.pack(">i", len(raw)))
        out.append(raw)
    elif t == LIST:
        etype = _ttype_of(v[0]) if v else STRING
        out.append(struct.pack(">bi", etype, len(v)))
        for e in v:
            _encode_value(e, out)
    elif t == MAP:
        items = list(v.items())
        kt = _ttype_of(items[0][0]) if items else STRING
        vt = _ttype_of(items[0][1]) if items else STRING
        out.append(struct.pack(">bbi", kt, vt, len(items)))
        for k, val in items:
            _encode_value(k, out)
            _encode_value(val, out)


def write_thrift_records(path: str, rows: Sequence[dict],
                         field_ids: Optional[Dict[str, int]] = None) -> None:
    """Serialize rows as back-to-back TBinaryProtocol structs (None
    fields are omitted, like an unset optional thrift field)."""
    if field_ids is None:
        names = sorted({k for r in rows for k in r})
        field_ids = {n: i + 1 for i, n in enumerate(names)}
    out: List[bytes] = []
    for row in rows:
        for name, fid in field_ids.items():
            v = row.get(name)
            if v is None:
                continue
            out.append(struct.pack(">bh", _ttype_of(v), fid))
            _encode_value(v, out)
        out.append(b"\x00")                     # STOP
    with open(path, "wb") as fh:
        fh.write(b"".join(out))
