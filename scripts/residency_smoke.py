"""Residency smoke gate: a working set 2-4x the HBM budget must serve
with graceful degradation — zero unflagged errors, exact results, the
device ledger never above budget, and a bounded p99 penalty versus the
unbounded twin run.

Two sequential phases over the SAME on-disk segments (a skewed SSB
aggregation mix plus a vector-similarity table):

1. unbounded — no device budget (the pre-manager behavior): every
   query runs device-resident; records the answer key and baseline
   p50/p99.
2. budgeted  — deviceBytesBudget ~ 1/3 of the working set (plus a host
   budget so the coldest host-tier segments continue to disk, driving
   the full device→host→disk→host ladder). The access skew flips
   mid-run, so yesterday's hot segments must demote to admit today's,
   and disk-tier stragglers pay metered cold-hit reloads on access.

Gates:

- every response in BOTH phases is exception-free and bit-equal to the
  unbounded phase's answer for the same (query, segment-subset) — a
  demoted segment must degrade to the host/disk path, never to a wrong
  or failed answer;
- ``LEDGER.total_bytes() <= budget`` at EVERY checkpoint — eviction is
  budget-conserving, the machine-checked ledger ground truth;
- the tiering engaged: promotions > 0, demotions > 0, cold hits > 0
  (a smoke that never leaves device tier proves nothing);
- budgeted p99 <= GRACE_FACTOR x unbounded p99 + floor — degradation
  is a latency story, not a cliff.

Set RESIDENCY_ARTIFACT to write the JSON artifact (the committed
RESIDENCY_r13.json at the repo root came from this script).
"""
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# the exactness gate compares device-path and host-path answers
# bit-for-bit, which requires the same accumulator widths on both —
# x64 on, exactly like tests/conftest.py and the oracle suite
import jax  # noqa: E402
jax.config.update("jax_enable_x64", True)

ROWS = int(os.environ.get("RESIDENCY_ROWS", 4000))
SEGMENTS = int(os.environ.get("RESIDENCY_SEGMENTS", 8))
VEC_SEGMENTS = 2
VEC_N = 512
VEC_DIM = 16
QUERIES = int(os.environ.get("RESIDENCY_QUERIES", 160))
CHECK_EVERY = 20                 # ledger checkpoint cadence (queries)
BUDGET_DIVISOR = 3.0             # working set ~3x the device budget
HOST_SEGS_BUDGET = 2.5           # host tier holds ~this many segments
GRACE_FACTOR = 10.0              # budgeted p99 vs unbounded p99 bound
GRACE_FLOOR_MS = 150.0           # CI-noise floor on top of the ratio
# heat half-life is 30s of MANAGER-clock time; the driver feeds the
# manager a virtual clock advancing this much per query, so the
# hot-set flip plays out the same decay curve deterministically on any
# CI box instead of needing minutes of wall time
VIRTUAL_S_PER_QUERY = 1.5


def build_vec_dirs(base):
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (Schema, dimension, metric,
                                         vector)
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    schema = Schema("vectab", [
        dimension("shard", DataType.INT),
        metric("rid", DataType.INT),
        vector("emb", VEC_DIM),
    ])
    rng = np.random.default_rng(23)
    dirs = []
    for s in range(VEC_SEGMENTS):
        cols = {
            "shard": rng.integers(0, 4, VEC_N).astype(np.int32),
            "rid": (np.arange(VEC_N, dtype=np.int32) + s * VEC_N),
            "emb": rng.standard_normal(
                (VEC_N, VEC_DIM)).astype(np.float32),
        }
        d = os.path.join(base, f"vec_{s}")
        SegmentCreator(schema, TableConfig("vectab"),
                       segment_name=f"vec_{s}").build(cols, d)
        dirs.append(d)
    return dirs, rng.standard_normal(VEC_DIM).astype(np.float32)


def _canon(v):
    """numpy/jax scalars → python scalars: the host path hands back
    np.float32 where the device path hands a python float of the SAME
    value; the gate compares values, not container reprs."""
    return repr(v.item() if hasattr(v, "item") else v)


def result_key(dt):
    """Canonical, metadata-free view of a DataTable result for the
    exactness gate (timings and execution-path tags excluded — the
    PATH is allowed to change under pressure, the answer is not)."""
    blk = dt.to_block()
    if blk.agg_intermediates is not None:
        return tuple(_canon(v) for v in blk.agg_intermediates)
    if blk.selection_rows is not None:
        return tuple(tuple(map(_canon, r)) for r in blk.selection_rows)
    if blk.selection_cols is not None:
        rows = zip(*[list(c) for c in blk.selection_cols])
        return tuple(tuple(map(_canon, r)) for r in rows)
    if blk.group_map is not None:
        return tuple(sorted((_canon(k), _canon(v))
                            for k, v in blk.group_map.items()))
    return ("empty",)


def run_phase(ssb_dirs, vec_dirs, vec_q, budget, host_budget,
              answers=None):
    """One full serve cycle; returns (report, answers, failures)."""
    from pinot_tpu.common.metrics import MetricsRegistry, ServerMeter
    from pinot_tpu.common.request import InstanceRequest
    from pinot_tpu.obs.residency import LEDGER
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    from pinot_tpu.server.data_manager import InstanceDataManager
    from pinot_tpu.server.query_executor import InstanceQueryExecutor
    from pinot_tpu.server.residency_manager import ResidencyManager

    metrics = MetricsRegistry("server")
    clk = [0.0]
    mgr = ResidencyManager(budget, host_budget, clock=lambda: clk[0])
    mgr.bind_metrics(metrics)
    dm = InstanceDataManager()
    dm.add_removal_listener(mgr.untrack)
    executor = InstanceQueryExecutor(dm, metrics=metrics, residency=mgr)

    segs, names = [], []
    for table, dirs in (("lineorder", ssb_dirs), ("vectab", vec_dirs)):
        tdm = dm.table(table, create=True)
        for d in dirs:
            seg = ImmutableSegmentLoader.load(d)
            tdm.add_segment(seg)
            # attach admission + eager warm-up ROUTED through the
            # manager: over-budget attaches land host-tier and are
            # simply not warmed (the raw seg.warm_device() bypass is
            # what serving paths must never call)
            mgr.track(table, seg, seg_dir=d)
            mgr.warm_device(seg.segment_name)
            segs.append(seg)
            if table == "lineorder":
                names.append(seg.segment_name)

    qs = ", ".join(repr(float(x)) for x in vec_q)
    ssb_pql = compile_pql(
        "SELECT COUNT(*), SUM(lo_revenue), MAX(lo_supplycost) "
        "FROM lineorder WHERE lo_quantity < 30")
    vec_pql = compile_pql(
        f"SELECT rid, VECTOR_SIMILARITY(emb, [{qs}], 10, 'COSINE') "
        "FROM vectab")

    rng = np.random.default_rng(7)
    answers = {} if answers is None else answers
    failures = []
    lat_ms = []
    checkpoints = []
    phase_answers = {}

    for i in range(QUERIES):
        clk[0] += VIRTUAL_S_PER_QUERY
        # the skew flips mid-run to segments that attach left OFF the
        # device tier: today's hot set must be cold-hit reloaded and
        # then promoted by demoting yesterday's
        hot = names[:2] if i < QUERIES // 2 else names[4:6]
        r = rng.random()
        if r < 0.6:
            req = InstanceRequest(request_id=i, query=ssb_pql)
            req.search_segments = list(hot)
            key = ("ssb", tuple(hot))
        elif r < 0.9:
            req = InstanceRequest(request_id=i, query=ssb_pql)
            key = ("ssb", ("*",))
        else:
            req = InstanceRequest(request_id=i, query=vec_pql)
            key = ("vec", ("*",))
        t0 = time.perf_counter()
        dt = executor.execute(req)
        lat_ms.append((time.perf_counter() - t0) * 1e3)
        if dt.exceptions:
            failures.append(f"query {i} {key}: {dt.exceptions}")
            continue
        got = result_key(dt)
        phase_answers.setdefault(key, got)
        if key in answers and answers[key] != got:
            failures.append(f"query {i} {key}: answer drifted under "
                            "memory pressure")
        if (i + 1) % CHECK_EVERY == 0:
            total = LEDGER.total_bytes()
            checkpoints.append(total)
            if budget is not None and total > budget:
                failures.append(
                    f"checkpoint after query {i + 1}: ledger {total} "
                    f"bytes exceeds budget {budget}")

    def meter_total(name):
        # residency meters are tagged per table/tier; the gate cares
        # about the fleet-wide total, so sum every series of the name
        meters = metrics.metric_maps()[0]
        return sum(m.count for k, m in meters.items()
                   if k == name or k.endswith("." + name))

    lat = np.asarray(lat_ms)
    report = {
        "queries": QUERIES,
        "deviceBytesBudget": budget,
        "hostBytesBudget": host_budget,
        "latencyP50Ms": round(float(np.percentile(lat, 50)), 3),
        "latencyP99Ms": round(float(np.percentile(lat, 99)), 3),
        "latencyMaxMs": round(float(lat.max()), 3),
        "ledgerCheckpoints": checkpoints,
        "promotions": meter_total(ServerMeter.RESIDENCY_PROMOTIONS),
        "demotions": meter_total(ServerMeter.RESIDENCY_DEMOTIONS),
        "coldHits": meter_total(ServerMeter.RESIDENCY_COLD_HITS),
        "tiersAtEnd": mgr.snapshot()["tiers"],
    }
    for seg in segs:
        seg.destroy()
    mgr.shutdown()
    return report, phase_answers, failures


def main() -> int:
    from pinot_tpu.tools.datagen import build_ssb_segment_dirs

    base = tempfile.mkdtemp()
    ssb_dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "ssb"), ROWS, SEGMENTS, seed=9)
    vec_dirs, vec_q = build_vec_dirs(os.path.join(base, "vec"))

    # size the budgets off the real working set: load one of each shape
    from pinot_tpu.segment.loader import (ImmutableSegmentLoader,
                                          segment_host_bytes)
    probes = [ImmutableSegmentLoader.load(ssb_dirs[0]),
              ImmutableSegmentLoader.load(vec_dirs[0])]
    working_set = (probes[0].device_bytes_estimate() * SEGMENTS +
                   probes[1].device_bytes_estimate() * VEC_SEGMENTS)
    # host tier holds only a few evicted segments before the coldest
    # continue to disk — the second rung of the degradation ladder
    host_budget = int(segment_host_bytes(probes[0]) * HOST_SEGS_BUDGET)
    for p in probes:
        p.destroy()
    budget = int(working_set / BUDGET_DIVISOR)

    print(f"working set ~{working_set} device bytes over "
          f"{SEGMENTS}+{VEC_SEGMENTS} segments; budget {budget} "
          f"({working_set / budget:.1f}x oversubscribed), host budget "
          f"{host_budget}", file=sys.stderr)

    unbounded, answers, fail_a = run_phase(
        ssb_dirs, vec_dirs, vec_q, None, None)
    budgeted, _, fail_b = run_phase(
        ssb_dirs, vec_dirs, vec_q, budget, host_budget,
        answers=answers)

    failures = [f"[unbounded] {f}" for f in fail_a] + \
               [f"[budgeted] {f}" for f in fail_b]
    if budgeted["demotions"] == 0:
        failures.append("budgeted run performed no demotions — the "
                        "working set never pressured the budget")
    if budgeted["promotions"] == 0:
        failures.append("budgeted run performed no promotions — the "
                        "skew flip never re-admitted a hot segment")
    if budgeted["coldHits"] == 0:
        failures.append("budgeted run took no cold hits — the disk "
                        "tier was never exercised")
    p99_bound = (GRACE_FACTOR * unbounded["latencyP99Ms"] +
                 GRACE_FLOOR_MS)
    if budgeted["latencyP99Ms"] > p99_bound:
        failures.append(
            f"budgeted p99 {budgeted['latencyP99Ms']:.1f}ms exceeds "
            f"{p99_bound:.1f}ms (unbounded "
            f"{unbounded['latencyP99Ms']:.1f}ms x {GRACE_FACTOR} + "
            f"{GRACE_FLOOR_MS}ms) — degradation is a cliff, not a "
            "slope")

    report = {
        "rows": ROWS, "segments": SEGMENTS,
        "vectorSegments": VEC_SEGMENTS,
        "workingSetDeviceBytes": working_set,
        "oversubscription": round(working_set / budget, 2),
        "unbounded": unbounded,
        "budgeted": budgeted,
        "p99Ratio": round(budgeted["latencyP99Ms"] /
                          max(unbounded["latencyP99Ms"], 1e-9), 3),
        "distinctAnswerKeys": len(answers),
    }
    print(json.dumps(report, indent=1))
    artifact = os.environ.get("RESIDENCY_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print("residency smoke: " + ("OK" if not failures else "FAILED"))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
