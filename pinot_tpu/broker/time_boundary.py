"""Hybrid-table time boundary: split queries between offline and realtime.

Parity: pinot-broker/.../routing/HelixExternalViewBasedTimeBoundaryService
.java:95-132 — boundary = max end time across offline segments minus one
time-unit day (minus one hour for HOURLY-push tables); the offline
sub-query gets ``time <= boundary`` and the realtime one ``time > boundary``
(attach at BaseBrokerRequestHandler.java:430).
"""
from __future__ import annotations

import copy
import threading
from typing import Dict, Optional

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.common.timeutils import unit_ms


class TimeBoundaryInfo:
    def __init__(self, column: str, value: int):
        self.column = column
        self.value = value


class TimeBoundaryService:
    def __init__(self):
        self._boundaries: Dict[str, TimeBoundaryInfo] = {}
        self._lock = threading.Lock()

    def update_from_segments(self, offline_table: str, time_column: str,
                             time_unit: str, segment_end_times,
                             hourly_push: bool = False) -> None:
        ends = [e for e in segment_end_times if e is not None]
        if not ends:
            return
        max_end = max(int(e) for e in ends)
        u = unit_ms(time_unit)
        delta = (3_600_000 if hourly_push else 86_400_000) // u
        boundary = max_end - max(delta, 1)
        with self._lock:
            self._boundaries[offline_table] = TimeBoundaryInfo(time_column,
                                                               boundary)

    def get(self, offline_table: str) -> Optional[TimeBoundaryInfo]:
        with self._lock:
            return self._boundaries.get(offline_table)

    def remove(self, offline_table: str) -> None:
        with self._lock:
            self._boundaries.pop(offline_table, None)


def attach_time_boundary(request: BrokerRequest, info: TimeBoundaryInfo,
                         offline: bool) -> BrokerRequest:
    """Copy the request with the boundary filter AND'ed in."""
    out = copy.deepcopy(request)
    if offline:
        bound = FilterQueryTree(
            operator=FilterOperator.RANGE, column=info.column,
            lower=None, upper=str(info.value), upper_inclusive=True)
    else:
        bound = FilterQueryTree(
            operator=FilterOperator.RANGE, column=info.column,
            lower=str(info.value), lower_inclusive=False, upper=None)
    if out.filter is None:
        out.filter = bound
    else:
        out.filter = FilterQueryTree(operator=FilterOperator.AND,
                                     children=[out.filter, bound])
    return out
