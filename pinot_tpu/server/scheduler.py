"""Query schedulers: FCFS, bounded FCFS, and token-bucket priority.

Parity: pinot-core/.../core/query/scheduler/ — QuerySchedulerFactory
(algorithms "fcfs" | "bounded_fcfs" | "tokenbucket",
QuerySchedulerFactory.java:40-68). The token path is the full hierarchy:

- TokenSchedulerGroup (tokenbucket/TokenSchedulerGroup.java:31-56): per-group
  CPU-ms token accounting. Tokens drain at (elapsed_ms x threads_in_use); a
  new batch is allotted every token lifetime quantum with LINEAR DECAY
  (alpha = 0.80) so heavy users of the previous quantum start the next one
  penalized, giving sparse/low-qps groups a fair chance.
- MultiLevelPriorityQueue (MultiLevelPriorityQueue.java:38): per-group
  waitlists; the winner is the group with the most tokens (ties: earliest
  waiting query), moderated by the resource manager's soft thread limit —
  a higher-priority group already past the soft limit loses to one under
  it. Per-group capacity check on put() (OutOfCapacity), expired-query
  trimming against the query deadline.
- PriorityScheduler (PriorityScheduler.java): a dedicated scheduler thread
  gated by a running-queries semaphore takes the winner and hands it to a
  BoundedAccountingExecutor-style wrapper that reserves the group's worker
  allotment, increments threads-in-use around execution (the accounting
  the token drain reads), and releases the reservation when the query
  finishes (resources/BoundedAccountingExecutor.java:30-118).

Execution happens on a thread pool; the device serializes kernels anyway,
so scheduling decides ORDER and fairness, exactly the role it plays in the
reference.
"""
from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional


class QueryScheduler:
    """submit(group, fn) -> Future; subclasses order execution.

    `deadline_s` is the query's remaining budget (broker deadline
    propagation): schedulers that queue work drop entries whose budget
    expired before a worker picked them up — computing an answer nobody
    will read only steals tokens from live queries.

    Two pools, reference parity: query RUNNERS (`_pool`, one thread per
    admitted query — pqr threads) and query WORKERS (`segment_pool`,
    the per-segment plan executor CombineOperator fans out on — pqw
    threads). They must be distinct: a runner blocks on its segment
    futures, so per-segment work scheduled back onto the runner pool
    would deadlock once every runner waits on work none can start.
    """

    def __init__(self, num_workers: int = 4,
                 num_segment_workers: Optional[int] = None):
        self._pool = ThreadPoolExecutor(max_workers=num_workers,
                                        thread_name_prefix="query-runner")
        self.num_workers = num_workers
        self.num_segment_workers = num_segment_workers or num_workers
        self.segment_pool = ThreadPoolExecutor(
            max_workers=self.num_segment_workers,
            thread_name_prefix="query-worker")

    def submit(self, group: str, fn: Callable[[], object],
               deadline_s: Optional[float] = None) -> Future:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        self.segment_pool.shutdown(wait=False)


class FCFSQueryScheduler(QueryScheduler):
    """First-come-first-served (the reference default); unqueued, so
    deadline enforcement happens in the executor itself."""

    def submit(self, group: str, fn: Callable[[], object],
               deadline_s: Optional[float] = None) -> Future:
        return self._pool.submit(fn)


class SchedulerOutOfCapacityError(Exception):
    """Parity: OutOfCapacityException — bounded queue rejected the query."""


class SchedulerDeadlineError(Exception):
    """Query expired in the scheduler queue (trimExpired)."""


class ResourceLimitPolicy:
    """Per-group thread/queue bounds.

    Parity: core/query/scheduler/resources/ResourceLimitPolicy — soft and
    hard per-group thread limits as fractions of total workers, plus a
    pending-queue bound.
    """

    def __init__(self, num_workers: int,
                 max_threads_per_group_pct: float = 0.5,
                 soft_threads_per_group_pct: float = 0.3,
                 max_pending_per_group: int = 64):
        self.table_threads_hard_limit = max(
            1, int(num_workers * max_threads_per_group_pct))
        self.table_threads_soft_limit = max(
            1, int(num_workers * soft_threads_per_group_pct))
        self.max_pending_per_group = max_pending_per_group


class TokenSchedulerGroup:
    """Per-group token accounting with linear decay.

    Parity: tokenbucket/TokenSchedulerGroup.java:31-56. One token = 1ms of
    one thread's wall clock. Every group is over-provisioned with
    num_tokens_per_ms == total workers (work-stealing: an idle cluster
    always has schedulable tokens). Token replenishment happens lazily in
    consume_tokens(): drain by elapsed*threads within the current quantum,
    then per elapsed quantum apply

        tokens = ALPHA * lifetime * per_ms + (1-ALPHA) * (tokens - lifetime * threads)

    — the linear decay that remembers last-quantum utilization and
    penalizes heavy users so sparse groups win the next comparisons.
    """

    ALPHA = 0.80

    def __init__(self, name: str, num_tokens_per_ms: int,
                 token_lifetime_ms: int = 100,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.num_tokens_per_ms = num_tokens_per_ms
        self.token_lifetime_ms = token_lifetime_ms
        self._clock = clock
        now = self._now_ms()
        self.available_tokens = float(num_tokens_per_ms * token_lifetime_ms)
        self._last_update_ms = now
        self._last_token_ms = now
        self.threads_in_use = 0
        self.reserved_threads = 0
        self.pending: deque = deque()   # SchedulerQueryContext entries
        self._lock = threading.Lock()

    def _now_ms(self) -> float:
        return self._clock() * 1e3

    def consume_tokens(self) -> float:
        """Lazy drain + quantum replay with linear decay."""
        with self._lock:
            now = self._now_ms()
            diff = now - self._last_update_ms
            if diff <= 0:
                return self.available_tokens
            threads = self.threads_in_use
            next_token = self._last_token_ms + self.token_lifetime_ms
            if next_token > now:
                self.available_tokens -= diff * threads
            else:
                self.available_tokens -= \
                    (next_token - self._last_update_ms) * threads
                # quantum catch-up in closed form: the per-quantum update
                # t' = A + B*(t - C) with A = ALPHA*L*N, B = 1-ALPHA,
                # C = L*threads is affine, so k quanta give
                # t_k = B^k * t0 + (A - B*C) * (1 - B^k) / (1 - B)
                # — O(1) however long the group idled (a naive replay
                # loop runs 864k iterations for a day-idle group, inside
                # the priority-queue lock). NOTE: the first replayed
                # quantum subtracts the full C even though its partial
                # in-quantum usage was already drained above — that IS
                # the reference's exact arithmetic
                # (TokenSchedulerGroup.consumeTokens: the decay loop
                # runs after the boundary drain and subtracts
                # tokenLifetimeMs*threads every iteration), kept for
                # behavioral parity
                k = int((now - next_token) // self.token_lifetime_ms) + 1
                a = self.ALPHA * self.token_lifetime_ms * \
                    self.num_tokens_per_ms
                b = 1 - self.ALPHA
                c = self.token_lifetime_ms * threads
                bk = b ** min(k, 1024)      # b^1024 == 0.0 in float64
                self.available_tokens = (
                    bk * self.available_tokens +
                    (a - b * c) * (1 - bk) / (1 - b))
                self._last_token_ms = next_token + \
                    (k - 1) * self.token_lifetime_ms
                self.available_tokens -= (now - self._last_token_ms) * threads
            self._last_update_ms = now
            return self.available_tokens

    # -- thread accounting (BoundedAccountingExecutor hooks) ---------------
    def increment_threads(self) -> None:
        self.consume_tokens()
        with self._lock:
            self.threads_in_use += 1

    def decrement_threads(self) -> None:
        self.consume_tokens()
        with self._lock:
            self.threads_in_use -= 1

    def add_reserved(self, n: int) -> None:
        with self._lock:
            self.reserved_threads += n

    def release_reserved(self, n: int) -> None:
        with self._lock:
            self.reserved_threads -= n

    def total_reserved_threads(self) -> int:
        return self.reserved_threads

    def compare_key(self):
        """Sort key: more tokens wins; ties go FCFS by arrival."""
        arrival = self.pending[0].arrival_ms if self.pending else float("inf")
        return (-self.consume_tokens(), arrival)

    def stats(self) -> dict:
        return {"name": self.name,
                "availableTokens": round(self.consume_tokens(), 1),
                "numPending": len(self.pending),
                "threadsInUse": self.threads_in_use,
                "reservedThreads": self.reserved_threads}


class SchedulerQueryContext:
    """One queued query (parity: SchedulerQueryContext.java)."""

    __slots__ = ("group", "fn", "future", "arrival_ms", "seq",
                 "deadline_ms")

    def __init__(self, group: str, fn: Callable[[], object], seq: int,
                 arrival_ms: float,
                 deadline_ms: Optional[float] = None):
        self.group = group
        self.fn = fn
        self.future: Future = Future()
        self.arrival_ms = arrival_ms
        self.seq = seq
        # absolute clock instant (ms) after which the query's broker
        # stops listening; None = only the scheduler-wide deadline
        self.deadline_ms = deadline_ms


class MultiLevelPriorityQueue:
    """Token-priority queue over per-group waitlists.

    Parity: MultiLevelPriorityQueue.java:38 — put() enforces per-group
    capacity; take_next() trims expired queries, then picks the group with
    the highest token priority subject to the soft-limit moderation:
    a winner past the soft thread limit yields to a contender under it.
    """

    def __init__(self, policy: ResourceLimitPolicy, num_workers: int,
                 token_lifetime_ms: int = 100,
                 query_deadline_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.num_workers = num_workers
        self.token_lifetime_ms = token_lifetime_ms
        self.query_deadline_s = query_deadline_s
        self._clock = clock
        self._groups: Dict[str, TokenSchedulerGroup] = {}  # tpulint: disable=cache-bound -- one group per table: bounded by tables hosted on this server
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._seq = 0

    def group(self, name: str) -> TokenSchedulerGroup:
        """Get-or-create a group (takes the lock; tpulint concurrency
        found the scheduler thread calling the unlocked variant —
        two threads racing the same name could each build and account
        against their own TokenSchedulerGroup)."""
        with self._lock:
            return self._group_locked(name)

    def _group_locked(self, name: str) -> TokenSchedulerGroup:
        g = self._groups.get(name)
        if g is None:
            g = TokenSchedulerGroup(name, self.num_workers,
                                    self.token_lifetime_ms, self._clock)
            self._groups[name] = g  # tpulint: disable=concurrency -- every caller holds self._lock (enforced by the public group())
        return g

    def put(self, group_name: str, fn: Callable[[], object],
            deadline_s: Optional[float] = None) -> SchedulerQueryContext:
        with self._lock:
            g = self._group_locked(group_name)
            if len(g.pending) >= self.policy.max_pending_per_group and \
                    g.total_reserved_threads() >= \
                    self.policy.table_threads_hard_limit:
                raise SchedulerOutOfCapacityError(
                    f"group {group_name} out of capacity: "
                    f"{len(g.pending)} pending >= "
                    f"{self.policy.max_pending_per_group}, "
                    f"{g.total_reserved_threads()} reserved >= "
                    f"{self.policy.table_threads_hard_limit}")
            now_ms = self._clock() * 1e3
            ctx = SchedulerQueryContext(
                group_name, fn, self._seq, now_ms,
                None if deadline_s is None else now_ms + deadline_s * 1e3)
            self._seq += 1
            g.pending.append(ctx)
            self._not_empty.notify()
            return ctx

    def remove(self, ctx: SchedulerQueryContext) -> bool:
        """Un-queue a context (closes the submit/shutdown race)."""
        with self._lock:
            g = self._groups.get(ctx.group)
            if g is not None and ctx in g.pending:
                g.pending.remove(ctx)
                return True
        return False

    def _trim_expired(self, g: TokenSchedulerGroup) -> None:
        now_ms = self._clock() * 1e3
        oldest_ok = now_ms - self.query_deadline_s * 1e3
        # scheduler-wide deadline: FIFO order makes the front oldest
        while g.pending and g.pending[0].arrival_ms < oldest_ok:
            ctx = g.pending.popleft()
            ctx.future.set_exception(SchedulerDeadlineError(
                f"query for group {g.name} expired after "
                f"{self.query_deadline_s}s in scheduler queue"))
        # per-query propagated deadlines are NOT monotone in arrival
        # order (budgets differ per query) — scan the whole waitlist
        expired = [ctx for ctx in g.pending
                   if ctx.deadline_ms is not None and
                   ctx.deadline_ms <= now_ms]
        for ctx in expired:
            g.pending.remove(ctx)
            ctx.future.set_exception(SchedulerDeadlineError(
                f"query for group {g.name} missed its propagated "
                "deadline in the scheduler queue"))

    def take_next(self, timeout: float = 0.02
                  ) -> Optional[SchedulerQueryContext]:
        """Winner group's oldest query, or None after `timeout`.

        put() and wake() notify the condition, so dispatch latency does
        not depend on the timeout — it only bounds how often the idle
        scheduler thread re-scans (the reference busy-polls at 1ms,
        QUEUE_WAKEUP_MICROS; 20ms here cuts idle scanning ~20x with the
        same responsiveness because our put() signals)."""
        with self._lock:
            winner = self._take_internal()
            if winner is None:
                self._not_empty.wait(timeout)
                winner = self._take_internal()
            return winner

    def wake(self) -> None:
        """Re-evaluate schedulability (called when reserved threads are
        released — a hard-limited group may have become eligible — and on
        shutdown so the scheduler thread exits promptly)."""
        with self._lock:
            self._not_empty.notify_all()

    def _take_internal(self) -> Optional[SchedulerQueryContext]:
        soft = self.policy.table_threads_soft_limit
        hard = self.policy.table_threads_hard_limit
        winner: Optional[TokenSchedulerGroup] = None
        wkey = None
        for g in self._groups.values():
            self._trim_expired(g)
            if not g.pending or g.total_reserved_threads() >= hard:
                continue          # canSchedule == False
            if winner is None:
                winner, wkey = g, g.compare_key()
                continue
            key = g.compare_key()
            if key > wkey:        # lower priority than current winner
                # ...unless the winner is past the soft limit and this
                # group is under it (soft-limit moderation)
                if winner.total_reserved_threads() > soft and \
                        g.total_reserved_threads() < soft:
                    winner, wkey = g, key
                continue
            # higher (or equal) priority: take it if it is under the soft
            # limit or leaner than the current winner
            if g.total_reserved_threads() < soft or \
                    g.total_reserved_threads() < \
                    winner.total_reserved_threads():
                winner, wkey = g, key
        if winner is None:
            return None
        return winner.pending.popleft()

    def drain(self) -> List[SchedulerQueryContext]:
        out: List[SchedulerQueryContext] = []
        with self._lock:
            for g in self._groups.values():
                while g.pending:
                    out.append(g.pending.popleft())
        return out

    def stats(self) -> List[dict]:
        with self._lock:
            return [g.stats() for g in self._groups.values()]


class TokenBucketScheduler(QueryScheduler):
    """Priority scheduling by hierarchical per-group token accounting.

    Parity: tokenbucket/TokenPriorityScheduler + PriorityScheduler.java —
    a dedicated scheduler thread gated by a running-queries semaphore pulls
    the token-priority winner from the MultiLevelPriorityQueue and runs it
    under BoundedAccountingExecutor-style accounting: the group's worker
    allotment is reserved up front, threads-in-use is incremented around
    execution (driving the token drain), and both are released at the end.
    """

    TOKEN_LIFETIME_MS = 100

    def __init__(self, num_workers: int = 4,
                 policy: Optional[ResourceLimitPolicy] = None,
                 query_deadline_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(num_workers)
        self.policy = policy or ResourceLimitPolicy(
            num_workers, max_pending_per_group=1024)
        self.queue = MultiLevelPriorityQueue(
            self.policy, num_workers, self.TOKEN_LIFETIME_MS,
            query_deadline_s, clock)
        self._sem = threading.Semaphore(num_workers)
        self._running = True
        self._thread = threading.Thread(target=self._scheduler_loop,
                                        name="scheduler", daemon=True)
        self._thread.start()

    def submit(self, group: str, fn: Callable[[], object],
               deadline_s: Optional[float] = None) -> Future:
        if not self._running:
            f: Future = Future()
            f.set_exception(RuntimeError("scheduler is shut down"))
            return f
        try:
            ctx = self.queue.put(group, fn, deadline_s=deadline_s)
        except SchedulerOutOfCapacityError as e:
            f = Future()
            f.set_exception(e)
            return f
        if not self._running and self.queue.remove(ctx):
            # shutdown raced the put() in: the drain already ran, so fail
            # the context here rather than leave its future unresolved
            ctx.future.set_exception(RuntimeError("scheduler is shut down"))
        return ctx.future

    def _scheduler_loop(self) -> None:
        while self._running:
            self._sem.acquire()
            ctx = None
            g = None
            reserved = 0
            try:
                while self._running and ctx is None:
                    ctx = self.queue.take_next()
                if ctx is None:      # shutting down
                    self._sem.release()
                    break
                g = self.queue.group(ctx.group)
                # BoundedAccountingExecutor: reserve the group's worker
                # allotment before execution (1 runner per query here —
                # the per-segment fan-out runs inside the device kernel)
                g.add_reserved(1)
                reserved = 1
                g.consume_tokens()   # startQuery accounting point
                self._pool.submit(self._run, ctx, g, reserved)
            except Exception as e:  # noqa: BLE001 — scheduler must survive
                # a dequeued query must never hang its caller: fail the
                # future and undo the reservation before moving on
                if reserved and g is not None:
                    g.release_reserved(reserved)
                if ctx is not None and not ctx.future.done():
                    ctx.future.set_exception(e)
                self._sem.release()

    def _run(self, ctx: SchedulerQueryContext, g: TokenSchedulerGroup,
             bounds: int) -> None:
        try:
            if not ctx.future.set_running_or_notify_cancel():
                return
            g.increment_threads()
            try:
                ctx.future.set_result(ctx.fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                ctx.future.set_exception(e)
            finally:
                g.decrement_threads()
        finally:
            g.release_reserved(bounds)
            g.consume_tokens()       # endQuery accounting point
            self._sem.release()
            self.queue.wake()        # a hard-limited group may be eligible

    def group_stats(self) -> List[dict]:
        return self.queue.stats()

    def shutdown(self) -> None:
        self._running = False  # tpulint: disable=concurrency -- single irreversible flip of a GIL-atomic bool; readers poll it, no compound invariant
        self.queue.wake()
        for ctx in self.queue.drain():
            ctx.future.set_exception(RuntimeError("scheduler is shut down"))
        super().shutdown()


def make_scheduler(algorithm: str = "fcfs", num_workers: int = 4
                   ) -> QueryScheduler:
    """Parity: QuerySchedulerFactory.create (falls back to FCFS)."""
    if algorithm == "tokenbucket":
        return TokenBucketScheduler(num_workers)
    if algorithm == "bounded_fcfs":
        return BoundedFCFSScheduler(num_workers)
    return FCFSQueryScheduler(num_workers)


class BoundedFCFSScheduler(QueryScheduler):
    """Per-group FCFS with bounded per-group resources.

    Parity: fcfs/BoundedFCFSScheduler + PolicyBasedResourceManager — FCFS
    order across groups (oldest pending first), but a group already at
    its thread limit is skipped, and a group with a full pending queue
    rejects new queries instead of growing without bound.
    """

    def __init__(self, num_workers: int = 4,
                 policy: Optional[ResourceLimitPolicy] = None):
        super().__init__(num_workers)
        self.policy = policy or ResourceLimitPolicy(num_workers)
        self._pending: Dict[str, list] = {}  # tpulint: disable=cache-bound -- one queue per table (bounded by hosted tables); each queue is capped at max_pending_per_group with a typed reject
        self._running: Dict[str, int] = {}  # tpulint: disable=cache-bound -- per-table running counters: bounded by hosted tables
        self._order: list = []            # (seq, group) FCFS across groups
        self._seq = 0
        self._lock = threading.Lock()

    def submit(self, group: str, fn: Callable[[], object],
               deadline_s: Optional[float] = None) -> Future:
        future: Future = Future()
        with self._lock:
            q = self._pending.setdefault(group, [])
            if len(q) >= self.policy.max_pending_per_group:
                future.set_exception(SchedulerOutOfCapacityError(
                    f"group {group}: {len(q)} pending >= "
                    f"{self.policy.max_pending_per_group}"))
                return future
            q.append((fn, future))
            heapq.heappush(self._order, (self._seq, group))
            self._seq += 1
        self._pool.submit(self._drain)
        return future

    def _next(self):
        """Oldest pending entry whose group is under its thread limit."""
        skipped = []
        try:
            while self._order:
                seq, group = heapq.heappop(self._order)
                if not self._pending.get(group):
                    continue            # stale order entry
                if self._running.get(group, 0) >= \
                        self.policy.table_threads_hard_limit:
                    skipped.append((seq, group))
                    continue
                fn, future = self._pending[group].pop(0)
                self._running[group] = self._running.get(group, 0) + 1  # tpulint: disable=concurrency -- only caller is _drain, which holds self._lock
                return group, fn, future
            return None
        finally:
            for item in skipped:
                heapq.heappush(self._order, item)

    def _drain(self) -> None:
        with self._lock:
            item = self._next()
        if item is None:
            return
        group, fn, future = item
        try:
            if future.set_running_or_notify_cancel():
                try:
                    future.set_result(fn())
                except BaseException as e:  # noqa: BLE001
                    future.set_exception(e)
        finally:
            with self._lock:
                self._running[group] -= 1
                more = any(self._pending.values())
            if more:
                self._pool.submit(self._drain)


# ---------------------------------------------------------------------------
# Cross-query dispatch coalescing
# ---------------------------------------------------------------------------


class BatchGroup:
    """An open admission window for one plan-shape key.

    Members accumulate until seal(); the group's deadline is the
    TIGHTEST member deadline (a batch must not let a late joiner relax
    an early member's budget — the whole batch answers by the earliest
    promise). All mutation happens under the owning coalescer's lock.
    """

    __slots__ = ("key", "created_s", "deadline_s", "members", "sealed")

    def __init__(self, key, created_s: float,
                 deadline_s: Optional[float], member):
        self.key = key
        self.created_s = created_s
        self.deadline_s = deadline_s
        self.members: List = [member]
        self.sealed = False


class DispatchCoalescer:
    """Same-plan-shape queries share one kernel execution.

    State machine per key (the instance layer supplies the key — table
    + plan-shape + segment set — and opaque members):

    - ``solo``:   nothing with this key is in flight → execute
                  immediately; the window costs an idle query NOTHING.
    - ``bypass``: same-key work is in flight but this member's budget
                  cannot survive the window → execute immediately.
    - ``lead``:   same-key work is in flight → open a window; the
                  caller schedules a runner that sleeps out
                  remaining_window_s() then seal()s and executes the
                  batch.
    - ``joined``: an open unsealed window exists → appended to it.

    solo/bypass/sealed-group executions each count as one in-flight
    dispatch for their key until the caller's ``leave(key)``; seal() is
    idempotent (runner and failure callback may race) and returns the
    members exactly once, so a member future is resolved by exactly one
    path.
    """

    def __init__(self, window_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 on_dispatch: Optional[Callable[[int], None]] = None,
                 on_bypass: Optional[Callable[[], None]] = None):
        self.window_s = float(window_s)
        # a member bypasses when its remaining budget is under this
        # multiple of the window: surviving the sleep is not enough, it
        # still has to execute afterwards
        self.min_slack_windows = 2.0
        self._clock = clock
        self._on_dispatch = on_dispatch
        self._on_bypass = on_bypass
        self._lock = threading.Lock()
        self._inflight: Dict[object, int] = {}
        self._open: Dict[object, BatchGroup] = {}

    def arrive(self, key, member, deadline_s: Optional[float]):
        """Returns (state, group): state in {"solo", "bypass", "joined",
        "lead"}; group is set for joined/lead."""
        bypass = False
        with self._lock:
            g = self._open.get(key)
            if g is not None and not g.sealed:
                g.members.append(member)
                if deadline_s is not None:
                    g.deadline_s = deadline_s if g.deadline_s is None \
                        else min(g.deadline_s, deadline_s)
                return "joined", g
            inflight = self._inflight.get(key, 0)
            now = self._clock()
            if inflight == 0:
                self._inflight[key] = 1
                return "solo", None
            if deadline_s is not None and \
                    deadline_s - now < self.min_slack_windows * \
                    self.window_s:
                self._inflight[key] = inflight + 1
                bypass = True
            else:
                g = BatchGroup(key, now, deadline_s, member)
                self._open[key] = g
                return "lead", g
        if bypass and self._on_bypass is not None:
            self._on_bypass()
        return "bypass", None

    def joinable(self, key) -> bool:
        """An open, unsealed window exists for this key (the hedge-join
        admission carve-out reads this)."""
        with self._lock:
            g = self._open.get(key)
            return g is not None and not g.sealed

    def remaining_window_s(self, group: BatchGroup) -> float:
        return max(0.0, group.created_s + self.window_s - self._clock())

    def seal(self, group: BatchGroup) -> List:
        """Close the window and take its members; [] if already sealed.
        The sealed group counts as one in-flight dispatch until the
        caller's leave(key)."""
        with self._lock:
            if group.sealed:
                return []
            group.sealed = True
            if self._open.get(group.key) is group:
                del self._open[group.key]
            self._inflight[group.key] = \
                self._inflight.get(group.key, 0) + 1
            members = list(group.members)
        if self._on_dispatch is not None:
            self._on_dispatch(len(members))
        return members

    def leave(self, key) -> None:
        """A solo/bypass/sealed-group execution for this key finished."""
        with self._lock:
            n = self._inflight.get(key, 0) - 1
            if n <= 0:
                self._inflight.pop(key, None)
            else:
                self._inflight[key] = n
