from pinot_tpu.server.data_manager import (InstanceDataManager,
                                           SegmentDataManager,
                                           TableDataManager)
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.query_executor import InstanceQueryExecutor
from pinot_tpu.server.scheduler import (FCFSQueryScheduler,
                                        TokenBucketScheduler, make_scheduler)

__all__ = ["InstanceDataManager", "SegmentDataManager", "TableDataManager",
           "ServerInstance", "InstanceQueryExecutor", "FCFSQueryScheduler",
           "TokenBucketScheduler", "make_scheduler"]
