"""HTTP client for the LLC segment-completion protocol.

Parity: the server side of SegmentCompletionProtocol — the reference's
ServerSegmentCompletionProtocolHandler POSTs segmentConsumed /
segmentStoppedConsuming / segmentCommitStart / segmentCommitEnd to the
lead controller's REST API.  This client exposes the same four-method
interface as the in-process RealtimeSegmentManager, so
RealtimeTableDataManager works unchanged in a multi-process deployment
(tools/distributed.py wires it when a controller HTTP address is given).
"""
from __future__ import annotations

import json
import logging
import urllib.error
import urllib.parse
import urllib.request

from pinot_tpu.common.completion import CompletionResponse

log = logging.getLogger(__name__)

#: the ACTIVE controller's HTTP base, published by the lead controller
#: at boot/takeover (the /CONTROLLER/DEEPSTORE_BASE pattern) — servers
#: re-resolve the completion endpoint from it after a failover
CONTROLLER_ENDPOINT_PATH = "/CONTROLLER/ENDPOINT"


class HttpSegmentCompletionClient:
    def __init__(self, controller: str = None, timeout: float = 60.0,
                 store=None):
        """`controller`: host:port of the controller's HTTP API.
        `store`: optional property store — when given, the ACTIVE
        controller endpoint published at /CONTROLLER/ENDPOINT overrides
        `controller`, and a connection failure re-resolves it and
        retries once, so a standby-controller takeover doesn't strand
        this server's completion protocol on the dead leader."""
        if controller is None and store is None:
            raise ValueError("no controller endpoint: pass `controller` "
                             "or a store publishing "
                             f"{CONTROLLER_ENDPOINT_PATH}")
        self.base = f"http://{controller}" if controller else None
        self.timeout = timeout
        self.store = store
        if self.store is not None:
            # best-effort eager resolve; a missing record is NOT a boot
            # failure (servers may start before any controller has led)
            # — the first _post resolves lazily, and _completion_call
            # retries the ConnectionError until a leader publishes
            self._resolve()

    def _resolve(self) -> bool:
        """Refresh self.base from the published record; True on change."""
        try:
            rec = self.store.get(CONTROLLER_ENDPOINT_PATH) or {}
        except Exception:  # noqa: BLE001 — store hiccup: keep old base
            return False
        base = rec.get("base")
        if base and base.rstrip("/") != self.base:
            log.info("completion endpoint re-resolved: %s -> %s",
                     self.base, base)
            self.base = base.rstrip("/")
            return True
        return False

    def _post(self, path: str, params: dict, body: bytes = None) -> dict:
        try:
            return self._post_once(path, params, body)
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError):
            # the controller may have failed over: re-resolve the
            # active endpoint from the store and retry once. Completion
            # ops are idempotent at the controller (reports re-enter
            # the FSM; a duplicate commit_end fails the election check).
            if self.store is None or not self._resolve():
                raise
            return self._post_once(path, params, body)

    def _post_once(self, path: str, params: dict,
                   body: bytes = None) -> dict:
        if self.base is None:
            # boot-order independence: no endpoint known yet (store-only
            # construction before any leader published) — resolve now or
            # surface a retriable connection error
            if not self._resolve() and self.base is None:
                raise ConnectionError(
                    f"no controller endpoint published at "
                    f"{CONTROLLER_ENDPOINT_PATH} yet")
        url = f"{self.base}{path}?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/octet-stream"}
            if body else {})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def segment_consumed(self, table: str, segment: str, instance: str,
                         offset: int) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentConsumed", {"table": table, "name": segment,
                                 "instance": instance, "offset": offset}))

    def stopped_consuming(self, table: str, segment: str, instance: str,
                          reason: str = "") -> None:
        self._post("/segmentStoppedConsuming",
                   {"table": table, "name": segment, "instance": instance,
                    "reason": reason})

    def extend_build_time(self, table: str, segment: str,
                          instance: str, extra_ms: float = 60_000.0
                          ) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentExtendBuildTime",
            {"table": table, "name": segment, "instance": instance,
             "extraTimeMs": str(extra_ms)}))

    def commit_start(self, table: str, segment: str, instance: str,
                     offset: int) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentCommitStart", {"table": table, "name": segment,
                                    "instance": instance,
                                    "offset": offset}))

    def commit_end(self, table: str, segment: str, instance: str,
                   offset: int, segment_dir: str) -> CompletionResponse:
        from pinot_tpu.controller.http_api import pack_segment_dir
        return CompletionResponse.from_json(self._post(
            "/segmentCommitEnd", {"table": table, "name": segment,
                                  "instance": instance, "offset": offset},
            body=pack_segment_dir(segment_dir)))
