"""Networked property store tests: server, client, watches, ephemerals.

Parity: the ZooKeeper role in the reference — remote cluster-state store
with watch push and ephemeral-node liveness (docs/architecture.rst).
"""
import threading
import time

import pytest

from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.controller.store_client import (RemotePropertyStore,
                                               StoreClosedError)
from pinot_tpu.controller.store_server import PropertyStoreServer


@pytest.fixture()
def server():
    srv = PropertyStoreServer()
    srv.start()
    yield srv
    srv.stop()


def _client(server, **kw):
    return RemotePropertyStore("127.0.0.1", server.port, **kw)


def test_basic_ops_roundtrip(server):
    c = _client(server)
    try:
        assert c.get("/a") is None
        c.set("/a/b", {"x": 1})
        c.set("/a/c", {"y": [1, 2, {"z": "s"}]})
        assert c.get("/a/b") == {"x": 1}
        assert c.get("/a/c") == {"y": [1, 2, {"z": "s"}]}
        assert c.children("/a") == ["b", "c"]
        assert c.list_paths("/a") == ["/a/b", "/a/c"]
        assert c.remove("/a/b") is True
        assert c.remove("/a/b") is False
        assert c.get("/a/b") is None
    finally:
        c.close()


def test_update_cas_loop_under_contention(server):
    n_threads, n_incr = 4, 25
    clients = [_client(server) for _ in range(n_threads)]
    try:
        clients[0].set("/counter", {"n": 0})

        def bump(c):
            for _ in range(n_incr):
                c.update("/counter", lambda rec: {"n": (rec or {"n": 0})["n"]
                                                  + 1})

        threads = [threading.Thread(target=bump, args=(c,))
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert clients[0].get("/counter") == {"n": n_threads * n_incr}
    finally:
        for c in clients:
            c.close()


def test_watch_push_across_clients(server):
    a, b = _client(server), _client(server)
    try:
        events = []
        got = threading.Event()

        def cb(path, rec):
            events.append((path, rec))
            if len(events) >= 3:
                got.set()

        a.watch("/EXTERNALVIEW/", cb)
        b.set("/EXTERNALVIEW/t1", {"segments": {"s0": {"i0": "ONLINE"}}})
        b.set("/OTHER/t1", {"ignored": True})   # outside prefix: no event
        b.set("/EXTERNALVIEW/t2", {"segments": {}})
        b.remove("/EXTERNALVIEW/t1")
        assert got.wait(5), events
        assert events[0] == ("/EXTERNALVIEW/t1",
                             {"segments": {"s0": {"i0": "ONLINE"}}})
        assert events[1] == ("/EXTERNALVIEW/t2", {"segments": {}})
        assert events[2] == ("/EXTERNALVIEW/t1", None)
    finally:
        a.close()
        b.close()


def test_ephemeral_paths_vanish_on_disconnect(server):
    a, b = _client(server), _client(server)
    try:
        seen = []
        gone = threading.Event()

        def cb(path, rec):
            seen.append((path, rec))
            if rec is None:
                gone.set()

        b.watch("/LIVEINSTANCES/", cb)
        a.set("/LIVEINSTANCES/Server_9", {"tags": ["T"]}, ephemeral=True)
        a.set("/CONFIGS/stay", {"k": 1})          # persistent
        deadline = time.monotonic() + 5
        while b.get("/LIVEINSTANCES/Server_9") is None:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        a.close()                                  # session death
        assert gone.wait(5), seen
        assert b.get("/LIVEINSTANCES/Server_9") is None
        assert b.get("/CONFIGS/stay") == {"k": 1}  # persists
    finally:
        b.close()


def test_shared_store_with_inprocess_side(server):
    """The controller holds the in-process store; remote clients see the
    same tree (the deployment shape: store server runs in the controller)."""
    local: PropertyStore = server.store
    c = _client(server)
    try:
        local.set("/CONFIGS/TABLE/t", {"v": 1})
        assert c.get("/CONFIGS/TABLE/t") == {"v": 1}
        c.set("/CONFIGS/TABLE/u", {"v": 2})
        assert local.get("/CONFIGS/TABLE/u") == {"v": 2}
        # watches registered locally fire for remote writes
        fired = threading.Event()
        local.watch("/CONFIGS/", lambda p, r: fired.set())
        c.set("/CONFIGS/TABLE/w", {"v": 3})
        assert fired.wait(5)
    finally:
        c.close()


def test_client_errors(server):
    c = _client(server)
    try:
        with pytest.raises(ConnectionError):
            RemotePropertyStore("127.0.0.1", 1)    # nothing listening
    finally:
        c.close()
    with pytest.raises(StoreClosedError):
        c.get("/x")                                # after close


def test_local_cas_semantics():
    s = PropertyStore()
    assert s.cas("/p", None, {"v": 1}) is True
    assert s.cas("/p", None, {"v": 2}) is False
    assert s.cas("/p", {"v": 1}, {"v": 2}) is True
    assert s.get("/p") == {"v": 2}


def test_bind_conflict_raises_instead_of_hanging(server):
    s2 = PropertyStoreServer(port=server.port)
    with pytest.raises(OSError, match="cannot bind"):
        s2.start()


def test_malformed_frame_keeps_connection_alive(server):
    import json
    import socket
    import struct

    sock = socket.create_connection(("127.0.0.1", server.port))
    try:
        bad = b"not json"
        sock.sendall(struct.pack(">I", len(bad)) + bad)
        n = struct.unpack(">I", sock.recv(4))[0]
        resp = json.loads(sock.recv(n))
        assert resp["ok"] is False and resp["id"] is None
        good = json.dumps({"id": 7, "op": "ping"}).encode()
        sock.sendall(struct.pack(">I", len(good)) + good)
        n = struct.unpack(">I", sock.recv(4))[0]
        assert json.loads(sock.recv(n)) == {"id": 7, "ok": True}
    finally:
        sock.close()
