"""Minion plane: background segment-maintenance tasks.

Parity: pinot-minion (worker + executor SPI) and
pinot-controller helix/core/minion (task manager + generators),
rebuilt on the cluster property store instead of the Helix Task
Framework.
"""
from pinot_tpu.minion.executors import (CONVERT_TO_RAW_TASK,
                                        MERGE_ROLLUP_TASK, PURGE_TASK,
                                        UPSERT_COMPACTION_TASK,
                                        MinionContext, PinotTaskExecutor,
                                        TaskExecutorRegistry,
                                        UpsertCompactionTaskExecutor)
from pinot_tpu.minion.task_manager import (ConvertToRawIndexTaskGenerator,
                                           MergeRollupTaskGenerator,
                                           PinotTaskGenerator,
                                           PinotTaskManager,
                                           PurgeTaskGenerator,
                                           UpsertCompactionTaskGenerator)
from pinot_tpu.minion.tasks import (COMPLETED, ERROR, GENERATED,
                                    IN_PROGRESS, PinotTaskConfig, TaskQueue)
from pinot_tpu.minion.worker import (MinionEventObserver,
                                     MinionWorker)

__all__ = [
    "CONVERT_TO_RAW_TASK", "MERGE_ROLLUP_TASK", "PURGE_TASK",
    "UPSERT_COMPACTION_TASK",
    "MinionContext", "PinotTaskExecutor", "TaskExecutorRegistry",
    "UpsertCompactionTaskExecutor",
    "ConvertToRawIndexTaskGenerator", "MergeRollupTaskGenerator",
    "PinotTaskGenerator",
    "PinotTaskManager", "PurgeTaskGenerator",
    "UpsertCompactionTaskGenerator", "COMPLETED", "ERROR",
    "GENERATED", "IN_PROGRESS", "PinotTaskConfig", "TaskQueue",
    "MinionEventObserver",
    "MinionWorker",
]
