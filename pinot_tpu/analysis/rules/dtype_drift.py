"""dtype-drift: 64-bit literals on the JAX path, int32 overflow casts.

On TPU, x64 is disabled: a ``dtype=jnp.float64``/``int64`` reaching a
``jnp`` op is SILENTLY downcast to 32 bits — sums lose integer
exactness past 2^24 (f32) and doc-id math wraps past 2^31. The flip
side: narrowing a fresh arithmetic result straight to int32 (e.g. a
doc-count × width product) overflows for the 100M-row segments this
engine targets. Host-side numpy 64-bit math is exempt — that's where
exact combines are SUPPOSED to happen.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

_WIDE = {"jax.numpy.float64", "jax.numpy.int64", "jax.numpy.uint64",
         "numpy.float64", "numpy.int64", "numpy.uint64"}
_WIDE_STR = {"float64", "int64", "uint64"}
_NARROW_I32 = {"jax.numpy.int32", "numpy.int32"}


def _dtype_is_wide(node: ast.AST, aliases) -> Optional[str]:
    d = astutil.resolve(node, aliases)
    if d in _WIDE:
        return d
    s = astutil.const_str(node)
    if s in _WIDE_STR:
        return s
    return None


def _contains_arith(node: ast.AST) -> bool:
    """Growth-capable arithmetic over at least one non-constant operand
    (a pure-literal expression like ``2**31 - 1`` can't overflow at
    runtime — it's a compile-time constant)."""
    has_op = any(isinstance(n, ast.BinOp) and
                 isinstance(n.op, (ast.Mult, ast.Add, ast.Pow, ast.LShift))
                 for n in ast.walk(node))
    has_var = any(isinstance(n, (ast.Name, ast.Attribute, ast.Subscript,
                                 ast.Call))
                  for n in ast.walk(node))
    return has_op and has_var


@register
class DtypeDriftRule(Rule):
    id = "dtype-drift"
    description = ("64-bit dtypes reaching jnp ops (silently downcast "
                   "when x64 is off) and int32 casts of arithmetic "
                   "results (doc-id overflow)")

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = astutil.resolve(node.func, ctx.aliases)
            # jnp.full(..., dtype=jnp.int64) and friends
            if callee and callee.startswith("jax."):
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    wide = _dtype_is_wide(kw.value, ctx.aliases)
                    if wide:
                        yield ctx.finding(
                            self.id, kw.value,
                            f"dtype={wide} passed to {callee} — silently "
                            "downcast to 32 bits when x64 is disabled "
                            "(TPU default); keep 64-bit math host-side")
            # jnp.int64(x) / jnp.float64(x) scalar constructors
            if callee in ("jax.numpy.int64", "jax.numpy.uint64",
                          "jax.numpy.float64"):
                yield ctx.finding(
                    self.id, node,
                    f"{callee.replace('jax.numpy.', 'jnp.')}(...) is a "
                    "32-bit value when x64 is disabled — the wide width "
                    "exists only on the CPU/x64 test path")
            # (a * b).astype(np.int32): narrowing a fresh product
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args:
                target = astutil.resolve(node.args[0], ctx.aliases) or \
                    astutil.const_str(node.args[0])
                if target in _NARROW_I32 or target == "int32":
                    if isinstance(node.func.value, ast.BinOp) and \
                            _contains_arith(node.func.value):
                        yield ctx.finding(
                            self.id, node,
                            "int32 cast applied directly to an arithmetic "
                            "result — doc-id scale products overflow "
                            "int32; combine in int64 first, narrow last")
            # np.int32(a * b)
            if callee in _NARROW_I32 and node.args and \
                    isinstance(node.args[0], ast.BinOp) and \
                    _contains_arith(node.args[0]):
                yield ctx.finding(
                    self.id, node,
                    "int32() around an arithmetic expression — doc-id "
                    "scale products overflow int32; compute in int64 "
                    "and narrow after bounds-checking")
