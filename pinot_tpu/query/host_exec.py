"""Host (numpy) query executor — the fallback + CPU baseline path.

Covers query shapes the device kernels don't (group cardinality over the
groups limit, order-by keys too wide to pack, percentile over raw columns)
and doubles as the CPU reference implementation the benchmarks compare
against. Produces IntermediateResultsBlock objects merge-compatible with the
device path.

Parity note: this is the moral equivalent of the reference's scan-based
operators (ScanBasedFilterOperator + DefaultAggregationExecutor /
DefaultGroupByExecutor / SelectionOperator) executed columnar-vectorized.

DELIBERATE TWIN DECISION (round 5): this module and ops/kernels.py both
implement the full operator semantics. The duplication is intentional,
not accidental: (a) the host twin doubles as the INDEPENDENT oracle the
randomized agreement sweeps (tests/test_query_generator.py) compare the
device path against — sharing a predicate-resolution layer would make
the two paths fail together; (b) the performance-critical layouts
diverge fundamentally (dictId-interval compares on padded lanes vs
member-vector gathers on exact arrays), so a shared abstraction would
be an interface with two disjoint implementations anyway. The cost — a
new scalar function must be added twice — is bounded by the agreement
sweep, which fails loudly when one side is missing or diverges.
"""
# tpulint: disable-file=host-sync -- every value on this path is host
# numpy by construction (the device kernels never run here), so the
# kernel-path sync heuristics don't apply.
from __future__ import annotations

import re as _re
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.common.sketches import HyperLogLog, TDigest
from pinot_tpu.query.aggregation import AggregationFunction, make_functions
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.segment.loader import DataSource, ImmutableSegment


def _upsert_valid_mask(segment) -> Optional[np.ndarray]:
    """Per-doc liveness mask for upsert tables, or None. Mutable
    snapshot views carry a PINNED `valid_doc_mask`; immutable segments
    snapshot their live ValidDocIds bitmap here (realtime/upsert.py)."""
    vm = getattr(segment, "valid_doc_mask", None)
    if vm is not None:
        return vm
    vd = getattr(segment, "valid_doc_ids", None)
    if vd is not None and vd.num_invalid:
        return vd.valid_mask(0, segment.num_docs)
    return None


def execute_host(segment: ImmutableSegment, request: BrokerRequest
                 ) -> IntermediateResultsBlock:
    mask = _eval_filter(request.filter, segment)
    vm = _upsert_valid_mask(segment)
    if vm is not None:
        # superseded rows are masked BEFORE any aggregation/selection —
        # the host half of the host-vs-device upsert parity contract
        mask = mask & vm
    dimrow = None
    jctx = getattr(request, "_join_ctx", None)
    if jctx is not None:
        # inner-join probe (the oracle twin of the fused device probe):
        # rows without a dim match mask out BEFORE aggregation, exactly
        # like the kernel's join predicate — and after the vdoc mask,
        # so dead upserted rows never join here either
        hit, dimrow = _join_probe(segment, jctx)
        mask = mask & hit
    blk = IntermediateResultsBlock()
    matched = int(mask.sum())

    if request.is_group_by:
        _group_by(segment, request, mask, blk, jctx=jctx, dimrow=dimrow)
    elif request.is_aggregation:
        blk.agg_intermediates = [
            _aggregate(segment, f, mask) for f in make_functions(
                request.aggregations)]
    if request.vector is not None:
        # ANN probing narrows the candidate set inside _vector_topk;
        # the returned count keeps scanned-docs stats identical to the
        # device path's fused-filter accounting
        matched = _vector_topk(segment, request, mask, blk)
    elif request.is_selection:
        _selection(segment, request, mask, blk)

    blk.stats = ExecutionStats(
        num_docs_scanned=matched,
        num_entries_scanned_in_filter=(
            _count_leaves(request.filter) * segment.num_docs),
        num_segments_processed=1,
        num_segments_matched=1 if matched else 0,
        total_docs=segment.num_docs)
    return blk


def _count_leaves(tree: Optional[FilterQueryTree]) -> int:
    if tree is None:
        return 0
    if tree.is_leaf():
        return 1
    return sum(_count_leaves(c) for c in tree.children)


# ---------------------------------------------------------------------------
# Filter evaluation (vectorized numpy over decoded / id lanes)
# ---------------------------------------------------------------------------


def _eval_filter(tree: Optional[FilterQueryTree], segment: ImmutableSegment
                 ) -> np.ndarray:
    n = segment.num_docs
    if tree is None:
        return np.ones(n, dtype=bool)
    if tree.operator in (FilterOperator.AND, FilterOperator.OR):
        masks = [_eval_filter(c, segment) for c in tree.children]
        out = masks[0]
        for m in masks[1:]:
            out = (out & m) if tree.operator == FilterOperator.AND else \
                (out | m)
        return out
    return _eval_leaf(tree, segment)


def _expr_rows(text: str, segment: ImmutableSegment) -> np.ndarray:
    """Row-domain expression evaluation (host fallback / mutable path).

    Memoized per segment object (immutable segments are immutable; mutable
    segments are queried through per-query snapshot views, so the cache is
    naturally query-scoped there)."""
    cache = getattr(segment, "_expr_cache", None)
    if cache is None:
        try:
            cache = segment._expr_cache = {}
        except AttributeError:      # __slots__ or frozen object
            cache = None
    if cache is not None and text in cache:
        return cache[text]

    def resolve(c: str) -> np.ndarray:
        ds = segment.data_source(c)
        cm = ds.metadata
        if not cm.single_value:
            raise ValueError(f"MV column {c} in expression")
        if cm.has_dictionary:
            return np.asarray(ds.dictionary.values)[ds.dict_ids]
        return ds.raw_values

    out = np.asarray(expr_mod.evaluate(text, resolve))
    if cache is not None:
        if len(cache) > 32:
            cache.clear()
        cache[text] = out
    return out


def _eval_expr_leaf(tree: FilterQueryTree, segment: ImmutableSegment
                    ) -> np.ndarray:
    from pinot_tpu.query.plan import _pred_over_values
    vals = _expr_rows(tree.column, segment).astype(np.float64)
    return _pred_over_values(tree, vals)


def _eval_leaf(tree: FilterQueryTree, segment: ImmutableSegment) -> np.ndarray:
    if expr_mod.is_expression(tree.column):
        return _eval_expr_leaf(tree, segment)
    ds = segment.data_source(tree.column)
    cm = ds.metadata
    n = segment.num_docs
    op = tree.operator

    if op == FilterOperator.IS_NULL:
        return np.zeros(n, dtype=bool)
    if op == FilterOperator.IS_NOT_NULL:
        return np.ones(n, dtype=bool)

    if not cm.has_dictionary:
        vals = ds.raw_values
        cv = _coercer(cm.data_type)
        if op == FilterOperator.EQUALITY:
            return vals == cv(tree.values[0])
        if op == FilterOperator.NOT:
            return vals != cv(tree.values[0])
        if op == FilterOperator.IN:
            return np.isin(vals, [cv(v) for v in tree.values])
        if op == FilterOperator.NOT_IN:
            return ~np.isin(vals, [cv(v) for v in tree.values])
        if op == FilterOperator.RANGE:
            m = np.ones(n, dtype=bool)
            if tree.lower is not None:
                lo = cv(tree.lower)
                m &= (vals >= lo) if tree.lower_inclusive else (vals > lo)
            if tree.upper is not None:
                hi = cv(tree.upper)
                m &= (vals <= hi) if tree.upper_inclusive else (vals < hi)
            return m
        if op == FilterOperator.REGEXP_LIKE:
            import re
            pattern = re.compile(str(tree.values[0]))
            return np.fromiter(
                (pattern.search(str(v)) is not None for v in vals),
                dtype=bool, count=len(vals))
        raise ValueError(f"unsupported raw filter {op}")

    # dictionary-encoded: resolve to id-domain predicate, then test lanes
    dictionary = ds.dictionary
    card = dictionary.cardinality
    member = np.zeros(card + 1, dtype=bool)  # slot card = MV padding
    if op == FilterOperator.EQUALITY:
        i = dictionary.index_of(tree.values[0])
        if i >= 0:
            member[i] = True
    elif op == FilterOperator.NOT:
        member[:card] = True
        i = dictionary.index_of(tree.values[0])
        if i >= 0:
            member[i] = False
    elif op == FilterOperator.IN:
        for v in tree.values:
            i = dictionary.index_of(v)
            if i >= 0:
                member[i] = True
    elif op == FilterOperator.NOT_IN:
        member[:card] = True
        for v in tree.values:
            i = dictionary.index_of(v)
            if i >= 0:
                member[i] = False
    elif op == FilterOperator.RANGE:
        if getattr(dictionary, "is_sorted", True):
            lo, hi = dictionary.range_to_id_interval(
                tree.lower, tree.upper, tree.lower_inclusive,
                tree.upper_inclusive)
            member[lo:hi] = True
        else:
            # mutable (arrival-order) dictionary: compare every value
            vals = dictionary.values
            m = np.ones(card, dtype=bool)
            if cm.data_type.is_numeric:
                cv = _coercer(cm.data_type)
            else:
                cv = str
            if tree.lower is not None:
                lo_v = cv(tree.lower)
                m &= (vals >= lo_v) if tree.lower_inclusive else (vals > lo_v)
            if tree.upper is not None:
                hi_v = cv(tree.upper)
                m &= (vals <= hi_v) if tree.upper_inclusive else (vals < hi_v)
            member[:card] = m
    elif op == FilterOperator.REGEXP_LIKE:
        pat = _re.compile(tree.values[0])
        for i in range(card):
            if pat.search(str(dictionary.get(i))):
                member[i] = True
    else:
        raise ValueError(f"unsupported filter {op}")

    if cm.single_value:
        return member[ds.dict_ids]
    return member[ds.mv_dict_ids].any(axis=1)


def _coercer(data_type):
    """Predicate-literal coercion for a column's DataType (raw columns
    compare in the value domain: hex literals become bytes for BYTES,
    everything else numeric/str)."""
    dt = data_type.np_dtype
    if dt.kind == "f":
        return lambda v: dt.type(float(v))
    if dt.kind in "iu":
        return lambda v: dt.type(int(str(v)))
    from pinot_tpu.common.datatype import DataType as _DT
    if data_type == _DT.BYTES:
        return lambda v: v if isinstance(v, bytes) \
            else bytes.fromhex(str(v))
    return str          # chunked raw string columns compare as strings


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------


def _masked_values(segment: ImmutableSegment, col: str, mask: np.ndarray
                   ) -> np.ndarray:
    src = _mv_group_source(segment, col)
    if src is not None:                  # MV column or valuein(mvcol, ...)
        vals, _counts = _mv_entries(src[0], src[1], np.nonzero(mask)[0])
        return vals
    if expr_mod.is_expression(col):
        return _expr_rows(col, segment)[mask]
    ds = segment.data_source(col)
    cm = ds.metadata
    if not cm.has_dictionary:
        return ds.raw_values[mask]
    return ds.dictionary.values[ds.dict_ids[mask]]


def _hll_derived(segment: ImmutableSegment, col: str) -> bool:
    """True when `col` is a derived serialized-HLL column (its values are
    hex sketches to union, not raw values to hash)."""
    try:
        cm = segment.data_source(col).metadata
    except KeyError:
        return False
    return getattr(cm, "derived_metric_type", None) == "HLL"


def _aggregate(segment: ImmutableSegment, f: AggregationFunction,
               mask: np.ndarray):
    base = f.info.base
    if base == "COUNT" and not f.info.is_mv:
        return int(mask.sum())
    if f.info.is_mv and _mv_group_source(segment, f.column) is None:
        raise ValueError(
            f"{base}MV needs a multi-value column, got {f.column}")
    vals = _masked_values(segment, f.column, mask)
    if base == "COUNT":  # COUNTMV: entries
        return int(len(vals))
    if len(vals) == 0:
        return None
    if base == "SUM":
        return float(np.sum(np.asarray(vals, dtype=np.float64)))
    if base == "MIN":
        return float(vals.min())
    if base == "MAX":
        return float(vals.max())
    if base == "AVG":
        return (float(np.sum(np.asarray(vals, dtype=np.float64))), len(vals))
    if base == "MINMAXRANGE":
        return (float(vals.min()), float(vals.max()))
    if base == "DISTINCTCOUNT":
        return set(_plain(v) for v in np.unique(vals))
    if base in ("DISTINCTCOUNTHLL", "FASTHLL", "DISTINCTCOUNTRAWHLL"):
        if base == "FASTHLL" and _hll_derived(segment, f.column):
            from pinot_tpu.common.sketches import union_serialized_hlls
            return union_serialized_hlls(np.unique(vals))
        return HyperLogLog.from_values(np.unique(vals))
    if base == "PERCENTILE":
        uniq, counts = np.unique(vals, return_counts=True)
        return {_plain(u): int(c) for u, c in zip(uniq, counts)}
    if base in ("PERCENTILEEST", "PERCENTILETDIGEST"):
        uniq, counts = np.unique(np.asarray(vals, dtype=np.float64),
                                 return_counts=True)
        return TDigest.from_values(uniq, weights=counts)
    raise ValueError(base)


# ---------------------------------------------------------------------------
# Join probe (host twin of the fused device join predicate)
# ---------------------------------------------------------------------------


def _join_probe(segment: ImmutableSegment, jctx):
    """(hit mask [n], dim row index [n]) for the fact key column —
    value-domain searchsorted against the JoinContext's dim keys, so
    mutable (arrival-order-dictionary) segments probe exactly like
    committed ones."""
    from pinot_tpu.query.plan import _join_key_source
    n = segment.num_docs
    if jctx.empty:
        return np.zeros(n, dtype=bool), np.zeros(n, dtype=np.int64)
    source, ds = _join_key_source(jctx, segment)
    if source == "sv":
        vals = np.asarray(ds.dictionary.values)[ds.dict_ids]
    else:
        vals = ds.raw_values
    return jctx.probe_values(vals[:n])


# ---------------------------------------------------------------------------
# Group-by
# ---------------------------------------------------------------------------


def _valuein_parts(c: str):
    """(column, literal texts) if ``c`` is ``valuein(col, lit, ...)``,
    else None (shared validation: expression.valuein_parts)."""
    if not expr_mod.is_expression(c):
        return None
    return expr_mod.valuein_parts(c)


def _mv_group_source(segment: ImmutableSegment, c: str):
    """(data source, allowed-dictId bool mask | None) when ``c`` is an MV
    dictionary column or ``valuein(mvcol, ...)``; None for scalar keys.

    Parity: DefaultGroupByExecutor.aggregateGroupByMV — MV keys
    contribute one group entry per (doc, value); ValueInTransformFunction
    restricts the value set (`core/operator/transform/transformer`)."""
    vi = _valuein_parts(c)
    name = vi[0] if vi else c
    if expr_mod.is_expression(name):
        return None
    ds = segment.data_source(name)
    cm = ds.metadata
    if cm.single_value or not cm.has_dictionary:
        if vi:
            raise ValueError(
                f"valuein needs a dictionary-encoded MV column, got {name}")
        return None
    allowed = None
    if vi:
        allowed = np.zeros(cm.cardinality, dtype=bool)
        ids = ds.dictionary.index_of_many(vi[1])
        allowed[ids[ids >= 0]] = True
    return ds, allowed


def _mv_entries(ds, allowed, row2doc: np.ndarray):
    """Per-row MV entries for the given doc rows: (values, counts) where
    counts[i] is row i's entry count and values holds the entries
    row-major (padding slots — id == cardinality — and, for valuein,
    disallowed values are dropped)."""
    card = ds.metadata.cardinality
    ids = ds.mv_dict_ids[row2doc]                 # [rows, width]
    valid = ids < card
    if allowed is not None:
        valid &= allowed[np.clip(ids, 0, card - 1)]
    counts = valid.sum(axis=1)
    values = np.asarray(ds.dictionary.values)[ids[valid]]
    return values, counts


def _group_value_rows(segment: ImmutableSegment, c: str,
                      row2doc: np.ndarray) -> np.ndarray:
    """Row values for one scalar group-by key (column or expression) over
    the expanded row space (row2doc maps rows back to doc ids)."""
    if expr_mod.is_expression(c):
        return _expr_rows(c, segment)[row2doc]
    ds = segment.data_source(c)
    cm = ds.metadata
    if cm.has_dictionary and cm.single_value:
        return np.asarray(ds.dictionary.values)[ds.dict_ids[row2doc]]
    if not cm.has_dictionary:
        return ds.raw_values[row2doc]
    raise ValueError(f"host group-by needs SV column {c}")


def _group_by(segment: ImmutableSegment, request: BrokerRequest,
              mask: np.ndarray, blk: IntermediateResultsBlock,
              jctx=None, dimrow=None) -> None:
    gcols = request.group_by.columns
    join = request.join if jctx is not None else None
    # MV keys expand the row space: one row per (doc, value) — and per
    # value combination when several keys are MV (reference cross-product
    # semantics, DefaultGroupByExecutor.aggregateGroupByMV). Scalar keys
    # and aggregations then index rows through row2doc.
    row2doc = np.nonzero(mask)[0]
    mv_lanes: Dict[int, np.ndarray] = {}
    for idx, c in enumerate(gcols):
        if join is not None and join.qualifies(c):
            continue            # dim-side keys are scalar by contract
        src = _mv_group_source(segment, c)
        if src is None:
            continue
        values, counts = _mv_entries(src[0], src[1], row2doc)
        rep = np.repeat(np.arange(len(row2doc)), counts)
        row2doc = row2doc[rep]
        for k in mv_lanes:
            mv_lanes[k] = mv_lanes[k][rep]
        mv_lanes[idx] = values
    # per-key-column unique coding (value domain, so plain columns,
    # no-dictionary columns and transform expressions all group uniformly)
    codes: List[np.ndarray] = []
    uniq_vals: List[np.ndarray] = []
    for idx, c in enumerate(gcols):
        lane = mv_lanes.get(idx)
        if lane is None and join is not None and join.qualifies(c):
            # dim-side group key: decode through the matched dim row
            # (mask already guarantees every surviving row has one)
            lane = jctx.dim_values(join.unqualify(c))[dimrow[row2doc]]
        if lane is None:
            lane = _group_value_rows(segment, c, row2doc)
        u, inv = np.unique(lane, return_inverse=True)
        uniq_vals.append(u)
        codes.append(inv.astype(np.int64))
    key = np.zeros(len(row2doc), dtype=np.int64)
    for u, inv in zip(uniq_vals, codes):
        key = key * max(len(u), 1) + inv
    uniq_keys, inverse = np.unique(key, return_inverse=True)
    g = len(uniq_keys)

    # decode group values
    value_cols = []
    rem = uniq_keys.copy()
    for u in reversed(uniq_vals):
        value_cols.append(u[rem % max(len(u), 1)])
        rem //= max(len(u), 1)
    value_cols.reverse()
    group_keys = [tuple(_plain(vc[i]) for vc in value_cols) for i in range(g)]

    functions = make_functions(request.aggregations)
    per_fn: List[List] = []
    for f in functions:
        base = f.info.base
        if base == "COUNT" and (f.column == "*" or not f.info.is_mv):
            counts = np.zeros(g, dtype=np.int64)
            np.add.at(counts, inverse, 1)
            per_fn.append([int(c) for c in counts])
            continue
        # MV aggregation argument (SUMMV/COUNTMV/... or valuein(...)):
        # one contribution per (row, entry) — reference aggregateGroupByMV.
        # Non-suffixed aggregations over MV columns keep the engine-wide
        # entry-flattening semantics (the device kernels' source=="mv"
        # path does the same); only *MV over a single-value column is
        # rejected. COUNT stays row-count — COUNTMV is the entry count.
        src = _mv_group_source(segment, f.column)
        if src is None and f.info.is_mv:
            raise ValueError(
                f"{base}MV needs a multi-value column, got {f.column}")
        if src is not None:
            vals, ecounts = _mv_entries(src[0], src[1], row2doc)
            inv_f = np.repeat(inverse, ecounts)
        else:
            vals = _group_value_rows(segment, f.column, row2doc)
            inv_f = inverse
        if base == "COUNT":              # COUNTMV: entries per group
            counts = np.zeros(g, dtype=np.int64)
            np.add.at(counts, inv_f, 1)
            per_fn.append([int(c) for c in counts])
            continue
        if base not in ("DISTINCTCOUNT", "DISTINCTCOUNTHLL", "FASTHLL",
                        "DISTINCTCOUNTRAWHLL"):
            vals = vals.astype(np.float64)   # distinct bases keep strings
        if base in ("SUM", "AVG"):
            sums = np.zeros(g)
            np.add.at(sums, inv_f, vals)
            if base == "SUM":
                per_fn.append([float(s) for s in sums])
            else:
                counts = np.zeros(g, dtype=np.int64)
                np.add.at(counts, inv_f, 1)
                per_fn.append([(float(s), int(c))
                               for s, c in zip(sums, counts)])
        elif base in ("MIN", "MAX", "MINMAXRANGE"):
            mins = np.full(g, np.inf)
            maxs = np.full(g, -np.inf)
            np.minimum.at(mins, inv_f, vals)
            np.maximum.at(maxs, inv_f, vals)
            if base == "MIN":
                per_fn.append([float(v) for v in mins])
            elif base == "MAX":
                per_fn.append([float(v) for v in maxs])
            else:
                per_fn.append([(float(a), float(b))
                               for a, b in zip(mins, maxs)])
        else:
            # set/map/sketch intermediates per group
            items: List = [None] * g
            for gi in range(g):
                sel = vals[inv_f == gi]
                if base == "DISTINCTCOUNT":
                    items[gi] = set(_plain(v) for v in np.unique(sel))
                elif base in ("DISTINCTCOUNTHLL", "FASTHLL", "DISTINCTCOUNTRAWHLL"):
                    if base == "FASTHLL" and _hll_derived(segment, f.column):
                        from pinot_tpu.common.sketches import \
                            union_serialized_hlls
                        items[gi] = union_serialized_hlls(np.unique(sel))
                    else:
                        items[gi] = HyperLogLog.from_values(np.unique(sel))
                elif base == "PERCENTILE":
                    u, c = np.unique(sel, return_counts=True)
                    items[gi] = {_plain(x): int(y) for x, y in zip(u, c)}
                else:
                    u, c = np.unique(sel, return_counts=True)
                    items[gi] = TDigest.from_values(u, weights=c)
            per_fn.append(items)

    blk.group_map = {
        group_keys[i]: [per_fn[fi][i] for fi in range(len(functions))]
        for i in range(g)}


# ---------------------------------------------------------------------------
# Vector similarity (exact filtered top-k — the oracle twin of the
# device kernel's "vector" selection kind)
# ---------------------------------------------------------------------------


def _np_tree_sum(x: np.ndarray) -> np.ndarray:
    """Balanced pairwise f32 sum over the last (pow2) axis — the host
    half of the score exactness contract (kernels.vec_tree_sum): both
    sides run the SAME sequence of IEEE f32 adds, so scores agree
    bit-for-bit with the device kernel."""
    x = np.asarray(x, np.float32)
    while x.shape[-1] > 1:
        x = x[..., 0::2] + x[..., 1::2]
    return x[..., 0]


def _np_vector_scores(mat: np.ndarray, query, metric: str) -> np.ndarray:
    """float32 [n] similarity scores over pow2-dim-padded operands."""
    dim = mat.shape[1]
    dim_pad = 1
    while dim_pad < max(dim, 1):
        dim_pad *= 2
    m = np.zeros((len(mat), dim_pad), np.float32)
    m[:, :dim] = mat
    q = np.zeros(dim_pad, np.float32)
    q[:dim] = np.asarray(query, np.float32)
    dot = _np_tree_sum(m * q[None, :])
    if metric == "cosine":
        q_norm = np.float32(np.sqrt(_np_tree_sum(q * q)))
        if not q_norm > 0:
            raise ValueError("COSINE similarity needs a non-zero, finite "
                             "query vector")
        denom = np.sqrt(_np_tree_sum(m * m)).astype(np.float32) * q_norm
        with np.errstate(divide="ignore", invalid="ignore"):
            scores = (dot / denom).astype(np.float32)
        scores[~(denom > 0)] = -np.inf
        return scores
    return dot.astype(np.float32)


def _vector_topk(segment: ImmutableSegment, request: BrokerRequest,
                 mask: np.ndarray, blk: IntermediateResultsBlock) -> int:
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.request import VECTOR_RESULT_COLUMNS
    v = request.vector
    ds = segment.data_source(v.column)
    cm = ds.metadata
    if cm.data_type != DataType.VECTOR:
        raise ValueError(
            f"VECTOR_SIMILARITY over non-VECTOR column '{v.column}'")
    if len(v.query) != cm.vector_dimension:
        raise ValueError(
            f"query vector has {len(v.query)} dimensions; column "
            f"'{v.column}' stores {cm.vector_dimension}")
    # wire-arrived requests bypass the parser/planner guards, so the
    # host twin re-validates k and metric itself
    if v.k <= 0:
        raise ValueError(f"VECTOR_SIMILARITY k must be positive, "
                         f"got {v.k}")
    metric = v.metric.lower()
    if metric == "mips":
        metric = "dot"
    if metric not in ("cosine", "dot"):
        raise ValueError(f"unknown similarity metric '{v.metric}' "
                         "(COSINE | DOT | MIPS)")
    # ANN probe: nprobe>0 with a built IVF index narrows the candidate
    # mask to rows whose coarse cell is in the query's top-nprobe list.
    # The numpy twins in index/ivf.py select the SAME probe ids (same
    # tree sums, monotone-int32 keys, tie-breaking) as the device pred,
    # so host and device agree on the probed candidate set bit-exactly.
    # Segments without an index (and consuming tails) stay exact.
    nprobe = int(getattr(v, "nprobe", 0) or 0)
    if nprobe > 0 and getattr(ds, "ivf_centroids", None) is not None \
            and getattr(ds, "ivf_assignments", None) is not None:
        from pinot_tpu.index import ivf as ivf_mod
        dim = cm.vector_dimension
        q = np.zeros(ivf_mod.pad_dim(dim), np.float32)
        q[:dim] = np.asarray(v.query, np.float32)
        q_norm = np.float32(np.sqrt(_np_tree_sum(q * q)))
        nprobe_eff = min(nprobe, ivf_mod.pad_centroids(
            int(ds.ivf_centroids.shape[0])))
        probed = ivf_mod.probe_mask_np(
            np.asarray(ds.ivf_assignments, np.int32),
            ds.host_operand("ivfc"), ds.host_operand("ivfv"),
            q, q_norm, metric, nprobe_eff)
        aligned = np.zeros(len(mask), bool)
        aligned[: len(probed)] = probed[: len(mask)]
        mask = mask & aligned
    # score ONLY the filter's candidates: per-row scores are independent
    # of which other rows are scored (the tree contract is per-row), so
    # this is bit-identical to scoring everything at a fraction of the
    # work on selective queries
    docids = np.nonzero(mask)[0]
    num_candidates = len(docids)
    s = _np_vector_scores(ds.vec_values[docids], v.query, metric)
    # rank: score desc, docid asc — lexsort's LAST key is primary, and
    # stability gives equal scores ascending docids (the device kernel's
    # top_k tie-break)
    order = np.lexsort((docids, -s))[: v.k]
    docids = docids[order]
    s = s[order]

    # consuming tail views report GLOBAL docids under the base segment
    # name, so frozen+tail merges are indistinguishable from a
    # whole-segment pass (same contract as the device finish)
    from pinot_tpu.query.execution import vector_segment_identity
    name, base = vector_segment_identity(segment)

    user_cols = list(request.selection.columns) if request.selection else []
    decoded = {}
    for c in user_cols:
        cds = segment.data_source(c)
        ccm = cds.metadata
        if ccm.data_type == DataType.VECTOR:
            decoded[c] = [[float(x) for x in row]
                          for row in cds.vec_values[docids]]
        elif not ccm.has_dictionary:
            decoded[c] = cds.raw_values[docids]
        elif ccm.single_value:
            decoded[c] = cds.dictionary.values[cds.dict_ids[docids]]
        else:
            card = ccm.cardinality
            decoded[c] = [
                [_plain(cds.dictionary.get(i)) for i in row if i < card]
                for row in cds.mv_dict_ids[docids]]
    rows = []
    for r in range(len(docids)):
        rows.append(tuple(_plain(decoded[c][r]) for c in user_cols) +
                    (int(docids[r]) + base, name, float(s[r])))
    blk.selection_rows = rows
    blk.selection_columns = user_cols + list(VECTOR_RESULT_COLUMNS)
    blk.selection_display_cols = None
    return num_candidates


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------


def _selection(segment: ImmutableSegment, request: BrokerRequest,
               mask: np.ndarray, blk: IntermediateResultsBlock) -> None:
    from pinot_tpu.query.plan import selection_columns
    sel = request.selection
    cols = selection_columns(segment, request)
    extras = [ob.column for ob in (sel.order_by or [])
              if ob.column not in cols]
    docids = np.nonzero(mask)[0]
    if sel.order_by:
        sort_keys = []
        for ob in reversed(sel.order_by):  # lexsort: last key is primary
            ds = segment.data_source(ob.column)
            cm = ds.metadata
            if getattr(ds, "vec_values", None) is not None:
                raise ValueError("order-by on VECTOR column (use "
                                 "VECTOR_SIMILARITY for ranked results)")
            if cm.has_dictionary and cm.single_value:
                k = ds.dict_ids[docids].astype(np.int64)
            elif not cm.has_dictionary:
                k = ds.raw_values[docids]
            else:
                raise ValueError("order-by on MV column")
            if k.dtype.kind == "O":
                # strings/bytes: rank-encode so DESC can negate
                _u, k = np.unique(k, return_inverse=True)
            sort_keys.append(-k if not ob.ascending else k)
        order = np.lexsort(sort_keys)
        docids = docids[order]
    docids = docids[: sel.offset + sel.size]

    rows = []
    decoded = {}
    display_n = len(cols)
    cols = cols + extras
    for c in cols:
        ds = segment.data_source(c)
        cm = ds.metadata
        if getattr(ds, "vec_values", None) is not None:
            decoded[c] = [[float(x) for x in row]
                          for row in ds.vec_values[docids]]
        elif not cm.has_dictionary:
            decoded[c] = ds.raw_values[docids]
        elif cm.single_value:
            decoded[c] = ds.dictionary.values[ds.dict_ids[docids]]
        else:
            card = cm.cardinality
            decoded[c] = [
                [_plain(ds.dictionary.get(i)) for i in row if i < card]
                for row in ds.mv_dict_ids[docids]]
    for r in range(len(docids)):
        rows.append(tuple(_plain(decoded[c][r]) for c in cols))
    blk.selection_rows = rows
    blk.selection_columns = cols
    blk.selection_display_cols = display_n


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()  # tpulint: disable=host-sync -- np.generic scalar: isinstance-guarded, host value
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v
