"""Cluster resource manager: tables, segments, assignment, deep store.

Parity: pinot-controller/.../helix/core/PinotHelixResourceManager.java (the
cluster-ops god object): create/update tables, addNewSegment
(:1579-1604 — segment metadata write + ideal-state update via the
assignment strategy), delete segments, rebalance entry; segment upload
keeps the artifact in the deep store (PinotFS) for servers to fetch.

Store layout (beyond state_machine.py's):
  /CONFIGS/TABLE/<table>       table config JSON
  /CONFIGS/SCHEMA/<name>       schema JSON
  /SEGMENTS/<table>/<segment>  segment metadata (download path, time range)
"""
from __future__ import annotations

import glob
import os
import time
from typing import Dict, List, Optional

from pinot_tpu.common.cluster_state import CONSUMING, ONLINE
from pinot_tpu.common.filesystem import LocalPinotFS, PinotFS
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.assignment import (SegmentAssignmentStrategy,
                                             make_assignment)
from pinot_tpu.controller.quota import (StorageQuotaChecker, dir_size_bytes,
                                        parse_storage_size)
from pinot_tpu.controller.state_machine import (ClusterCoordinator, DROPPED)
from pinot_tpu.controller.tenants import (BROKER_RESOURCE, DEFAULT_TENANT,
                                          TenantManager, broker_tenant_tag,
                                          server_tenant_tag)
from pinot_tpu.segment.metadata import SegmentMetadata

TABLE_CONFIGS = "/CONFIGS/TABLE"
SCHEMAS = "/CONFIGS/SCHEMA"
SEGMENTS = "/SEGMENTS"


class InvalidTableConfigError(ValueError):
    """Malformed table config — REST maps this to 400, not 404/500."""


def _validate_table_config(config: TableConfig) -> None:
    """Reject malformed configs at create/update time, not first use
    (parity: TableConfigUtils.validate — e.g. an unparseable
    quota.storage must fail the config call, not every later upload)."""
    quota = config.quota_config
    if quota is not None and quota.storage:
        try:
            parse_storage_size(quota.storage)
        except ValueError as e:
            raise InvalidTableConfigError(str(e)) from None


class ResourceManager:
    def __init__(self, coordinator: ClusterCoordinator, deep_store_dir: str,
                 fs: Optional[PinotFS] = None,
                 maintain_broker_resource: bool = True):
        """`maintain_broker_resource`: whether THIS manager owns the
        /BROKERRESOURCE records (watching live instances and rewriting
        on membership change). True for the controller process; server/
        broker processes construct read-only managers and must pass
        False — a single writer, like the reference's Helix controller
        owning the broker resource ideal state."""
        self.coordinator = coordinator
        self.store = coordinator.store
        self.deep_store_dir = deep_store_dir
        self.fs = fs or LocalPinotFS()
        self.fs.mkdir(deep_store_dir)
        self._assignments: Dict[str, SegmentAssignmentStrategy] = {}
        self._quota_checker = StorageQuotaChecker()
        # when set (e.g. "http://controller:9000"), segment records
        # advertise downloadPath through the controller's /deepstore
        # endpoints instead of the raw filesystem path — the deployment
        # shape where servers have no shared filesystem and download
        # committed artifacts over HTTP (parity: the reference's
        # controller VIP download URLs in SegmentZKMetadata)
        self.download_base: Optional[str] = None
        self.tenants = TenantManager(self.store)
        # broker membership follows live-instance records (registration,
        # death, tag changes) — the OWNING manager watches them so
        # /BROKERRESOURCE/<table> never goes stale for clients' dynamic
        # broker selectors
        self._live_watcher = None
        if maintain_broker_resource:
            from pinot_tpu.controller.state_machine import LIVE as _LIVE
            self._live_watcher = lambda path, rec: \
                self.refresh_all_broker_resources()
            self.store.watch(_LIVE + "/", self._live_watcher)

    def close(self) -> None:
        if self._live_watcher is not None:
            self.store.unwatch(self._live_watcher)

    # -- schemas & tables --------------------------------------------------
    def add_schema(self, schema: Schema) -> None:
        # structural validation at create time (parity: Schema.validate):
        # today the only per-field invariant is the VECTOR family's —
        # DIMENSION, single-value, 1 <= dimension <= MAX_VECTOR_DIMENSION
        try:
            schema.validate()
        except ValueError as e:
            raise InvalidTableConfigError(str(e)) from None
        self.store.set(f"{SCHEMAS}/{schema.schema_name}", schema.to_json())

    def get_schema(self, name: str) -> Optional[Schema]:
        rec = self.store.get(f"{SCHEMAS}/{name}")
        return Schema.from_json(rec) if rec else None

    def add_table(self, config: TableConfig,
                  assignment: str = "balanced") -> str:
        table = config.table_name_with_type
        _validate_table_config(config)
        self._validate_upsert_config(config)
        self._validate_vector_columns(config)
        self._validate_retention_config(config)
        self._validate_task_configs(config)
        tenant = config.tenant_config.server or DEFAULT_TENANT
        if tenant != DEFAULT_TENANT and not self.server_instances_for(
                config):
            # parity: table creation fails when the named tenant has no
            # tagged instances (DefaultTenant stays lenient so tables can
            # be registered before servers in bootstrap flows)
            raise InvalidTableConfigError(
                f"server tenant {tenant} has no live tagged instances")
        self.store.set(f"{TABLE_CONFIGS}/{table}", config.to_json())
        builder = (config.routing_config.builder_name or "").lower()
        if assignment == "balanced" and "partitionaware" in builder:
            # partition-aware routing needs its assignment half: same-
            # partition segments co-located so routing can isolate them
            assignment = "partitionaware"
        self._assignments[table] = make_assignment(assignment)
        self.coordinator.set_ideal_state(table,
                                         self.coordinator.ideal_state(table))
        self.refresh_broker_resource(table, config)
        return table

    def _validate_upsert_config(self, config: TableConfig) -> None:
        """Upsert tables must be REALTIME with single-value primary-key
        columns the schema defines (parity: TableConfigUtils
        validateUpsertConfig — reject at create time, not first use)."""
        uc = config.upsert_config
        if uc is None:
            return
        if uc.mode.upper() not in ("NONE", "FULL"):
            # an unrecognized mode must fail loudly, not silently
            # disable dedup (only FULL is implemented; PARTIAL is not)
            raise InvalidTableConfigError(
                f"unsupported upsert mode {uc.mode!r}; supported: "
                "NONE, FULL")
        if not uc.enabled:
            return
        from pinot_tpu.common.table_config import TableType
        if config.table_type != TableType.REALTIME:
            raise InvalidTableConfigError(
                "upsert mode FULL requires a REALTIME table")
        if not uc.primary_key_columns:
            raise InvalidTableConfigError(
                "upsert mode FULL requires primaryKeyColumns")
        schema = self.get_schema(config.table_name)
        if schema is None:
            raise InvalidTableConfigError(
                f"upsert table '{config.table_name}' needs its schema "
                "registered first")
        fields = {f.name: f for f in schema.fields}
        for col in uc.primary_key_columns:
            field = fields.get(col)
            if field is None:
                raise InvalidTableConfigError(
                    f"upsert primary key column '{col}' not in schema")
            if not field.single_value:
                raise InvalidTableConfigError(
                    f"upsert primary key column '{col}' must be "
                    "single-value")
            from pinot_tpu.common.datatype import DataType
            if field.data_type == DataType.VECTOR:
                raise InvalidTableConfigError(
                    f"upsert primary key column '{col}' cannot be a "
                    "VECTOR column")

    def _validate_vector_columns(self, config: TableConfig) -> None:
        """VECTOR columns carry no dictionary, so every dictionary- or
        value-hash-backed index config is a misconfiguration — reject at
        create time (the schema may legitimately not be registered yet
        for OFFLINE bootstrap flows; then there is nothing to check)."""
        from pinot_tpu.index import ivf
        for col, raw in (config.indexing_config.vector_index_configs
                         or {}).items():
            cfg = dict(ivf.DEFAULT_CONFIG)
            cfg.update(raw or {})
            try:
                ivf.validate_config(cfg, col)
            except ValueError as e:
                raise InvalidTableConfigError(str(e)) from None
        schema = self.get_schema(config.table_name)
        if schema is None:
            return
        from pinot_tpu.common.datatype import DataType
        vec_cols = {f.name for f in schema.fields
                    if f.data_type == DataType.VECTOR}
        bad_idx = set(config.indexing_config.vector_index_configs
                      or {}) - vec_cols
        if bad_idx:
            raise InvalidTableConfigError(
                f"vectorIndexConfigs name non-VECTOR column(s) "
                f"{sorted(bad_idx)}")
        if not vec_cols:
            return
        idx = config.indexing_config
        for label, cols in (
                ("invertedIndexColumns", idx.inverted_index_columns),
                ("bloomFilterColumns", idx.bloom_filter_columns),
                ("noDictionaryColumns", idx.no_dictionary_columns)):
            bad = vec_cols & set(cols or ())
            if bad:
                raise InvalidTableConfigError(
                    f"VECTOR column(s) {sorted(bad)} cannot appear in "
                    f"{label} (vector forward blocks have no dictionary "
                    "or hashable values)")

    def _validate_retention_config(self, config: TableConfig) -> None:
        """Reject malformed retention at create/update time instead of
        silently never scheduling a deletion (parity: TableConfigUtils
        retention validation; the upsert-config precedent)."""
        from pinot_tpu.common.timeutils import UNIT_MS
        sc = config.segments_config
        unit, value = sc.retention_time_unit, sc.retention_time_value
        if unit is None and value is None:
            return
        if unit is None or value is None:
            raise InvalidTableConfigError(
                "retentionTimeUnit and retentionTimeValue must be set "
                "together (one without the other never schedules a "
                "deletion)")
        if str(unit).upper() not in UNIT_MS:
            raise InvalidTableConfigError(
                f"unrecognized retentionTimeUnit {unit!r}; supported: "
                f"{sorted(UNIT_MS)}")
        try:
            ok = int(value) > 0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise InvalidTableConfigError(
                f"retentionTimeValue must be a positive integer, got "
                f"{value!r}")

    def _validate_task_configs(self, config: TableConfig) -> None:
        """Reject malformed minion task configs at the API instead of
        silently never scheduling (generators would skip or crash a
        periodic run otherwise)."""

        def _num(cfg, key, default, lo, hi, task):
            raw = cfg.get(key, default)
            try:
                v = float(raw)
            except (TypeError, ValueError):
                raise InvalidTableConfigError(
                    f"{task}.{key} must be a number, got {raw!r}"
                    ) from None
            if not lo <= v <= hi:
                raise InvalidTableConfigError(
                    f"{task}.{key} must be in [{lo}, {hi}], got {raw!r}")
            return v

        upsert_on = config.upsert_config is not None and \
            config.upsert_config.enabled
        for ttype, cfg in (config.task_configs or {}).items():
            if ttype == "UpsertCompactionTask":
                if not upsert_on:
                    raise InvalidTableConfigError(
                        "UpsertCompactionTask requires an enabled "
                        "upsertConfig (there are no validDocIds-dead "
                        "rows to drop otherwise)")
                _num(cfg, "invalidDocsThresholdPercent", "20", 0.0,
                     100.0, ttype)
                _num(cfg, "minInvalidDocs", "1", 0, 1e12, ttype)
            elif ttype == "MergeRollupTask":
                if upsert_on:
                    raise InvalidTableConfigError(
                        "MergeRollupTask is not supported on upsert "
                        "tables (merging reshuffles doc ids under the "
                        "key map; use UpsertCompactionTask)")
                _num(cfg, "smallSegmentDocsThreshold", "1", 1, 1e12,
                     ttype)
                _num(cfg, "maxNumSegmentsPerTask", "8", 2, 1e6, ttype)
                merge_type = cfg.get("mergeType", "CONCATENATE")
                if str(merge_type).upper() not in ("CONCATENATE",
                                                   "ROLLUP"):
                    raise InvalidTableConfigError(
                        f"MergeRollupTask.mergeType must be CONCATENATE "
                        f"or ROLLUP, got {merge_type!r}")
            elif ttype == "IvfRetrainTask":
                if not (config.indexing_config.vector_index_configs
                        or {}):
                    raise InvalidTableConfigError(
                        "IvfRetrainTask requires vectorIndexConfigs "
                        "(there is no codebook to retrain otherwise)")
                _num(cfg, "retrainDriftThreshold", "0.2", 0.0, 1e6,
                     ttype)

    # -- tenants -----------------------------------------------------------
    def server_instances_for(self, config: TableConfig) -> List[str]:
        """Live server instances the table's segments may be assigned to
        — scoped to its server tenant tag (parity: the tag-filtered
        instance lists PinotHelixResourceManager feeds the assignment
        strategies)."""
        ttype = getattr(config.table_type, "name", str(config.table_type))
        tag = server_tenant_tag(config.tenant_config.server, ttype)
        return self.coordinator.live_instances(tag=tag)

    def refresh_broker_resource(self, table: str,
                                config: Optional[TableConfig] = None
                                ) -> List[str]:
        """Recompute /BROKERRESOURCE/<table>: the brokers serving the
        table, by broker tenant tag (parity: the Helix brokerResource
        ideal state; watched by DynamicBrokerSelector clients)."""
        config = config or self.get_table_config(table)
        if config is None:
            return []
        tag = broker_tenant_tag(config.tenant_config.broker)
        brokers = self.coordinator.live_instances(tag=tag)
        rec = {"tenant": config.tenant_config.broker,
               "instances": brokers}
        if self.store.get(f"{BROKER_RESOURCE}/{table}") != rec:
            self.store.set(f"{BROKER_RESOURCE}/{table}", rec)
        return brokers

    def refresh_all_broker_resources(self) -> None:
        for table in self.table_names():
            self.refresh_broker_resource(table)

    def get_table_config(self, table: str) -> Optional[TableConfig]:
        rec = self.store.get(f"{TABLE_CONFIGS}/{table}")
        return TableConfig.from_json(rec) if rec else None

    def update_table_config(self, config: TableConfig) -> str:
        """Overwrite a table's config (parity: updateTableConfig REST —
        replication/indexing changes take effect on the next rebalance /
        segment reload)."""
        table = config.table_name_with_type
        if self.store.get(f"{TABLE_CONFIGS}/{table}") is None:
            raise ValueError(f"table {table} not found")
        _validate_table_config(config)
        self._validate_retention_config(config)
        self._validate_task_configs(config)
        tenant = config.tenant_config.server or DEFAULT_TENANT
        if tenant != DEFAULT_TENANT and not self.server_instances_for(
                config):
            raise InvalidTableConfigError(
                f"server tenant {tenant} has no live tagged instances")
        self.store.set(f"{TABLE_CONFIGS}/{table}", config.to_json())
        self.refresh_broker_resource(table, config)
        return table

    def table_names(self) -> List[str]:
        return self.store.children(TABLE_CONFIGS)

    def delete_table(self, table: str) -> None:
        self.coordinator.drop_table(table)
        self.store.remove(f"{TABLE_CONFIGS}/{table}")
        self.store.remove(f"{BROKER_RESOURCE}/{table}")
        for seg in self.segment_names(table):
            self.store.remove(f"{SEGMENTS}/{table}/{seg}")
        self.fs.delete(os.path.join(self.deep_store_dir, table))

    # -- segments ----------------------------------------------------------
    def add_segment(self, table: str, segment_dir: str,
                    metadata: Optional[SegmentMetadata] = None) -> str:
        """Upload a built segment: deep-store copy + metadata + assignment.

        Parity: PinotSegmentUploadRestletResource → ZKOperator →
        addNewSegment.
        """
        config = self.get_table_config(table)
        if config is None:
            raise ValueError(f"table {table} does not exist")
        meta = metadata or SegmentMetadata.load(segment_dir)
        name = meta.segment_name
        # integrity admission: externally built artifacts without a crc
        # are stamped now; stamped ones are verified before the deep
        # store accepts them (parity: ZKOperator checking the upload crc)
        from pinot_tpu.segment.integrity import stamp_crc, verify_segment
        if isinstance(segment_dir, str) and os.path.isdir(segment_dir):
            if meta.crc is None:
                meta.crc = stamp_crc(segment_dir)
            else:
                verify_segment(segment_dir, meta.crc)
        # storage quota admission (parity: StorageQuotaChecker invoked
        # from the upload resource before the segment is accepted)
        size_bytes = dir_size_bytes(segment_dir)
        if config.quota_config is not None and config.quota_config.storage:
            existing = {seg: (self.segment_metadata(table, seg) or {}).get(
                "sizeBytes") for seg in self.segment_names(table)}
            self._quota_checker.check_segment_upload(
                config, table, existing, name, size_bytes)
        dest = os.path.join(self.deep_store_dir, table, name)
        if os.path.abspath(segment_dir) != os.path.abspath(dest):
            self.fs.delete(dest)
            self.fs.copy(segment_dir, dest)
            if meta.crc is not None and isinstance(self.fs, LocalPinotFS):
                # a torn deep-store copy must never become the durable
                # artifact servers download
                from pinot_tpu.segment.integrity import (
                    SegmentIntegrityError, verify_segment as _verify)
                try:
                    _verify(dest, meta.crc)
                except SegmentIntegrityError:
                    self.fs.delete(dest)
                    raise
        # per-column partition metadata rides the segment ZK record so the
        # broker can prune before scatter (parity: the partition info in
        # SegmentZKMetadata consumed by PartitionZKMetadataPruner)
        partition_meta = {
            cname: {"functionName": cm.partition_function,
                    "numPartitions": cm.num_partitions,
                    "partitions": list(cm.partitions)}
            for cname, cm in meta.columns.items()
            if cm.partition_function and cm.partitions}
        self.store.set(f"{SEGMENTS}/{table}/{name}", {
            "segmentName": name,
            "downloadPath": self.advertised_download_path(table, name),
            "startTime": meta.start_time,
            "endTime": meta.end_time,
            "timeUnit": meta.time_unit,
            "totalDocs": meta.total_docs,
            "pushTimeMs": int(time.time() * 1e3),
            "crc": meta.crc,
            "sizeBytes": size_bytes,
            "partitionMetadata": partition_meta,
            # segment-custom stats (e.g. IVF drift) for task generators
            "customMap": dict(meta.custom or {}),
        })
        replicas = config.segments_config.replication
        strategy = self._assignments.setdefault(
            table, make_assignment("balanced"))
        servers = self.server_instances_for(config)
        if not servers:
            raise ValueError(
                f"no live server instances for tenant "
                f"{config.tenant_config.server} (table {table})")
        current = self.coordinator.ideal_state(table)
        if name in current:
            # refresh of an existing segment: keep its assignment, bounce
            # it through OFFLINE so servers reload the new artifact
            # (parity: the segment refresh message ZKOperator sends)
            assigned = sorted(current[name])

            def offline(segments):
                segments[name] = {inst: "OFFLINE" for inst in assigned}
                return segments

            self.coordinator.update_ideal_state(table, offline)
        else:
            # externally built segments may omit per-column partition
            # lists — tolerate like the rebalance path does, instead of
            # failing the whole upload with a KeyError
            pids = {p for info in partition_meta.values()
                    for p in info.get("partitions") or ()}
            assigned = strategy.assign(name, servers, replicas, current,
                                       partition_ids=pids or None)

        def add(segments):
            segments[name] = {inst: ONLINE for inst in assigned}
            return segments

        self.coordinator.update_ideal_state(table, add)
        return name

    def advertised_download_path(self, table: str, segment: str) -> str:
        """The downloadPath servers should fetch: the controller's
        /deepstore HTTP endpoint when `download_base` is set, the raw
        deep-store path otherwise (shared-filesystem deployments)."""
        if self.download_base:
            return (f"{self.download_base.rstrip('/')}/deepstore/"
                    f"{table}/{segment}")
        return os.path.join(self.deep_store_dir, table, segment)

    def canonical_artifact_path(self, table: str, segment: str) -> str:
        """The artifact's location inside THIS controller's deep store
        (what an advertised HTTP downloadPath resolves to)."""
        return os.path.join(self.deep_store_dir, table, segment)

    def resolve_download_path(self, path: str) -> str:
        """Re-base an HTTP deep-store URL onto the endpoint the CURRENT
        controller publishes (/CONTROLLER/DEEPSTORE_BASE): segment
        records are durable, but a restarted controller may come back
        on a different port — a stamped absolute URL would point at the
        dead process forever. Shared by every artifact consumer
        (server participant, minion workers)."""
        if "://" not in path or "/deepstore/" not in path:
            return path
        rec = self.store.get("/CONTROLLER/DEEPSTORE_BASE") or {}
        base = rec.get("base")
        if not base:
            return path
        rel = path.split("/deepstore/", 1)[1]
        return f"{base.rstrip('/')}/deepstore/{rel}"

    def segment_names(self, table: str) -> List[str]:
        return self.store.children(f"{SEGMENTS}/{table}")

    def segment_metadata(self, table: str, segment: str) -> Optional[dict]:
        return self.store.get(f"{SEGMENTS}/{table}/{segment}")

    def delete_segment(self, table: str, segment: str,
                       tombstone_artifact: bool = False) -> None:
        """Parity: SegmentDeletionManager — drop from ideal state, remove
        metadata, delete the deep-store artifact (the recorded
        downloadPath AND the canonical location, plus any stale
        split-commit staging copies — retention must not leak bytes).

        `tombstone_artifact`: delayed delete — the canonical artifact
        slides to a ``.trash.<ms>`` tombstone the integrity scrubber
        reclaims after its grace window (the retention path: a
        fat-fingered retention config stays recoverable for the grace
        period)."""
        meta = self.segment_metadata(table, segment) or {}

        def drop(segments):
            if segment in segments:
                segments[segment] = {inst: DROPPED
                                     for inst in segments[segment]}
            return segments

        self.coordinator.update_ideal_state(table, drop)

        def purge(segments):
            segments.pop(segment, None)
            return segments

        self.coordinator.update_ideal_state(table, purge)
        self.store.remove(f"{SEGMENTS}/{table}/{segment}")
        # published per-segment deadness dies with the segment
        from pinot_tpu.realtime.upsert import deadness_path
        self.store.remove(deadness_path(table, segment))
        canonical = os.path.join(self.deep_store_dir, table, segment)
        if tombstone_artifact and os.path.isdir(canonical):
            from pinot_tpu.controller.compaction import trash_path
            self.fs.move(canonical,
                         trash_path(canonical, time.time() * 1e3))
        else:
            self.fs.delete(canonical)
        download = meta.get("downloadPath")
        if download and "://" not in download and \
                os.path.abspath(download) != os.path.abspath(canonical):
            self.fs.delete(download)
        for stale in glob.glob(canonical + ".staging.*"):
            self.fs.delete(stale)

    def reload_segment(self, table: str, segment: str,
                       converge_timeout_s: float = 30.0) -> None:
        """Rolling per-replica bounce through OFFLINE so holders re-run
        the load path — applying schema evolution (default columns) and
        new index configs to an already-served segment. One replica
        reloads at a time, WAITING for the external view to show it
        serving again before the next bounce — with remote participants
        the ideal-state write returns before the server transitions, and
        bouncing the next replica early would leave a window with zero
        serving replicas (a replication-1 segment is briefly unrouted —
        the reference's in-place reload message has no gap, but also no
        Helix-visible progress). Parity: the segment reload REST
        operation. Each closure re-reads the LIVE instance map, so a
        concurrent rebalance is never clobbered with a stale holder
        set."""
        current = self.coordinator.ideal_state(table)
        if segment not in current:
            raise ValueError(f"segment {segment} not in {table}")
        for inst in sorted(current[segment]):

            def offline(segments, inst=inst):
                entry = dict(segments.get(segment, {}))
                if entry.get(inst) == ONLINE:
                    entry[inst] = "OFFLINE"
                    segments[segment] = entry
                return segments

            self.coordinator.update_ideal_state(table, offline)
            try:
                if self.coordinator.ideal_state(table).get(
                        segment, {}).get(inst) == "OFFLINE":
                    # wait for the UNLOAD to be visible before flipping
                    # back: a remote agent lags the store write, and the
                    # stale ONLINE in the view would otherwise satisfy
                    # the re-ONLINE wait spuriously — letting the next
                    # replica bounce while this one is still going down
                    # (observed as both-replicas-OFFLINE view windows)
                    self._await_converged(table,
                                          {segment: {inst: "OFFLINE"}},
                                          1, converge_timeout_s)
            except TimeoutError:
                # dead/wedged replica: restore the ideal state to ONLINE
                # so the instance isn't parked OFFLINE forever, then
                # surface the failure

                def restore(segments, inst=inst):
                    entry = dict(segments.get(segment, {}))
                    if entry.get(inst) == "OFFLINE":
                        entry[inst] = ONLINE
                        segments[segment] = entry
                    return segments

                self.coordinator.update_ideal_state(table, restore)
                raise

            def online(segments, inst=inst):
                entry = dict(segments.get(segment, {}))
                if entry.get(inst) == "OFFLINE":
                    entry[inst] = ONLINE
                    segments[segment] = entry
                return segments

            self.coordinator.update_ideal_state(table, online)
            if self.coordinator.ideal_state(table).get(
                    segment, {}).get(inst) == ONLINE:
                self._await_converged(table, {segment: {inst: ONLINE}},
                                      1, converge_timeout_s)

    def reload_table(self, table: str) -> int:
        segments = self.segment_names(table)
        if self.get_table_config(table) is None:
            raise ValueError(f"table {table} does not exist")
        for seg in segments:
            self.reload_segment(table, seg)
        return len(segments)

    # -- rebalance ---------------------------------------------------------
    def rebalance_table(self, table: str, dry_run: bool = False,
                        downtime: bool = False,
                        min_available_replicas: int = 1,
                        batch_size: int = 10,
                        converge_timeout_s: float = 30.0) -> Dict:
        """Recompute the whole assignment against live tenant instances
        and walk the ideal state toward it WITHOUT dropping availability.

        Parity: TableRebalancer.java:51,82-97,195-217 — no-downtime mode
        steps the ideal state make-before-break: new replicas are added
        (and awaited in the external view) before old ones are dropped,
        keeping ≥min_available_replicas serving replicas per segment at
        every intermediate state; `downtime=True` is the one-shot write
        (faster, for maintenance windows); `batch_size` bounds how many
        segments move per step (bounds the transient extra capacity the
        make-before-break union costs).
        """
        config = self.get_table_config(table)
        if config is None:
            raise ValueError(f"table {table} does not exist")
        replicas = config.segments_config.replication
        strategy = self._assignments.setdefault(
            table, make_assignment("balanced"))
        servers = self.server_instances_for(config)
        current = self.coordinator.ideal_state(table)
        target: Dict[str, Dict[str, str]] = {}
        for seg in self.segment_names(table):
            cur = current.get(seg, {})
            if CONSUMING in cur.values():
                # in-progress LLC segments are pinned to their consumers
                # (parity: TableRebalancer leaves CONSUMING partitions to
                # the realtime repair path) — flipping them ONLINE would
                # kill ingestion and fail the load ('no committed
                # artifact')
                target[seg] = dict(cur)
                continue
            pm = (self.segment_metadata(table, seg) or {}).get(
                "partitionMetadata") or {}
            pids = {p for info in pm.values()
                    for p in info.get("partitions") or ()}
            assigned = strategy.assign(seg, servers, replicas, target,
                                       partition_ids=pids or None)
            target[seg] = {inst: ONLINE for inst in assigned}
        if dry_run:
            return target
        if downtime:
            self.coordinator.set_ideal_state(table, target)
            return target

        moving = sorted(s for s in set(current) | set(target)
                        if current.get(s) != target.get(s))
        for i in range(0, len(moving), max(batch_size, 1)):
            batch = moving[i:i + max(batch_size, 1)]
            # step 1 (make): run old + new replicas side by side
            def add_new(segments, batch=batch):
                for seg in batch:
                    merged = dict(segments.get(seg, {}))
                    merged.update(target.get(seg, {}))
                    segments[seg] = merged
                return segments

            self.coordinator.update_ideal_state(table, add_new)
            # wait for the NEWLY ADDED replicas specifically: counting
            # already-serving old replicas would let the drop step run
            # before the new copies finish loading, and a subsequent
            # bounce of the old survivor would leave zero serving
            # replicas (observed under rebalance+reload churn)
            added = {s: {i: st for i, st in target.get(s, {}).items()
                         if i not in current.get(s, {})}
                     for s in batch}
            self._await_converged(table, added, min_available_replicas,
                                  converge_timeout_s,
                                  require_all=True)

            # step 2 (break): drop replicas not in the target
            def drop_old(segments, batch=batch):
                for seg in batch:
                    tgt = target.get(seg)
                    if tgt:
                        segments[seg] = dict(tgt)
                    else:
                        segments.pop(seg, None)
                return segments

            self.coordinator.update_ideal_state(table, drop_old)
        return target

    def _await_converged(self, table: str,
                         wanted: Dict[str, Dict[str, str]],
                         min_available: int, timeout_s: float,
                         require_all: bool = False) -> None:
        """Block until every segment has ≥min_available (or, with
        require_all, every one) of its wanted replicas serving in the
        external view (parity: the external-view convergence wait
        between TableRebalancer steps)."""
        deadline = time.monotonic() + timeout_s
        while True:
            view = self.coordinator.external_view(table).segment_states
            # drop-only segments (empty wanted map) need no convergence
            ok = all(
                not wanted.get(seg) or
                sum(1 for inst, st in wanted[seg].items()
                    if view.get(seg, {}).get(inst) == st) >=
                (len(wanted[seg]) if require_all else
                 min(min_available, len(wanted[seg])))
                for seg in wanted)
            if ok:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rebalance: external view of {table} did not "
                    f"converge within {timeout_s}s")
            time.sleep(0.05)
