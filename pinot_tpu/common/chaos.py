"""Deterministic chaos plane: a seeded coordinator that executes a
declarative fault schedule against a live cluster.

Chaos-engineering support for the production soak (ROADMAP item 5):
`common/faults.py` injects faults into ONE transport deterministically;
this module sequences WHOLE-CLUSTER faults — kill -9 a serving server,
SIGTERM-drain another, kill the lead controller and verify standby
takeover, kill the minion mid-swap, arm/disarm transport latency and
drop windows — from a declarative schedule on an injectable clock.

Design rules (the same ones the rest of the repo's fault machinery
follows):

- **Deterministic**: one seeded RNG picks targets for events that do
  not name one; the clock and the sleep are injectable; the recorded
  timeline of two runs with the same seed, schedule, fake clock and
  adapter is byte-identical (``timeline_json``).
- **Declarative**: a schedule is a list of :class:`ChaosEvent` (or
  plain dicts) — *what* fires *when*, with an optional fault window
  duration and a per-fault recovery deadline. No imperative glue.
- **Cluster-agnostic**: the coordinator drives a duck-typed *adapter*.
  Every event ``kind`` is an adapter method ``kind(target, **params)``;
  windowed events additionally need ``clear_fault(target)``; seeded
  target selection needs ``targets(kind) -> iterable`` and recovery
  tracking needs ``recovery_probe(event, target) -> callable | None``.
  `tools/cluster.py`'s multi-process driver implements the verbs
  against real processes; tests use fakes.
- **Accountable**: every action (fired / disarmed / recovered /
  recovery_deadline_violated / error) lands on an event timeline with
  offsets from schedule start; ``report()`` is the JSON block the SOAK
  artifact commits.
"""
from __future__ import annotations

import dataclasses
import json
import random
import time
from typing import Callable, Dict, Iterable, List, Optional, Union


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault.

    ``at_s`` is the offset from schedule start. ``kind`` names the
    adapter verb (``kill_server``, ``drain_server``, ``fail_controller``,
    ``kill_minion``, ``net_latency``, ``net_drop``, ``start_server``...).
    ``target=None`` means the coordinator picks one (seeded) from
    ``adapter.targets(kind)`` at fire time. ``duration_s > 0`` makes
    the event a *window*: ``adapter.clear_fault(target)`` runs at
    ``at_s + duration_s``. ``recovery_deadline_s`` arms recovery
    tracking: the adapter's probe must go true within the deadline or
    the timeline records a violation."""
    at_s: float
    kind: str
    target: Optional[str] = None
    duration_s: float = 0.0
    recovery_deadline_s: Optional[float] = None
    params: dict = dataclasses.field(default_factory=dict)
    note: str = ""

    def to_json(self) -> dict:
        d = {"atS": self.at_s, "kind": self.kind}
        if self.target is not None:
            d["target"] = self.target
        if self.duration_s:
            d["durationS"] = self.duration_s
        if self.recovery_deadline_s is not None:
            d["recoveryDeadlineS"] = self.recovery_deadline_s
        if self.params:
            d["params"] = dict(sorted(self.params.items()))
        if self.note:
            d["note"] = self.note
        return d


def coerce_schedule(schedule: Iterable[Union[ChaosEvent, dict]]
                    ) -> List[ChaosEvent]:
    """Accept plain dicts (the declarative JSON form) next to
    ChaosEvent instances."""
    out: List[ChaosEvent] = []
    for ev in schedule:
        if isinstance(ev, ChaosEvent):
            out.append(ev)
            continue
        out.append(ChaosEvent(
            at_s=float(ev.get("atS", ev.get("at_s", 0.0))),
            kind=ev["kind"],
            target=ev.get("target"),
            duration_s=float(ev.get("durationS",
                                    ev.get("duration_s", 0.0))),
            recovery_deadline_s=ev.get("recoveryDeadlineS",
                                       ev.get("recovery_deadline_s")),
            params=dict(ev.get("params", {})),
            note=ev.get("note", "")))
    return out


class ChaosCoordinator:
    """Executes a :class:`ChaosEvent` schedule against an adapter.

    ``run()`` blocks until every event fired, every window disarmed and
    every recovery resolved (or violated); the soak harness runs it on
    its own thread against the real clock, the unit tests drive
    ``step()`` directly on a fake clock. The coordinator never raises
    out of an adapter verb — a failed verb is itself a timeline entry
    (chaos tooling dying mid-soak would mask the very bugs it exists
    to surface)."""

    def __init__(self, adapter, schedule, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 poll_interval_s: float = 0.5):
        self.adapter = adapter
        self.schedule = coerce_schedule(schedule)
        self.seed = seed
        self._rng = random.Random(seed)
        self._clock = clock
        self._sleep = sleep
        self.poll_interval_s = poll_interval_s
        self.timeline: List[dict] = []
        self._seq = 0
        self._t0: Optional[float] = None
        # pending actions, ordered by (time, arrival): fire events plus
        # the disarms their windows schedule
        self._actions: List[dict] = []
        for i, ev in enumerate(sorted(self.schedule,
                                      key=lambda e: e.at_s)):
            self._actions.append({"at": ev.at_s, "order": i,
                                  "type": "fire", "event": ev})
        # recoveries being tracked: {event, target, probe, firedAt,
        # deadline}
        self._pending: List[dict] = []

    # -- lifecycle ---------------------------------------------------------
    def begin(self) -> None:
        if self._t0 is None:
            self._t0 = self._clock()

    def done(self) -> bool:
        return self._t0 is not None and not self._actions \
            and not self._pending

    def elapsed_s(self) -> float:
        return 0.0 if self._t0 is None else self._clock() - self._t0

    def stop(self) -> None:
        """Abort: drop not-yet-fired actions and unresolved recovery
        tracking so ``run()`` returns at its next wakeup. The timeline
        keeps everything that already happened."""
        self.begin()
        self._actions = []
        self._pending = []

    def run(self) -> dict:
        """Blocking: execute the whole schedule, then return
        ``report()``."""
        self.begin()
        while not self.done():
            self.step()
            if self.done():
                break
            delay = self.poll_interval_s
            if self._actions and not self._pending:
                delay = max(0.0, min(
                    self._actions[0]["at"] - self.elapsed_s(),
                    self.poll_interval_s))
            self._sleep(max(delay, 1e-3))
        return self.report()

    def step(self) -> None:
        """Fire every due action at the current clock, then poll
        pending recoveries. Idempotent between clock advances."""
        self.begin()
        now = self.elapsed_s()
        due = [a for a in self._actions if a["at"] <= now]
        self._actions = [a for a in self._actions if a["at"] > now]
        for action in sorted(due, key=lambda a: (a["at"], a["order"])):
            if action["type"] == "fire":
                self._fire(action["event"], now)
            else:
                self._disarm(action["event"], action["target"], now)
        self._poll_recoveries(self.elapsed_s() if due else now)

    # -- internals ---------------------------------------------------------
    def _record(self, **entry) -> dict:
        entry["seq"] = self._seq
        self._seq += 1
        self.timeline.append(entry)
        return entry

    def _fire(self, ev: ChaosEvent, now: float) -> None:
        target = ev.target
        if target is None:
            pool = sorted(self.adapter.targets(ev.kind) or []) \
                if hasattr(self.adapter, "targets") else []
            if not pool:
                self._record(tOffsetS=round(now, 3), action="skipped",
                             kind=ev.kind, reason="no targets")
                return
            target = self._rng.choice(pool)
        verb = getattr(self.adapter, ev.kind, None)
        if verb is None:
            self._record(tOffsetS=round(now, 3), action="error",
                         kind=ev.kind, target=target,
                         error=f"adapter has no verb {ev.kind!r}")
            return
        try:
            result = verb(target, **ev.params)
        except Exception as e:  # noqa: BLE001 — chaos must not die mid-soak
            self._record(tOffsetS=round(now, 3), action="error",
                         kind=ev.kind, target=target,
                         error=f"{type(e).__name__}: {e}")
            return
        entry = {"tOffsetS": round(now, 3), "action": "fired",
                 "kind": ev.kind, "target": target}
        if ev.note:
            entry["note"] = ev.note
        if isinstance(result, (str, int, float, bool)):
            entry["result"] = result
        self._record(**entry)
        if ev.duration_s > 0:
            self._actions.append({"at": ev.at_s + ev.duration_s,
                                  "order": self._seq, "type": "disarm",
                                  "event": ev, "target": target})
            self._actions.sort(key=lambda a: (a["at"], a["order"]))
        if ev.recovery_deadline_s is not None:
            probe = None
            if hasattr(self.adapter, "recovery_probe"):
                try:
                    probe = self.adapter.recovery_probe(ev, target)
                except Exception:  # noqa: BLE001 — probe setup optional
                    probe = None
            if probe is not None:
                self._pending.append({
                    "event": ev, "target": target, "probe": probe,
                    "firedAt": now,
                    "deadline": now + ev.recovery_deadline_s})

    def _disarm(self, ev: ChaosEvent, target: str, now: float) -> None:
        try:
            self.adapter.clear_fault(target)
            self._record(tOffsetS=round(now, 3), action="disarmed",
                         kind=ev.kind, target=target)
        except Exception as e:  # noqa: BLE001
            self._record(tOffsetS=round(now, 3), action="error",
                         kind=ev.kind, target=target,
                         error=f"{type(e).__name__}: {e}")

    def _poll_recoveries(self, now: float) -> None:
        still: List[dict] = []
        for p in self._pending:
            ok = False
            try:
                ok = bool(p["probe"]())
            except Exception:  # noqa: BLE001 — probe racing the fault
                ok = False
            if ok:
                self._record(
                    tOffsetS=round(now, 3), action="recovered",
                    kind=p["event"].kind, target=p["target"],
                    recoveryS=round(now - p["firedAt"], 3),
                    deadlineS=p["event"].recovery_deadline_s)
            elif now >= p["deadline"]:
                self._record(
                    tOffsetS=round(now, 3),
                    action="recovery_deadline_violated",
                    kind=p["event"].kind, target=p["target"],
                    deadlineS=p["event"].recovery_deadline_s)
            else:
                still.append(p)
        self._pending = still

    # -- reporting ---------------------------------------------------------
    def violations(self) -> List[dict]:
        return [e for e in self.timeline
                if e["action"] == "recovery_deadline_violated"]

    def recoveries(self) -> Dict[str, float]:
        """kind → recovery seconds (last recovery per kind)."""
        out: Dict[str, float] = {}
        for e in self.timeline:
            if e["action"] == "recovered":
                out[e["kind"]] = e["recoveryS"]
        return out

    def report(self) -> dict:
        return {
            "seed": self.seed,
            "schedule": [ev.to_json() for ev in self.schedule],
            "timeline": list(self.timeline),
            "recoveries": self.recoveries(),
            "violations": self.violations(),
            "completed": self.done(),
        }

    def timeline_json(self) -> str:
        """Canonical serialization — the determinism contract: same
        seed + schedule + adapter + clock ⇒ byte-identical output."""
        return json.dumps(self.report(), sort_keys=True,
                          separators=(",", ":"))
