"""Server admin/debug HTTP API.

Parity: pinot-server/.../api/resources/ — TablesResource (table list +
per-segment metadata), TableSizeResource (estimated bytes per segment),
HealthCheckResource, and MmapDebugResource. The reference's "native
memory" debug surface reports mmap/direct buffers; the TPU build's
native memory is HBM, so /debug/memory reports the DEVICE-RESIDENT lane
bytes per table/segment (what the reference's PinotDataBuffer global
accounting becomes on this architecture) next to the host-side column
footprint.
"""
from __future__ import annotations

from pinot_tpu.common.service_status import get_service_status
from pinot_tpu.transport.http import (ApiServer, HttpRequest, HttpResponse,
                                      metrics_response)


from pinot_tpu.segment.loader import segment_host_bytes as _host_bytes


def _device_bytes(seg) -> int:
    total = 0
    for name in seg.column_names:
        dev = getattr(seg.data_source(name), "_dev", None) or {}
        total += sum(int(a.nbytes) for a in dev.values()
                     if hasattr(a, "nbytes"))
    return total


class ServerApiServer(ApiServer):
    """Admin/debug surface for one ServerInstance."""

    def __init__(self, server):
        super().__init__()
        self.server = server
        self.router.add("GET", "/health", self._health)
        self.router.add("GET", "/metrics", self._metrics)
        self.router.add("GET", "/tables", self._tables)
        self.router.add("GET", "/tables/{table}/segments", self._segments)
        self.router.add("GET", "/tables/{table}/size", self._size)
        self.router.add("GET", "/debug/memory", self._memory)
        self.router.add("GET", "/debug/residency", self._residency)
        self.router.add("GET", "/debug/health", self._debug_health)

    async def _metrics(self, request: HttpRequest) -> HttpResponse:
        return metrics_response(self.server.metrics, request)

    async def _health(self, request: HttpRequest) -> HttpResponse:
        from pinot_tpu.common.service_status import Status
        status, desc = get_service_status(self.server.instance_id)
        if status in (Status.GOOD, Status.STARTING) and \
                "no status callback" in desc:
            # standalone servers (no participant) have no callback; they
            # are healthy iff they answer at all
            return HttpResponse(200, b"OK", content_type="text/plain")
        if status == Status.GOOD:
            return HttpResponse(200, b"OK", content_type="text/plain")
        return HttpResponse.error(503, f"{status.name}: {desc}")

    async def _tables(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse.of_json(
            {"tables": self.server.data_manager.table_names()})

    async def _segments(self, request: HttpRequest) -> HttpResponse:
        table = request.path_params["table"]
        tdm = self.server.data_manager.table(table)
        if tdm is None:
            return HttpResponse.error(404, f"table {table} not found")
        sdms, _ = tdm.acquire_segments()
        try:
            out = {}
            for sdm in sdms:
                seg = sdm.segment
                meta = seg.metadata
                out[seg.segment_name] = {
                    "totalDocs": seg.num_docs,
                    "columns": len(seg.column_names),
                    "startTime": meta.start_time,
                    "endTime": meta.end_time,
                    "mutable": bool(getattr(seg, "is_mutable", False)),
                }
            return HttpResponse.of_json({"table": table, "segments": out})
        finally:
            for sdm in sdms:
                tdm.release_segment(sdm)

    async def _size(self, request: HttpRequest) -> HttpResponse:
        table = request.path_params["table"]
        tdm = self.server.data_manager.table(table)
        if tdm is None:
            return HttpResponse.error(404, f"table {table} not found")
        sdms, _ = tdm.acquire_segments()
        try:
            segs = {sdm.segment.segment_name:
                    {"hostBytes": _host_bytes(sdm.segment)}
                    for sdm in sdms}
            return HttpResponse.of_json({
                "table": table,
                "totalHostBytes": sum(v["hostBytes"]
                                      for v in segs.values()),
                "segments": segs})
        finally:
            for sdm in sdms:
                tdm.release_segment(sdm)

    async def _memory(self, request: HttpRequest) -> HttpResponse:
        out = {}
        dm = self.server.data_manager
        for table in dm.table_names():
            tdm = dm.table(table)
            if tdm is None:
                continue
            sdms, _ = tdm.acquire_segments()
            try:
                out[table] = {
                    sdm.segment.segment_name: {
                        "hbmResidentBytes": _device_bytes(sdm.segment),
                        "hostBytes": _host_bytes(sdm.segment),
                    } for sdm in sdms}
            finally:
                for sdm in sdms:
                    tdm.release_segment(sdm)
        total_hbm = sum(s["hbmResidentBytes"]
                        for t in out.values() for s in t.values())
        return HttpResponse.of_json({"totalHbmResidentBytes": total_hbm,
                                     "tables": out})

    async def _debug_health(self, request: HttpRequest) -> HttpResponse:
        """One-scrape leak-gate rollup (obs/health.py): RSS, residency
        ledger, exchange held-bytes, and the leak-sensitive gauges —
        the curated subset the soak's flatness detectors poll."""
        from pinot_tpu.obs.health import health_rollup
        return HttpResponse.of_json(health_rollup(
            "server", self.server.metrics,
            extra={"instanceId": self.server.instance_id}))

    async def _residency(self, request: HttpRequest) -> HttpResponse:
        """The process-global residency ledger: every accounted device
        upload (scan/vdoc/vector/hll/stack/join/window lanes + exchange
        held bytes) by table and kind, with the largest owners — each
        entry annotated with the residency manager's `tier` and
        last-access `heat` when the segment is under management. The
        `manager` block adds the tier map (budget, per-tier totals,
        per-segment tier/heat/pins/coldHits, promotion backlog). This
        is the ledger view the `deviceBytesResident{table,kind}` gauges
        export — /debug/memory remains the per-segment lane walk."""
        from pinot_tpu.obs.residency import LEDGER
        snap = LEDGER.snapshot()
        residency = getattr(self.server, "residency", None)
        if residency is not None:
            snap["manager"] = residency.snapshot()
        return HttpResponse.of_json(snap)
