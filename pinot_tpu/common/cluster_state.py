"""Cluster state model: ideal state + external view.

Parity: Helix's IdealState / ExternalView records as used by Pinot
(docs/architecture.rst:35-120 — table = resource, segment = partition,
server instances mapped to states ONLINE/OFFLINE/CONSUMING/ERROR). The
controller writes ideal states; servers converge and report; brokers build
routing tables from external views. Here both are plain mappings published
through a PropertyStore (controller plane) or handed directly to the broker
in embedded setups.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List

ONLINE = "ONLINE"
OFFLINE = "OFFLINE"
CONSUMING = "CONSUMING"
ERROR = "ERROR"


@dataclasses.dataclass
class TableView:
    """segment -> instance -> state, for one physical table."""
    table_name: str
    segment_states: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)

    def segments(self) -> List[str]:
        return list(self.segment_states.keys())

    def servers_for(self, segment: str, states=(ONLINE, CONSUMING)
                    ) -> List[str]:
        return sorted(inst for inst, st in
                      self.segment_states.get(segment, {}).items()
                      if st in states)

    def all_servers(self) -> List[str]:
        out = set()
        for m in self.segment_states.values():
            out.update(m.keys())
        return sorted(out)

    def copy(self) -> "TableView":
        return TableView(self.table_name,
                         {s: dict(m) for s, m in
                          self.segment_states.items()})

    def to_json(self) -> dict:
        return {"table": self.table_name, "segments": self.segment_states}

    @classmethod
    def from_json(cls, d: dict) -> "TableView":
        return cls(d["table"], {s: dict(m)
                                for s, m in d.get("segments", {}).items()})
