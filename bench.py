"""Benchmark: SSB-style aggregation queries, TPU engine vs CPU columnar scan.

Mirrors BASELINE.md configs 1-4 (+ the 8-segment combine of config 5): range
COUNT, filtered SUM/MIN/MAX, range+IN conjunction, 2-dim GROUP BY.

Two stages:
1. CORRECTNESS GATE — a small table goes through the FULL engine path
   (host-built segments -> HBM upload -> plan -> fused sharded kernel ->
   host finish -> broker reduce) and every query's result rows must equal
   the numpy oracle's.
2. THROUGHPUT — the BASELINE-sized table (default 100M rows, 8 segments).
   Column lanes are synthesized directly in HBM (the test harness reaches
   the TPU through a ~3MB/s relay, so uploading a 2.5GB table is the
   harness's bottleneck, not the engine's). Device timing is PIPELINED:
   N back-to-back kernel dispatches with one final sync — steady-state of
   a loaded server — so the relay's ~100ms per-sync round trip amortizes
   away. The CPU baseline does the same id-domain columnar work with
   vectorized numpy on an identically-distributed table.

Prints ONE JSON line:
  {"metric": ..., "value": p50 speedup vs CPU, "unit": "x",
   "vs_baseline": value / 8.0}   (BASELINE north star: >= 8x p50 vs CPU)

Env knobs: PINOT_TPU_BENCH_ROWS (default 100_000_000),
PINOT_TPU_BENCH_SEGMENTS (8), PINOT_TPU_BENCH_REPS (5).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def median(xs):
    return float(np.median(np.asarray(xs)))


PQLS = {
    "q1_range_count":
        "SELECT COUNT(*) FROM lineorder WHERE d_year > 1994",
    "q2_eq_sum_min_max":
        "SELECT SUM(lo_revenue), MIN(lo_revenue), MAX(lo_revenue) "
        "FROM lineorder WHERE c_region = 'ASIA'",
    "q3_range_in_conj":
        "SELECT COUNT(*) FROM lineorder WHERE d_year BETWEEN 1993 AND "
        "1996 AND s_nation IN ('CHINA', 'INDIA', 'JAPAN') AND "
        "lo_discount <= 5",
    "q4_group_by_2d":
        "SELECT SUM(lo_revenue) FROM lineorder WHERE lo_quantity < 25 "
        "GROUP BY d_year, c_region TOP 1000",
}


def make_cpu_queries(pools, ids):
    """The same queries as vectorized numpy id-domain columnar scans."""
    rev_vals = pools["lo_revenue"].astype(np.float64)
    y94 = int(np.searchsorted(pools["d_year"], 1994, side="right"))
    y93 = int(np.searchsorted(pools["d_year"], 1993))
    y96 = int(np.searchsorted(pools["d_year"], 1996, side="right"))
    d5 = int(np.searchsorted(pools["lo_discount"], 5, side="right"))
    q25 = int(np.searchsorted(pools["lo_quantity"], 25))

    def idq(col, value):
        i = int(np.searchsorted(pools[col], value))
        assert pools[col][i] == value
        return i

    asia = idq("c_region", "ASIA")
    nations = np.array([idq("s_nation", n)
                        for n in ("CHINA", "INDIA", "JAPAN")], np.int32)

    def q1():
        return int((ids["d_year"] >= y94).sum())

    def q2():
        m = ids["c_region"] == asia
        h = np.bincount(ids["lo_revenue"][m], minlength=len(rev_vals))
        nz = np.nonzero(h)[0]
        return (float(h @ rev_vals), float(rev_vals[nz[0]]),
                float(rev_vals[nz[-1]]))

    def q3():
        m = (ids["d_year"] >= y93) & (ids["d_year"] < y96) & \
            np.isin(ids["s_nation"], nations) & (ids["lo_discount"] < d5)
        return int(m.sum())

    def q4():
        m = ids["lo_quantity"] < q25
        key = ids["d_year"][m].astype(np.int64) * len(pools["c_region"]) + \
            ids["c_region"][m]
        n_groups = len(pools["d_year"]) * len(pools["c_region"])
        sums = np.zeros(n_groups)
        np.add.at(sums, key, rev_vals[ids["lo_revenue"][m]])
        return sums

    return {"q1_range_count": q1, "q2_eq_sum_min_max": q2,
            "q3_range_in_conj": q3, "q4_group_by_2d": q4}


def correctness_gate(engine, pools, cpu) -> None:
    """Engine answers (full path) must equal numpy on the same table."""
    resp = engine.query(PQLS["q1_range_count"])
    assert resp.aggregation_results[0].value == str(cpu["q1_range_count"]()),\
        "q1 mismatch"
    resp = engine.query(PQLS["q2_eq_sum_min_max"])
    s, mn, mx = cpu["q2_eq_sum_min_max"]()
    assert abs(float(resp.aggregation_results[0].value) - s) <= 1e-6 * s, \
        "q2 sum mismatch"
    assert float(resp.aggregation_results[1].value) == mn, "q2 min mismatch"
    assert float(resp.aggregation_results[2].value) == mx, "q2 max mismatch"
    resp = engine.query(PQLS["q3_range_in_conj"])
    assert resp.aggregation_results[0].value == str(cpu["q3_range_in_conj"]()
                                                    ), "q3 mismatch"
    resp = engine.query(PQLS["q4_group_by_2d"])
    sums = cpu["q4_group_by_2d"]()
    got = {tuple(str(x) for x in g["group"]): float(g["value"])
           for g in resp.aggregation_results[0].group_by_result}
    for gi, v in enumerate(sums):
        if v == 0:
            continue
        yi, ri = divmod(gi, len(pools["c_region"]))
        key = (str(pools["d_year"][yi]), str(pools["c_region"][ri]))
        assert abs(got[key] - v) <= 1e-9 * abs(v), f"q4 mismatch at {key}"


def main() -> None:
    rows = int(os.environ.get("PINOT_TPU_BENCH_ROWS", 100_000_000))
    n_segs = int(os.environ.get("PINOT_TPU_BENCH_SEGMENTS", 8))
    reps = int(os.environ.get("PINOT_TPU_BENCH_REPS", 5))

    import jax

    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.parallel import make_mesh
    from pinot_tpu.parallel.sharded import get_sharded_kernel
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.tools.datagen import (make_ssb_device_stack,
                                         make_ssb_segments, ssb_pools)
    from pinot_tpu.query.plan import InstancePlanMaker

    mesh = make_mesh()
    log(f"bench: {rows} rows, {n_segs} segments, devices={jax.devices()}")

    # 1. correctness gate (small, full path incl. HBM upload)
    gate_rows = min(rows, 2_000_000)
    gate = make_ssb_segments(gate_rows, n_segs, seed=3)
    engine = QueryEngine(gate.segments, mesh=mesh)
    gate_cpu = make_cpu_queries(gate.pools, gate.ids)
    correctness_gate(engine, gate.pools, gate_cpu)
    log(f"bench: correctness gate passed at {gate_rows} rows "
        "(device == numpy, full engine path)")

    # 2. throughput at full size
    t0 = time.perf_counter()
    lanes, num_docs_dev, plan_table, padded = make_ssb_device_stack(
        rows, n_segs, mesh, seed=3)
    jax.block_until_ready(list(lanes.values()))
    log(f"bench: device lanes synthesized in {time.perf_counter() - t0:.1f}s"
        f" (padded {padded}/segment)")

    pools = ssb_pools(3)
    t0 = time.perf_counter()
    rng = np.random.default_rng(3)
    host_ids = {c: rng.integers(0, len(p), rows).astype(np.int32)
                for c, p in pools.items() if c in
                ("d_year", "c_region", "s_nation", "lo_discount",
                 "lo_quantity", "lo_revenue")}
    log(f"bench: host baseline table in {time.perf_counter() - t0:.1f}s")
    cpu = make_cpu_queries(pools, host_ids)

    plan_maker = InstancePlanMaker()
    plan_seg = plan_table.segments[0]
    pipeline_n = max(4 * reps, 20)
    speedups = []
    for name, pql in PQLS.items():
        request = compile_pql(pql)
        plan = plan_maker.make_segment_plan(plan_seg, request)
        cols = {}
        for col, kind in plan.needed_cols:
            key = {"ids": f"{col}.ids", "parts": f"{col}.parts",
                   "raw": f"{col}.raw", "vlane": f"{col}.vlane",
                   "vals": f"{col}.vals"}[kind]
            cols[key] = lanes[key]
        fn = get_sharded_kernel(mesh, padded, plan.filter_spec,
                                tuple(plan.agg_specs or ()), plan.group_spec,
                                plan.select_spec, tuple(sorted(cols.keys())))
        args = (cols, tuple(plan.params), num_docs_dev)
        jax.device_get(fn(*args))              # compile + 1 RTT
        t0 = time.perf_counter()
        outs = None
        for _ in range(pipeline_n):
            outs = fn(*args)
        jax.device_get(outs["stats.num_docs_matched"])
        d = (time.perf_counter() - t0) / pipeline_n

        cpu_times = []
        for _ in range(max(3, reps // 2)):
            t = time.perf_counter()
            cpu[name]()
            cpu_times.append(time.perf_counter() - t)
        c = median(cpu_times)
        speedups.append(c / d)
        log(f"bench: {name}: device {d * 1e3:.2f}ms/query (pipelined x"
            f"{pipeline_n}), cpu p50 {c * 1e3:.2f}ms, speedup {c / d:.2f}x, "
            f"{rows / d / 1e9:.1f}B rows/s")

    p50 = median(speedups)
    print(json.dumps({
        "metric": "ssb_p50_query_speedup_vs_cpu_numpy",
        "value": round(p50, 3),
        "unit": "x",
        "vs_baseline": round(p50 / 8.0, 4),
    }))


if __name__ == "__main__":
    main()
