"""Component microbenchmarks (the pinot-perf JMH analogue).

Parity: pinot-perf/src/main/java/.../perf/ — BenchmarkOfflineIndexReader,
RawIndexBenchmark, dictionary benchmarks, BenchmarkRealtimeConsumptionSpeed
(SURVEY.md §6). Each benchmark times one storage/engine component in
isolation and reports a JSON line {"bench", "value", "unit"}; `run_all`
returns the records (and the CLI prints them). Sizes are parameters so CI
smoke runs stay fast while full runs use realistic scales.
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List

import numpy as np


def _rate(n: int, fn: Callable[[], None], reps: int = 3) -> float:
    """ops (rows) per second, median of reps."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return n / float(np.median(ts))


def bench_dictionary_encode(n: int = 1_000_000, card: int = 1000) -> dict:
    """SegmentDictionaryCreator path: string column → sorted dict + ids."""
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.segment.dictionary import Dictionary
    rng = np.random.default_rng(0)
    pool = np.array([f"value_{i:06d}" for i in range(card)], dtype=object)
    col = pool[rng.integers(0, card, n)]
    rate = _rate(n, lambda: Dictionary.build_encoded(DataType.STRING, col))
    return {"bench": "dictionary_encode_string", "value": round(rate),
            "unit": "rows/s"}


def bench_fwd_pack_unpack(n: int = 4_000_000, bits: int = 13) -> dict:
    """FixedBitSingleValueReader/Writer path: pack + unpack round-trip."""
    from pinot_tpu.segment.fwd import pack_bits, unpack_bits
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1 << bits, n).astype(np.int32)
    rate = _rate(n, lambda: unpack_bits(pack_bits(ids, bits), bits, n))
    return {"bench": "fwd_bitpack_roundtrip", "value": round(rate),
            "unit": "rows/s"}


def bench_inverted_lookup(n: int = 2_000_000, card: int = 500,
                          lookups: int = 200) -> dict:
    """BitmapInvertedIndexReader path: posting-list fetches."""
    from pinot_tpu.segment.inverted import InvertedIndexWriter
    import os
    import tempfile
    rng = np.random.default_rng(0)
    ids = rng.integers(0, card, n).astype(np.int32)
    with tempfile.TemporaryDirectory() as d:
        InvertedIndexWriter.write(d, "c", ids, card)
        from pinot_tpu.segment.inverted import InvertedIndexReader
        inv = InvertedIndexReader.load(d, "c", n)
        keys = rng.integers(0, card, lookups)
        rate = _rate(lookups, lambda: [inv.postings(int(k))
                                       for k in keys])
    return {"bench": "inverted_posting_lookup", "value": round(rate),
            "unit": "lookups/s"}


def bench_segment_build(rows: int = 1_000_000) -> dict:
    """SegmentIndexCreationDriverImpl path: full SSB segment build.

    One small warmup build first (the JMH warmup-iteration analogue —
    pinot-perf benches measure steady state): it compiles/loads the
    native seglib and faults in the code paths, so the timed run
    measures the build, not one-time process setup."""
    import tempfile

    from pinot_tpu.tools.datagen import build_ssb_segment_dirs
    with tempfile.TemporaryDirectory() as d:
        build_ssb_segment_dirs(d, 50_000, 1, seed=2, star_tree=True)
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        build_ssb_segment_dirs(d, rows, 1, seed=1, star_tree=True)
        dt = time.perf_counter() - t0
    return {"bench": "segment_build_ssb", "value": round(rows / dt),
            "unit": "rows/s"}


def bench_realtime_consumption(rows: int = 50_000) -> dict:
    """BenchmarkRealtimeConsumptionSpeed analogue: MutableSegmentImpl
    index_row throughput."""
    from pinot_tpu.common.schema import (Schema, dimension, metric)
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
    schema = Schema("t", [dimension("d1", DataType.STRING),
                          dimension("d2", DataType.INT),
                          metric("m1", DataType.LONG)])
    rng = np.random.default_rng(0)
    rws = [{"d1": f"v{int(rng.integers(0, 100))}",
            "d2": int(rng.integers(0, 1000)),
            "m1": int(rng.integers(0, 10_000))} for _ in range(rows)]

    def run():
        # the consume loop's shape: index_rows over fetch-batch chunks
        seg = MutableSegmentImpl(schema, TableConfig("t"), "s")
        for i in range(0, len(rws), 1000):
            seg.index_rows(rws[i: i + 1000])
    rate = _rate(rows, run)
    return {"bench": "realtime_index_row", "value": round(rate),
            "unit": "rows/s"}


def bench_realtime_freshness(events: int = 40) -> dict:
    """Event → queryable latency through the FULL realtime path: publish
    to the stream, consumer fetch + index, broker scatter sees the row.
    Parity intent: pinot-perf BenchmarkRealtimeConsumptionSpeed measures
    consumption; the freshness percentile is the user-facing number the
    consumption rate exists to serve."""
    import tempfile
    import time as _t

    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (Schema, TimeUnit, dimension,
                                         metric, time_field)
    from pinot_tpu.common.table_config import (IndexingConfig,
                                               SegmentsConfig, TableConfig,
                                               TableType)
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.cluster import EmbeddedCluster

    schema = Schema("fresh", [dimension("k", DataType.STRING),
                              metric("v", DataType.LONG),
                              time_field("ts", DataType.LONG,
                                         TimeUnit.MILLISECONDS)])
    stream = MemoryStream("fresh_topic", num_partitions=1)
    registry.register_stream_factory(
        "mem_fresh", MemoryStreamConsumerFactory(stream, batch_size=64))
    cfg = TableConfig(
        "fresh", table_type=TableType.REALTIME,
        indexing_config=IndexingConfig(stream_configs={
            "stream.factory.name": "mem_fresh",
            "stream.topic.name": "fresh_topic",
            "realtime.segment.flush.threshold.size": "1000000",
            "realtime.segment.flush.threshold.time.ms": "600000000",
        }),
        segments_config=SegmentsConfig(replication=1,
                                       time_column_name="ts"))
    lat = []
    with tempfile.TemporaryDirectory() as d:
        cluster = EmbeddedCluster(d, num_servers=1)
        try:
            cluster.add_schema(schema)
            cluster.add_table(cfg)

            def count() -> int:
                resp = cluster.query("SELECT COUNT(*) FROM fresh")
                if resp.exceptions:
                    return -1
                return int(resp.aggregation_results[0].value)

            # warm: first event pays table/consumer spin-up
            stream.publish({"k": "w", "v": 0,
                            "ts": int(_t.time() * 1e3)}, partition=0)
            deadline = _t.monotonic() + 20
            while count() < 1 and _t.monotonic() < deadline:
                _t.sleep(0.005)
            seen = count()
            for i in range(events):
                t0 = _t.monotonic()
                stream.publish({"k": f"e{i}", "v": i,
                                "ts": int(_t.time() * 1e3)}, partition=0)
                ev_deadline = t0 + 20
                while count() <= seen:
                    if _t.monotonic() > ev_deadline:
                        raise RuntimeError(
                            f"freshness event {i} never became queryable")
                    _t.sleep(0.0005)
                lat.append((_t.monotonic() - t0) * 1e3)
                seen += 1
        finally:
            cluster.stop()
    return {"bench": "realtime_freshness", "n": events,
            "value": round(float(np.percentile(lat, 50)), 2),
            "p99_ms": round(float(np.percentile(lat, 99)), 2),
            "unit": "ms_p50_event_to_queryable"}


def bench_startree_prefix_descent(rows: int = 2_000_000) -> dict:
    """StarTree query path: prefix-descent block narrowing vs cube size."""
    import tempfile

    from pinot_tpu.pql.optimizer import BrokerRequestOptimizer
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    from pinot_tpu.tools.datagen import build_ssb_segment_dirs
    with tempfile.TemporaryDirectory() as d:
        dirs, _, _ = build_ssb_segment_dirs(d, rows, 1, seed=2,
                                            star_tree=True)
        seg = ImmutableSegmentLoader.load(dirs[0])
        req = BrokerRequestOptimizer().optimize(compile_pql(
            "SELECT SUM(lo_revenue) FROM lineorder WHERE c_nation = "
            "'UNITED STATES' AND s_nation = 'UNITED STATES' GROUP BY "
            "c_city, s_city, d_year TOP 10000 "
            "OPTION(numGroupsLimit=4194304)"))
        ex = ServerQueryExecutor()
        ex.execute(req, [seg])
        n_q = 20
        rate = _rate(n_q, lambda: [ex.execute(req, [seg])
                                   for _ in range(n_q)])
    return {"bench": "startree_prefix_group_by", "value": round(rate, 1),
            "unit": "queries/s"}


BENCHES: Dict[str, Callable[..., dict]] = {
    "dictionary_encode": bench_dictionary_encode,
    "fwd_pack_unpack": bench_fwd_pack_unpack,
    "inverted_lookup": bench_inverted_lookup,
    "segment_build": bench_segment_build,
    "realtime_consumption": bench_realtime_consumption,
    "realtime_freshness": bench_realtime_freshness,
    "startree_prefix_descent": bench_startree_prefix_descent,
}


def _scaled_kwargs(fn: Callable[..., dict], scale: float) -> dict:
    """Scale a bench's n/rows defaults (floor 1000) — ONE rule shared by
    run_all and the CLI so recorded and CLI numbers stay comparable."""
    import inspect
    kw = {}
    for pname, p in inspect.signature(fn).parameters.items():
        if pname in ("n", "rows") and isinstance(p.default, int):
            kw[pname] = max(1000, int(p.default * scale))
    return kw


def run_all(scale: float = 1.0) -> List[dict]:
    """Run every microbenchmark; `scale` multiplies row counts (CI smoke
    uses ~0.01)."""
    return [fn(**_scaled_kwargs(fn, scale)) for fn in BENCHES.values()]


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="component microbenchmarks")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--only", choices=sorted(BENCHES), default=None)
    args = ap.parse_args(argv)
    benches = {args.only: BENCHES[args.only]} if args.only else BENCHES
    for fn in benches.values():
        print(json.dumps(fn(**_scaled_kwargs(fn, args.scale))),
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
