"""Broker response model (the JSON the client receives).

Parity: pinot-common/.../response/broker/BrokerResponseNative.java — PQL
response shape: aggregationResults (plain or groupByResult), selectionResults,
exceptions, and the execution-stats fields
(ServerQueryExecutorV1Impl.java:190-197 metadata propagated through
BrokerReduceService).
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional, Tuple

#: Exception-message prefix → (errorCode, machine cause). Every degraded
#: path in the system raises/appends strings with one of these prefixes;
#: `classify_exception` turns them into structured entries so that
#: "flagged vs unflagged" is a field check, never a message grep. An
#: exception whose prefix is NOT here gets no errorCode — the SLO
#: classifier (obs/slo.py) counts it as UNFLAGGED, which is exactly the
#: signal that a new degraded path forgot to register itself.
EXCEPTION_CLASSES: Dict[str, Tuple[int, str]] = {
    "PQLParsingError:": (150, "parse"),
    "AccessDeniedError:": (180, "accessDenied"),
    "TableDoesNotExistError:": (190, "unknownTable"),
    "RoutingError:": (190, "routing"),
    "QueryExecutionError:": (200, "execution"),
    "RequestDeserializationError:": (200, "deserialization"),
    "DeadlineExceededError:": (250, "deadline"),
    "QueryTimeoutError:": (250, "timeout"),
    "StageCompileError:": (422, "stageCompile"),
    "JoinCapacityError:": (422, "joinCapacity"),
    "SegmentMissingError:": (425, "segmentMissing"),
    "ServerQueryError:": (425, "serverFault"),
    "ExchangeStageError:": (425, "exchange"),
    "ExchangeMissError:": (425, "exchangeMiss"),
    "ServerNotRespondedError:": (427, "noServerResponded"),
    "QuotaExceededError:": (429, "quotaExceeded"),
    "ServerBusyError:": (503, "serverBusy"),
}


def classify_exception(message: str) -> Optional[Tuple[int, str]]:
    """(errorCode, cause) for a known exception-message prefix, else
    None (→ the entry stays unflagged and the SLO gate trips)."""
    prefix = message.split(" ", 1)[0] if message else ""
    return EXCEPTION_CLASSES.get(prefix)


def exception_entry(message: str, error_code: Optional[int] = None,
                    cause: Optional[str] = None) -> dict:
    """Build a structured exceptions[] entry: message plus errorCode +
    cause, classified from the message prefix unless given explicitly."""
    entry: dict = {"message": message}
    cls = classify_exception(message)
    if cls is not None:
        entry["errorCode"], entry["cause"] = cls
    if error_code is not None:
        entry["errorCode"] = error_code
    if cause is not None:
        entry["cause"] = cause
    return entry


@dataclasses.dataclass
class AggregationResult:
    function: str
    value: Optional[object] = None
    # group-by variant:
    group_by_columns: Optional[List[str]] = None
    group_by_result: Optional[List[dict]] = None   # [{"group": [...], "value": v}]

    def to_json(self) -> dict:
        if self.group_by_result is not None:
            return {"function": self.function,
                    "groupByColumns": self.group_by_columns,
                    "groupByResult": self.group_by_result}
        return {"function": self.function, "value": _fmt(self.value)}


@dataclasses.dataclass
class SelectionResults:
    columns: List[str]
    results: List[list]

    def to_json(self) -> dict:
        return {"columns": self.columns, "results": self.results}


@dataclasses.dataclass
class BrokerResponse:
    aggregation_results: Optional[List[AggregationResult]] = None
    selection_results: Optional[SelectionResults] = None
    exceptions: List[dict] = dataclasses.field(default_factory=list)
    num_docs_scanned: int = 0
    num_entries_scanned_in_filter: int = 0
    num_entries_scanned_post_filter: int = 0
    num_segments_processed: int = 0
    num_segments_matched: int = 0
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    num_consuming_segments_queried: int = 0
    min_consuming_freshness_time_ms: int = 0
    num_groups_limit_reached: bool = False
    total_docs: int = 0
    time_used_ms: float = 0.0
    # honest-degradation flag: True whenever the result may be missing
    # data (a server never responded, a segment had no live replica, or
    # execution was truncated by the deadline) — clients must be able to
    # tell a partial answer from a full one without string-matching
    # exception messages
    partial_response: bool = False
    # trace=true responses: {"broker": [...spans], "<server>": [...spans]}
    # (flat per-participant span lists; spans carry spanId/parentId)
    trace_info: Optional[Dict[str, list]] = None
    # trace=true responses: ONE merged cross-process tree — broker
    # compile/route/scatter/reduce spans with each server's queue-wait/
    # plan/execute/serde subtree grafted under its dispatch span
    trace_tree: Optional[dict] = None

    def to_json(self) -> dict:
        d = {
            "exceptions": self.exceptions,
            "numDocsScanned": self.num_docs_scanned,
            "numEntriesScannedInFilter": self.num_entries_scanned_in_filter,
            "numEntriesScannedPostFilter":
                self.num_entries_scanned_post_filter,
            "numSegmentsProcessed": self.num_segments_processed,
            "numSegmentsMatched": self.num_segments_matched,
            "numServersQueried": self.num_servers_queried,
            "numServersResponded": self.num_servers_responded,
            "numGroupsLimitReached": self.num_groups_limit_reached,
            "partialResponse": self.partial_response,
            "totalDocs": self.total_docs,
            "timeUsedMs": round(self.time_used_ms, 3),
        }
        if self.num_consuming_segments_queried:
            # realtime queries only (parity: the reference emits the
            # freshness pair only when consuming segments were queried;
            # an unconditional 0 would read as epoch-stale data)
            d["numConsumingSegmentsQueried"] = \
                self.num_consuming_segments_queried
            d["minConsumingFreshnessTimeMs"] = \
                self.min_consuming_freshness_time_ms
        if self.aggregation_results is not None:
            d["aggregationResults"] = [a.to_json()
                                       for a in self.aggregation_results]
        if self.selection_results is not None:
            d["selectionResults"] = self.selection_results.to_json()
        if self.trace_info is not None:
            d["traceInfo"] = self.trace_info
        if self.trace_tree is not None:
            d["traceTree"] = self.trace_tree
        return d

    def to_json_str(self) -> str:
        return json.dumps(self.to_json())


def _fmt(v):
    """Format final aggregation values as strings (the reference renders
    numbers as strings in the JSON response); floats keep full precision."""
    if v is None:
        return "null"
    if isinstance(v, bool):
        return str(v).lower()
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        return str(int(v)) if v == int(v) and abs(v) < 1e15 else str(v)
    return str(v)
