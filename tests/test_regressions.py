"""Regression tests for review findings: MV negated predicates, raw
DISTINCTCOUNT fallback, empty-filter + SELECT * merge, COUNTMV fast paths,
bloom pruning literal normalization."""
import os
import tempfile

import numpy as np
import pytest

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.schema import Schema, dimension, metric
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine import QueryEngine
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader


def _build(tmp, schema, cols, tc=None, name=None):
    SegmentCreator(schema, tc, segment_name=name).build(cols, tmp)
    return ImmutableSegmentLoader.load(tmp)


@pytest.fixture(scope="module")
def mv_seg():
    tmp = tempfile.mkdtemp()
    schema = Schema("t", [dimension("tags", DataType.STRING,
                                    single_value=False),
                          metric("v", DataType.INT)])
    cols = {
        # doc0 has ONLY 'x' and fewer entries than the padded width
        "tags": [["x"], ["x", "y"], ["y", "z", "w"], ["z"]],
        "v": np.array([1, 2, 3, 4], np.int32),
    }
    return _build(tmp, schema, cols), cols


def test_mv_neq_excludes_padding(mv_seg):
    seg, cols = mv_seg
    for use_device in (True, False):
        e = QueryEngine([seg], use_device=use_device)
        # doc0's only value is 'x' → must NOT match tags <> 'x'
        r = e.query("SELECT COUNT(*) FROM t WHERE tags <> 'x'")
        assert r.aggregation_results[0].value == "3", use_device
        r = e.query("SELECT COUNT(*) FROM t WHERE tags NOT IN ('x', 'y')")
        assert r.aggregation_results[0].value == "2", use_device


def test_countmv_counts_entries_not_docs(mv_seg):
    seg, cols = mv_seg
    total_entries = sum(len(x) for x in cols["tags"])
    for use_device in (True, False):
        e = QueryEngine([seg], use_device=use_device)
        r = e.query("SELECT COUNTMV(tags) FROM t")  # no filter → fast path?
        assert r.aggregation_results[0].value == str(total_entries), use_device
        r = e.query("SELECT COUNTMV(tags) FROM t WHERE v > 1")
        assert r.aggregation_results[0].value == str(
            sum(len(x) for x, v in zip(cols["tags"], cols["v"]) if v > 1))


def test_distinctcount_on_raw_column_falls_back():
    tmp = tempfile.mkdtemp()
    schema = Schema("t", [metric("m", DataType.FLOAT),
                          dimension("d", DataType.INT)])
    tc = TableConfig("t", indexing_config=IndexingConfig(
        no_dictionary_columns=["m"]))
    cols = {"m": np.array([1.5, 2.5, 1.5, 3.5], np.float32),
            "d": np.array([1, 1, 2, 2], np.int32)}
    seg = _build(tmp, schema, cols, tc)
    e = QueryEngine([seg])
    r = e.query("SELECT DISTINCTCOUNT(m) FROM t")
    assert r.aggregation_results[0].value == "3"
    r = e.query("SELECT PERCENTILE50(m) FROM t WHERE d = 1")
    assert float(r.aggregation_results[0].value) == 2.5


def test_select_star_order_by_with_empty_segment_merge():
    schema = Schema("t", [dimension("k", DataType.STRING),
                          metric("v", DataType.INT)])
    segs = []
    base = tempfile.mkdtemp()
    for i, ks in enumerate([["a", "b"], ["c", "d"]]):
        d = os.path.join(base, f"s{i}")
        os.makedirs(d)
        cols = {"k": np.array(ks, dtype=object),
                "v": np.array([i * 10 + 1, i * 10 + 2], np.int32)}
        segs.append(_build(d, schema, cols, name=f"s{i}"))
    for use_device in (True, False):
        e = QueryEngine(segs, use_device=use_device)
        # 'c' exists only in segment 2; segment 1 resolves EMPTY
        r = e.query("SELECT * FROM t WHERE k = 'c' ORDER BY v LIMIT 10")
        assert r.selection_results.columns == ["k", "v"], use_device
        assert r.selection_results.results == [["c", 11]], use_device


def test_bloom_pruner_numeric_literal_normalization():
    tmp = tempfile.mkdtemp()
    schema = Schema("t", [metric("price", DataType.FLOAT),
                          dimension("d", DataType.INT)])
    tc = TableConfig("t", indexing_config=IndexingConfig(
        bloom_filter_columns=["price"]))
    cols = {"price": np.array([5.0, 7.5, 9.0], np.float32),
            "d": np.array([1, 2, 3], np.int32)}
    seg = _build(tmp, schema, cols, tc)
    e = QueryEngine([seg])
    # '5' must not be bloom-pruned just because it hashes differently
    # than '5.0'
    r = e.query("SELECT COUNT(*) FROM t WHERE price = 5")
    assert r.aggregation_results[0].value == "1"
    assert r.num_segments_processed == 1


def test_compacted_group_by_chunked_psums(monkeypatch):
    """kmax > DENSE_ROWS_LIMIT: the compacted psums scatter must chunk so
    each int32 scatter covers <= DENSE_ROWS_LIMIT rows (no wraparound),
    and the host must recombine the chunks exactly in int64."""
    from pinot_tpu.ops import kernels
    from pinot_tpu.query import plan as plan_mod

    monkeypatch.setattr(kernels, "DENSE_ROWS_LIMIT", 256)
    # distinctive shape so the jit cache can't hand back a kernel traced
    # with the real DENSE_ROWS_LIMIT
    n = 3100
    rng = np.random.default_rng(11)
    tmp = tempfile.mkdtemp()
    schema = Schema("t", [dimension("g", DataType.STRING),
                          metric("v", DataType.INT)])
    cols = {"g": np.array(["g%02d" % i for i in
                           rng.integers(0, 7, n)], dtype=object),
            "v": rng.integers(0, 100_000, n).astype(np.int32)}
    seg = _build(tmp, schema, cols)
    # kmax starts at 1024 (> 256) and escalates to padded on overflow;
    # the filter matches nearly every row so escalation is exercised too
    expected = {}
    msk = cols["v"] >= 5
    for g, v, m in zip(cols["g"], cols["v"], msk):
        if m:
            expected[g] = expected.get(g, 0) + int(v)
    e = QueryEngine([seg], use_device=True)
    r = e.query("SELECT SUM(v) FROM t WHERE v >= 5 GROUP BY g TOP 10")
    got = {gr["group"][0]: float(gr["value"])
           for gr in r.aggregation_results[0].group_by_result}
    assert got == {k: float(v) for k, v in expected.items()}


def test_order_by_unselected_column_multi_segment():
    """ORDER BY a column that is not selected: rows must merge across
    segments in key order, and the response must show only the selected
    columns (the gathered order keys are trimmed by the reducer)."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import build_shared_segments
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, 3, n=1024, seed=4)
    order = np.argsort(merged["yearID"], kind="stable")
    exp = [merged["playerName"][i] for i in order[:7]]
    exp_years = sorted(merged["yearID"])[:7]
    for use_device in (True, False):
        e = QueryEngine(segs, use_device=use_device)
        r = e.query("SELECT playerName FROM baseballStats "
                    "ORDER BY yearID LIMIT 7")
        assert r.selection_results.columns == ["playerName"], use_device
        rows = r.selection_results.results
        assert len(rows) == 7
        # the single-column rows must match the two-column query's names
        rr = e.query("SELECT playerName, yearID FROM baseballStats "
                     "ORDER BY yearID LIMIT 7")
        assert [row[1] for row in rr.selection_results.results] == \
            [int(y) for y in exp_years], use_device
        assert [row[0] for row in rows] == \
            [row[0] for row in rr.selection_results.results], use_device


def test_virtual_columns():
    """$docId / $segmentName / $hostName (parity:
    core/segment/virtualcolumn/VirtualColumnProviderFactory)."""
    import socket
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import build_shared_segments
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, 2, n=1024, seed=6)
    for use_device in (True, False):
        e = QueryEngine(segs, use_device=use_device)
        r = e.query("SELECT COUNT(*) FROM baseballStats WHERE $docId < 100")
        assert r.aggregation_results[0].value == str(2 * 100), use_device
        r2 = e.query("SELECT COUNT(*) FROM baseballStats "
                     "GROUP BY $segmentName TOP 10")
        got = {g["group"][0]: g["value"]
               for g in r2.aggregation_results[0].group_by_result}
        assert got == {"shared_0": "1024", "shared_1": "1024"}, use_device
        r3 = e.query("SELECT COUNT(*) FROM baseballStats "
                     "GROUP BY $hostName TOP 5")
        groups = r3.aggregation_results[0].group_by_result
        assert len(groups) == 1
        assert groups[0]["group"][0] == socket.gethostname()
