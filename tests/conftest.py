"""Test config: CPU backend with 8 virtual devices + x64 for exact oracles.

Must run before jax is imported anywhere.
"""
import os
import sys

# Force CPU so the suite is hermetic and the virtual 8-device mesh exists
# even when the surrounding environment points JAX at a real accelerator.
# sitecustomize may have imported jax already, so set env AND update config
# (safe as long as no backend has been initialized yet).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end tests (tier-1 excludes)")
