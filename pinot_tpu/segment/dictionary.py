"""Sorted dictionaries: value <-> dictId encoding.

Parity: pinot-core/.../segment/creator/impl/SegmentDictionaryCreator.java and
the ImmutableDictionaryReader family (core/segment/index/readers/) — sorted
unique values, id = rank. Because values are sorted, range predicates resolve
to contiguous dictId intervals, which is what makes the TPU filter kernels
pure vectorized integer compares (SURVEY.md §7 "guiding translation").
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment import format as fmt


class Dictionary:
    """Immutable sorted dictionary for one column."""

    def __init__(self, data_type: DataType, values: np.ndarray):
        self.data_type = data_type
        self.values = values  # sorted unique; numeric ndarray or object array

    # -- core api ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self.values)

    @property
    def cardinality(self) -> int:
        return len(self.values)

    def get(self, dict_id: int):
        return self.values[dict_id]

    def index_of(self, value) -> int:
        """Exact lookup; -1 if absent (reference: Dictionary.indexOf)."""
        v = self._coerce(value)
        i = int(np.searchsorted(self.values, v))
        if i < len(self.values) and self.values[i] == v:
            return i
        return -1

    def index_of_many(self, values: Sequence) -> np.ndarray:
        return np.array([self.index_of(v) for v in values], dtype=np.int32)

    def encode(self, column: np.ndarray) -> np.ndarray:
        """Vectorized value→dictId for a full column (build path)."""
        if self.values.dtype.kind == "U":
            column = self._fast_str_cast(self.data_type, column)
            if np.asarray(column).dtype.kind != "U":
                # pathological long values: search in the object domain
                return np.searchsorted(
                    self.values.astype(object), column).astype(np.int32)
        ids = np.searchsorted(self.values, column)
        return ids.astype(np.int32)

    def decode(self, dict_ids: np.ndarray) -> np.ndarray:
        return self.values[dict_ids]

    def range_to_id_interval(self, lower, upper, lower_inclusive: bool,
                             upper_inclusive: bool) -> Tuple[int, int]:
        """Map a value range to a half-open dictId interval [lo, hi).

        This is the host-side predicate resolution step: a RANGE predicate on
        a dictionary-encoded column becomes ``lo <= dictId < hi`` on device.
        """
        if lower is None:
            lo = 0
        else:
            lv = self._coerce(lower)
            side = "left" if lower_inclusive else "right"
            lo = int(np.searchsorted(self.values, lv, side=side))
        if upper is None:
            hi = len(self.values)
        else:
            uv = self._coerce(upper)
            side = "right" if upper_inclusive else "left"
            hi = int(np.searchsorted(self.values, uv, side=side))
        return lo, max(lo, hi)

    @property
    def min_value(self):
        return self.values[0] if len(self.values) else None

    @property
    def max_value(self):
        return self.values[-1] if len(self.values) else None

    def _coerce(self, value):
        if self.data_type.is_numeric:
            # keep exact int when possible (int64 > 2^53 loses precision as
            # float); fall back to float so fractional bounds on int columns
            # (e.g. RANGE x > 2.5) still order correctly under searchsorted
            try:
                return int(str(value))
            except ValueError:
                return float(value)
        if self.data_type == DataType.BYTES:
            return value if isinstance(value, bytes) else bytes.fromhex(str(value))
        return str(value)

    # -- build + serde -----------------------------------------------------
    # fixed-width unicode columns allocate rows * max_len * 4 bytes; one
    # pathological long value would blow that up, so the C-speed cast
    # only applies under this per-value width
    _STR_FAST_MAX_LEN = 256

    @classmethod
    def _fast_str_cast(cls, data_type: DataType, column: np.ndarray):
        if data_type != DataType.STRING or \
                np.asarray(column).dtype.kind != "O":
            return column
        if len(column) and max(map(len, column)) > cls._STR_FAST_MAX_LEN:
            return column                     # object path: no blowup
        return np.asarray(column, dtype=np.str_)

    @classmethod
    def build_encoded(cls, data_type: DataType, column: np.ndarray):
        """(dictionary, encoded ids) in one pass, O(n) where possible.

        np.unique is an O(n log n) argsort — profiled as ~60% of the whole
        segment build at 50M rows. Two linear-time ladders replace it:
        small-range integers go through bincount (9x faster than unique);
        everything else through a hash factorize (15x faster on object
        strings, and no fixed-width unicode cast needed at row scale).
        The sorted-unique-values + id==rank contract is unchanged.
        """
        arr = np.asarray(column) if not isinstance(column, np.ndarray) \
            else column
        n = arr.size
        # -- small-range integer fast path: one bincount ------------------
        if n and arr.dtype.kind in "iu":
            mn, mx = int(arr.min()), int(arr.max())
            span = mx - mn + 1
            if span <= max(4 * n, 1 << 16):
                if arr.dtype.kind == "u":
                    # subtract in the native dtype first: uint64 values
                    # past 2**63 don't fit int64 until shifted down
                    shifted = (arr - arr.dtype.type(mn)).astype(np.int64)
                else:
                    shifted = arr.astype(np.int64) - mn
                counts = np.bincount(shifted, minlength=span)
                present = np.nonzero(counts)[0]
                lut = np.zeros(span, np.int32)
                lut[present] = np.arange(len(present), dtype=np.int32)
                values = (present.astype(arr.dtype) +
                          arr.dtype.type(mn)) if arr.dtype.kind == "u" \
                    else (present + mn).astype(arr.dtype)
                return cls(data_type, values), lut[shifted]
        # -- hash factorize: linear, works directly on object strings -----
        if n:
            from pinot_tpu.utils.factorize import sorted_factorize
            fact = sorted_factorize(arr)
            if fact is not None:
                uniq, inv = fact
                values = cls._fast_str_cast(data_type, uniq)
                return cls(data_type, np.asarray(values)), \
                    inv.astype(np.int32)
        column = cls._fast_str_cast(data_type, arr)
        uniq, inv = np.unique(column, return_inverse=True)
        return cls(data_type, uniq), inv.astype(np.int32)

    @classmethod
    def build(cls, data_type: DataType, column: np.ndarray) -> "Dictionary":
        # fixed-width unicode sorts/searches at C speed; object-array
        # sorts are python-compare bound (profiled: np.unique over
        # object strings was ~60% of the whole segment build)
        column = cls._fast_str_cast(data_type, column)
        uniq = np.unique(column)
        return cls(data_type, uniq)

    def save(self, seg_dir: str, col: str) -> None:
        if self.data_type.is_numeric:
            np.save(os.path.join(seg_dir, fmt.DICT_NUMERIC.format(col=col)),
                    self.values)
        else:
            encoded = [_to_bytes(v, self.data_type) for v in self.values]
            offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
            np.cumsum([len(b) for b in encoded], out=offsets[1:])
            with open(os.path.join(seg_dir, fmt.DICT_BYTES.format(col=col)),
                      "wb") as f:
                f.write(b"".join(encoded))
            np.save(os.path.join(seg_dir, fmt.DICT_OFFSETS.format(col=col)),
                    offsets)

    @classmethod
    def load(cls, seg_dir, col: str, data_type: DataType) -> "Dictionary":
        d = fmt.open_dir(seg_dir)
        if data_type.is_numeric:
            values = d.load_array(fmt.DICT_NUMERIC.format(col=col))
            return cls(data_type, values)
        offsets = d.load_array(fmt.DICT_OFFSETS.format(col=col))
        blob = d.read_bytes(fmt.DICT_BYTES.format(col=col))
        vals: List = []
        for i in range(len(offsets) - 1):
            raw = blob[offsets[i]:offsets[i + 1]]
            vals.append(raw if data_type == DataType.BYTES
                        else raw.decode("utf-8"))
        return cls(data_type, np.array(vals, dtype=object))


def _to_bytes(v, data_type: DataType) -> bytes:
    if data_type == DataType.BYTES:
        return v if isinstance(v, bytes) else bytes(v)
    return str(v).encode("utf-8")
