"""api-compat: JAX symbols absent from the installed version, or on a
deprecation denylist.

Exactly the failure class that took out the seed: `jax.shard_map` is
the JAX ≥ 0.6 spelling; on the pinned 0.4.x it lives at
`jax.experimental.shard_map.shard_map`, and every call site raised
AttributeError at query time — 33 tier-1 failures from one symbol.
The rule resolves every statically-visible `jax.*` dotted chain (and
every `import`/`from ... import` of a jax module) against the
INSTALLED jax via importlib/getattr, so version skew is caught at lint
time, not discovered one bench regression at a time. Version-portable
call sites go through `pinot_tpu.compat`, which probes with getattr —
invisible to (and the sanctioned escape from) this rule.
"""
from __future__ import annotations

import ast
import importlib
import warnings
from typing import Dict, Iterator, Optional

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

#: dotted path → why it must not be used (fires even when resolvable)
DENYLIST: Dict[str, str] = {
    "jax.tree_map": "removed in JAX 0.6 — use jax.tree.map",
    "jax.tree_multimap": "long removed — use jax.tree.map",
    "jax.tree_util.tree_multimap": "removed — use jax.tree_util.tree_map",
    "jax.experimental.host_callback":
        "removed — use jax.pure_callback / jax.debug.callback",
    "jax.experimental.maps": "xmap is removed — use jax.shard_map "
                             "(via pinot_tpu.compat)",
    "jax.experimental.pjit.pjit": "legacy alias — jax.jit takes shardings",
    "jax.abstract_arrays": "removed module",
    "jax.linear_util": "removed module",
    "jax.config.config": "removed — use jax.config.update",
}

_ROOTS = ("jax",)


class _Resolver:
    """getattr/import_module walk over the installed jax, memoized."""

    def __init__(self):
        self._cache: Dict[str, bool] = {}

    def resolvable(self, dotted: str) -> bool:
        hit = self._cache.get(dotted)
        if hit is not None:
            return hit
        parts = dotted.split(".")
        ok = True
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            try:
                obj = importlib.import_module(parts[0])
                for i, name in enumerate(parts[1:], start=1):
                    try:
                        obj = getattr(obj, name)
                    except AttributeError:
                        # lazily-imported submodule (jax.experimental.*)
                        obj = importlib.import_module(
                            ".".join(parts[: i + 1]))
            except ImportError:
                ok = False
        self._cache[dotted] = ok
        return ok


_RESOLVER = _Resolver()


@register
class ApiCompatRule(Rule):
    id = "api-compat"
    description = ("jax symbols absent from the installed version or on "
                   "the deprecation denylist")

    def check(self, ctx) -> Iterator[Finding]:
        sites = []   # (line, dotted, node)
        for node in ast.walk(ctx.tree):
            for dotted in self._site_dotteds(node, ctx):
                if dotted.split(".")[0] in _ROOTS:
                    sites.append((getattr(node, "lineno", 0), dotted,
                                  node))
        # keep only maximal chains: `jax.foo` riding inside `jax.foo.bar`
        # on the same line is the same usage site, not a second one
        by_line: Dict[int, list] = {}
        for line, dotted, _node in sites:
            by_line.setdefault(line, []).append(dotted)
        seen = set()
        for line, dotted, node in sites:
            if (line, dotted) in seen:
                continue
            seen.add((line, dotted))
            if any(other.startswith(dotted + ".")
                   for other in by_line[line] if other != dotted):
                continue
            deny = self._denied(dotted)
            if deny is not None:
                yield ctx.finding(self.id, node,
                                  f"`{deny}` is denylisted: "
                                  f"{DENYLIST[deny]}")
            elif not _RESOLVER.resolvable(dotted):
                import jax
                yield ctx.finding(
                    self.id, node,
                    f"`{dotted}` does not exist in the installed jax "
                    f"{jax.__version__} — gate it behind "
                    "pinot_tpu.compat")

    @staticmethod
    def _site_dotteds(node: ast.AST, ctx) -> list:
        if isinstance(node, ast.Attribute):
            d = astutil.resolve(node, ctx.aliases)
            return [d] if d else []
        if isinstance(node, ast.Import):
            return [a.name for a in node.names]
        if isinstance(node, ast.ImportFrom) and node.module and \
                not node.level:
            return [f"{node.module}.{a.name}" for a in node.names
                    if a.name != "*"]
        return []

    @staticmethod
    def _denied(dotted: str) -> Optional[str]:
        # a chain is denied if it IS a denylist entry or extends one
        # (jax.experimental.host_callback.call → the module entry)
        probe = dotted
        while probe:
            if probe in DENYLIST:
                return probe
            probe, _, _ = probe.rpartition(".")
        return None
