#!/usr/bin/env python
"""Self-healing chaos gate: continuous two-table load under kill -9 +
standby failover + graceful drain.

Boots the HA distributed shape — a standalone durable store (the ZK
role), a LEAD and a STANDBY controller sharing it (1s leader lease,
fenced mutations), three servers, one broker — with TWO tables under a
continuous query workload: an OFFLINE table at replication 2 and a
REALTIME primary-key-upsert table. Then, in order:

  1. kill -9 the server owning the consuming partition → the health
     monitor declares it dead after grace, the rebalancer restores full
     replication, and the consuming partition is taken over by a
     survivor that resumes from the last committed offset —
     exact-count + latest-value convergence.
  2. kill -9 the LEAD controller → the standby's lease takeover happens
     within ~one lease period; segment commits keep flowing through it
     (servers re-resolve the active controller endpoint from the store).
  3. SIGTERM-drain a server (seal consuming segments, deregister,
     finish in-flight work) → ZERO query errors in the drain window.

Gate: both tables converge exactly after every phase, zero NON-FLAGGED
query errors across the whole run (kill -9 windows may surface
partial-flagged responses — that's the broker being honest), zero
errors of any kind during the drain, and the cluster ends at
replication deficit 0. Result committed as SELFHEAL_r08.json.

Env knobs:
  SELFHEAL_ROWS       realtime rows (default 600)
  SELFHEAL_WINDOW_S   per-phase convergence window (default 60)
"""
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROWS = int(os.environ.get("SELFHEAL_ROWS", "600"))
WINDOW_S = float(os.environ.get("SELFHEAL_WINDOW_S", "60"))
OFF_TABLE = "baseballStats_OFFLINE"
RT_TABLE = "upsertStats_REALTIME"
TOPIC = "selfheal_topic"
FACTORY = "mem_selfheal"
LEASE_S = 1.0
GRACE_S = 1.5


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:  # noqa: BLE001 — still converging
            pass
        time.sleep(0.1)
    print(f"FAIL: timed out waiting for {what}", file=sys.stderr)
    return False


def upsert_schema():
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (Schema, TimeUnit, dimension,
                                         metric, time_field)
    return Schema("upsertStats", [
        dimension("playerName", DataType.STRING),
        dimension("teamID", DataType.STRING),
        metric("runs", DataType.INT),
        time_field("yearID", DataType.INT, TimeUnit.DAYS),
    ])


def upsert_config():
    from pinot_tpu.common.table_config import (IndexingConfig,
                                               SegmentsConfig, TableConfig,
                                               TableType, UpsertConfig)
    return TableConfig(
        "upsertStats", table_type=TableType.REALTIME,
        indexing_config=IndexingConfig(stream_configs={
            "stream.factory.name": FACTORY,
            "stream.topic.name": TOPIC,
            "realtime.segment.flush.threshold.size": "80",
            "realtime.segment.flush.threshold.time.ms": "600000000",
        }),
        segments_config=SegmentsConfig(replication=1,
                                       time_column_name="yearID"),
        upsert_config=UpsertConfig(mode="FULL",
                                   primary_key_columns=["playerName"]))


def make_rows(n, seed):
    import random
    rng = random.Random(seed)
    return [{
        "playerName": f"player_{rng.randrange(max(40, n // 4)):04d}",
        "teamID": rng.choice(["BOS", "NYA", "SEA", "HOU"]),
        "runs": rng.randrange(0, 150),
        "yearID": rng.randrange(1990, 2020),
    } for _ in range(n)]


def key_partition(row) -> int:
    """Primary-key-hash partitioning (upsert requires a key to stay in
    ONE stream partition — the per-partition key maps are independent)."""
    import zlib
    return zlib.crc32(row["playerName"].encode()) % 2


class Workload(threading.Thread):
    """Continuous two-table query loop; tallies error classes."""

    def __init__(self, broker):
        super().__init__(daemon=True)
        self.broker = broker
        self.stop_evt = threading.Event()
        self.total = 0
        self.flagged = 0            # partial-flagged responses (chaos-ok)
        self.unflagged = []         # NEVER acceptable
        self.window_errors = []     # any error inside a marked window
        self._in_window = False
        self._lock = threading.Lock()

    def mark_window(self, active: bool) -> None:
        with self._lock:
            self._in_window = active

    def run(self):
        queries = ("SELECT COUNT(*) FROM baseballStats",
                   "SELECT COUNT(*), SUM(runs) FROM upsertStats")
        i = 0
        while not self.stop_evt.is_set():
            q = queries[i % 2]
            i += 1
            try:
                resp = self.broker.query(q)
                exceptions = list(resp.exceptions or ())
                flagged = bool(resp.partial_response)
            except Exception as e:  # noqa: BLE001 — an unhandled raise
                exceptions, flagged = [f"raised: {e}"], False
            self.total += 1
            if exceptions:
                if flagged:
                    self.flagged += 1
                else:
                    self.unflagged.append((q, exceptions[:1]))
                with self._lock:
                    if self._in_window:
                        self.window_errors.append((q, exceptions[:1]))
            time.sleep(0.02)


def main() -> int:
    from pinot_tpu.common.metrics import ControllerMeter
    from pinot_tpu.controller.rebalance import replication_deficit
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.distributed import (DistributedBroker,
                                             DistributedController,
                                             DistributedServer,
                                             StandaloneStore)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from fixtures import build_segment, make_schema, make_table_config
    from pinot_tpu.common.table_config import SegmentsConfig

    base = tempfile.mkdtemp(prefix="pinot_tpu_selfheal_")
    t0 = time.monotonic()
    result = {"phases": {}}

    def log(msg):
        print(f"[{time.monotonic() - t0:6.1f}s] {msg}", flush=True)

    stream = MemoryStream(TOPIC, num_partitions=2)
    registry.register_stream_factory(
        FACTORY, MemoryStreamConsumerFactory(stream, batch_size=40))

    zk = StandaloneStore(os.path.join(base, "zk"))
    lead = DistributedController(base, store_addr=("127.0.0.1", zk.port),
                                 instance_id="ctrl_lead", http=True,
                                 lease_s=LEASE_S)
    standby = DistributedController(base,
                                    store_addr=("127.0.0.1", zk.port),
                                    standby=True, http=True,
                                    instance_id="ctrl_standby",
                                    lease_s=LEASE_S)
    for ctrl in (lead, standby):
        ctrl.controller.health_monitor.grace_s = GRACE_S
    if not wait_for(lead.is_leader, 10, "lead controller lease"):
        return 1
    servers = {}
    for i in range(3):
        name = f"Server_{i}"
        servers[name] = DistributedServer(
            name, "127.0.0.1", zk.port, lead.deep_store_dir,
            work_dir=os.path.join(base, f"s{i}_work"),
            controller_http="auto")
    broker = DistributedBroker("127.0.0.1", zk.port, lead.deep_store_dir)

    # -- tables + data ------------------------------------------------------
    mgr = lead.controller.manager
    mgr.add_schema(make_schema())
    mgr.add_schema(upsert_schema())
    mgr.add_table(make_table_config(
        segments_config=SegmentsConfig(replication=2)))
    off_total = 0
    for i in range(3):
        d = os.path.join(base, f"offseg{i}")
        os.makedirs(d)
        build_segment(d, n=700, seed=40 + i, name=f"offseg_{i}")
        mgr.add_segment(OFF_TABLE, d)
        off_total += 700
    lead.controller.realtime.setup_table(upsert_config())

    rows = make_rows(ROWS, seed=23)
    latest = {}
    for r in rows:
        latest[r["playerName"]] = r
    third = ROWS // 3
    for r in rows[:third]:
        stream.publish(r, partition=key_partition(r))
    exp1 = {r["playerName"]: r for r in rows[:third]}

    def off_count():
        r = broker.query("SELECT COUNT(*) FROM baseballStats")
        return -1 if r.exceptions else \
            int(r.aggregation_results[0].value)

    def rt_state():
        r = broker.query("SELECT COUNT(*), SUM(runs) FROM upsertStats")
        if r.exceptions or not r.aggregation_results:
            return (-1, -1.0)
        return (int(r.aggregation_results[0].value),
                float(r.aggregation_results[1].value))

    def rt_converged(expect):
        cnt = len(expect)
        total = float(sum(r["runs"] for r in expect.values()))
        return rt_state() == (cnt, total)

    def consuming_owners():
        from pinot_tpu.realtime.segment_name import LLCSegmentName
        ideal = standby.controller.coordinator.ideal_state(RT_TABLE)
        owners = {}
        for seg, states in ideal.items():
            for inst, st in states.items():
                if st == "CONSUMING" and LLCSegmentName.is_llc(seg):
                    owners[LLCSegmentName.parse(seg).partition] = inst
        return owners

    def committed_count():
        m = standby.controller.manager
        return sum(1 for s in m.segment_names(RT_TABLE)
                   if (m.segment_metadata(RT_TABLE, s) or {}).get(
                       "status") == "DONE")

    if not wait_for(lambda: off_count() == off_total, WINDOW_S,
                    "offline bootstrap"):
        return 1
    if not wait_for(lambda: rt_converged(exp1), WINDOW_S,
                    "realtime bootstrap (needs a committed segment for "
                    "the workload to survive the kill)"):
        return 1
    if not wait_for(lambda: committed_count() >= 1, WINDOW_S,
                    "first committed upsert segment"):
        return 1
    log(f"bootstrap: offline={off_total} rows, realtime "
        f"{len(exp1)} keys, {committed_count()} committed segment(s)")

    workload = Workload(broker)
    workload.start()
    ok = True
    try:
        # ---- phase 1: kill -9 the consuming server ------------------------
        owners = consuming_owners()
        assert owners, "no consuming partitions"
        part, victim = sorted(owners.items())[0]
        p0 = time.monotonic()
        workload.mark_window(True)      # chaos window: flagged-only
        servers.pop(victim).kill()
        log(f"phase 1: kill -9 {victim} (owned the consuming partition)")
        for r in rows[third:2 * third]:
            stream.publish(r, partition=key_partition(r))
        exp2 = {r["playerName"]: r for r in rows[:2 * third]}
        ok &= wait_for(
            lambda: replication_deficit(standby.controller.manager) == 0,
            WINDOW_S, "replication repaired after server kill")
        ok &= wait_for(
            lambda: consuming_owners().get(part) not in (None, victim),
            WINDOW_S, f"takeover of consuming partition {part}")
        ok &= wait_for(lambda: off_count() == off_total, WINDOW_S,
                       "offline count after repair")
        ok &= wait_for(lambda: rt_converged(exp2), WINDOW_S,
                       "realtime exact-count/latest-value after takeover")
        workload.mark_window(False)
        result["phases"]["killServer"] = {
            "victim": victim, "seconds": round(time.monotonic() - p0, 2),
            "converged": bool(ok)}
        log(f"phase 1 done in {time.monotonic() - p0:.1f}s (ok={ok})")

        # ---- phase 2: kill -9 the lead controller -------------------------
        commits_before = committed_count()
        p0 = time.monotonic()
        lead.kill()
        log("phase 2: kill -9 lead controller (lease must expire)")
        ok &= wait_for(standby.is_leader, 10, "standby lease takeover")
        takeover_s = time.monotonic() - p0
        if takeover_s > 3 * LEASE_S + 1.0:
            print(f"FAIL: takeover took {takeover_s:.1f}s "
                  f"(> ~one lease period)", file=sys.stderr)
            ok = False
        # commits must flow THROUGH THE STANDBY: publish enough to seal
        for r in rows[2 * third:]:
            stream.publish(r, partition=key_partition(r))
        exp3 = {r["playerName"]: r for r in rows}
        ok &= wait_for(lambda: committed_count() > commits_before,
                       WINDOW_S, "a segment committed via the standby")
        ok &= wait_for(lambda: rt_converged(exp3), WINDOW_S,
                       "realtime convergence under the standby")
        ok &= wait_for(lambda: off_count() == off_total, WINDOW_S,
                       "offline count under the standby")
        result["phases"]["killController"] = {
            "takeoverSeconds": round(takeover_s, 2),
            "leasePeriodSeconds": LEASE_S,
            "commitsViaStandby": committed_count() - commits_before,
            "leaderFailovers": standby.controller.metrics.meter(
                ControllerMeter.LEADER_FAILOVERS).count,
            "converged": bool(ok)}
        log(f"phase 2 done: takeover {takeover_s:.2f}s, "
            f"{committed_count() - commits_before} commit(s) via standby")

        # ---- phase 3: SIGTERM drain ---------------------------------------
        victim2 = next((inst for inst in consuming_owners().values()
                        if inst in servers), None) or sorted(servers)[0]
        p0 = time.monotonic()
        err_before = len(workload.window_errors)
        workload.mark_window(True)      # drain window: NO errors at all
        sealed = servers.pop(victim2).drain()
        drain_errors = list(workload.window_errors[err_before:])
        workload.mark_window(False)
        ok &= wait_for(
            lambda: replication_deficit(standby.controller.manager) == 0,
            WINDOW_S, "replication repaired after drain")
        ok &= wait_for(lambda: rt_converged(exp3), WINDOW_S,
                       "realtime convergence after drain")
        ok &= wait_for(lambda: off_count() == off_total, WINDOW_S,
                       "offline count after drain")
        if drain_errors:
            print(f"FAIL: {len(drain_errors)} query error(s) during the "
                  f"drain window: {drain_errors[:3]}", file=sys.stderr)
            ok = False
        result["phases"]["drainServer"] = {
            "victim": victim2, "sealed": bool(sealed),
            "seconds": round(time.monotonic() - p0, 2),
            "drainWindowErrors": len(drain_errors),
            "converged": bool(ok)}
        log(f"phase 3 done: drained {victim2} (sealed={sealed}, "
            f"{len(drain_errors)} window errors)")
    finally:
        workload.stop_evt.set()
        workload.join(timeout=10)

    if workload.unflagged:
        print(f"FAIL: {len(workload.unflagged)} NON-FLAGGED query "
              f"error(s): {workload.unflagged[:3]}", file=sys.stderr)
        ok = False
    metrics = standby.controller.metrics
    result.update({
        "ok": bool(ok),
        "queries": workload.total,
        "flaggedPartialResponses": workload.flagged,
        "unflaggedErrors": len(workload.unflagged),
        "rebalanceMoves": metrics.meter(
            ControllerMeter.REBALANCE_MOVES).count,
        "partitionTakeovers": metrics.meter(
            ControllerMeter.PARTITION_TAKEOVERS).count,
        "finalReplicationDeficit": replication_deficit(
            standby.controller.manager),
        "offlineRows": off_total,
        "realtimeKeys": len(latest),
    })
    print(json.dumps(result, indent=2))
    if ok:
        art = os.path.join(os.path.dirname(__file__), "..",
                           "SELFHEAL_r08.json")
        with open(art, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"PASS: self-healing gate green; artifact {art}")

    broker.stop()
    for srv in servers.values():
        try:
            srv.stop()
        except Exception:  # noqa: BLE001
            pass
    standby.stop()
    zk.stop()
    registry.unregister_stream_factory(FACTORY)
    shutil.rmtree(base, ignore_errors=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
