"""Rule modules — importing this package registers every rule."""
from pinot_tpu.analysis.rules import (api_compat, async_safety,
                                      concurrency, deep, dtype_drift,
                                      durability, host_sync, lock_order,
                                      metrics_contract, protocol_check,
                                      residency, retrace)

__all__ = ["api_compat", "async_safety", "concurrency", "deep",
           "dtype_drift", "durability", "host_sync", "lock_order",
           "metrics_contract", "protocol_check", "residency", "retrace"]
