"""Broker-level result cache for hybrid/realtime tables.

The server-side cache (server/result_cache.py) is CRC-exact but cannot
cover consuming segments — they have no CRC and mutate continuously.
For hybrid tables the honest bound is FRESHNESS, and the plumbing
already exists: ``minConsumingFreshnessTimeMs`` is the response field
that tells a client how stale its realtime rows may be. A cached
response younger than the query's freshness bound (the
``minConsumingFreshnessTimeMs`` query option, or the broker default)
is indistinguishable from a live answer UNDER THE CLIENT'S OWN
STALENESS CONTRACT — that is what makes serving it correct.

Only COMPLETE responses cache (no exceptions, not partial), and only
SMALL ones (``max_cells``): MB-scale selection payloads are poor cache
citizens (memory) and their deep copies taxed the reduce path of every
complete query. Bounded-size entries store a deep copy and hits hand
out another deep copy, so no query — and no embedding caller mutating
the response ``handle()`` returned — ever touches shared cache state.
"""
from __future__ import annotations

import copy
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from pinot_tpu.common.response import BrokerResponse


class BrokerResultCache:
    """Bounded LRU of (fingerprint → BrokerResponse, stored-at)."""

    def __init__(self, max_entries: int = 512,
                 max_cells: int = 50_000,
                 clock: Callable[[], float] = time.monotonic):
        self.max_entries = int(max_entries)
        self.max_cells = int(max_cells)
        self._clock = clock
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # bumped by clear(): a put whose captured generation is stale
        # (a view change invalidated the cache mid-query) is dropped —
        # same guard the server cache uses against the swap/put race
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def get(self, fingerprint: str,
            max_age_ms: float) -> Optional[BrokerResponse]:
        now = self._clock()
        with self._lock:
            entry = self._entries.get(fingerprint)
            if entry is None:
                self.misses += 1
                return None
            resp, stored_at = entry
            if (now - stored_at) * 1e3 > max_age_ms:
                # too stale for THIS query's bound; keep the entry —
                # a later query with a looser bound may still hit it
                self.misses += 1
                return None
            self._entries.move_to_end(fingerprint)
            self.hits += 1
        # deep copy OUTSIDE the lock: stored responses are only ever
        # replaced, never mutated in place, and copying a large
        # selection result under the lock would serialize the very
        # hit path that is the degradation valve under overload.
        # The stored minConsumingFreshnessTimeMs is an absolute
        # last-indexed timestamp: it already states the cached
        # data's true freshness, so it travels unchanged
        return copy.deepcopy(resp)

    def put(self, fingerprint: str, resp: BrokerResponse,
            gen: Optional[int] = None) -> None:
        """`gen`: the generation captured BEFORE the query executed
        (at probe time). A clear() that raced the in-flight query —
        an OFFLINE backfill's view change — bumps the generation, so
        the pre-backfill result is dropped instead of re-populating
        the cache with rows the backfill rewrote."""
        if resp.exceptions or resp.partial_response:
            return                     # only complete answers cache
        if _approx_cells(resp) > self.max_cells:
            return                     # large payloads never cache
        # deep copy outside the lock: the same object handle() hands
        # the embedding caller must never alias a cache entry (user
        # code mutating ITS response would poison every later hit).
        # The size cap above is what keeps this copy cheap — the
        # O(result size) tax on huge selections is gone because huge
        # selections no longer cache at all.
        stored = copy.deepcopy(resp)
        with self._lock:
            if gen is not None and gen != self._generation:
                return                 # lost the race with a clear()
            self._entries[fingerprint] = (stored, self._clock())
            self._entries.move_to_end(fingerprint)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._generation += 1
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses}


def _approx_cells(resp: BrokerResponse) -> int:
    """Result size in cells — the copy/memory cost driver."""
    n = 0
    if resp.selection_results is not None:
        n += len(resp.selection_results.results) * \
            max(1, len(resp.selection_results.columns))
    for agg in resp.aggregation_results or ():
        n += len(agg.group_by_result) \
            if agg.group_by_result is not None else 1
    return n
