"""ParticipantAgent: server-process side of the distributed state machine.

Parity: the Helix participant embedded in HelixServerStarter — the server
process announces itself as a live instance (ephemeral), watches ideal
states, drives its own state model (segment load/unload/consume), and
publishes current states for the controller's view composer
(controller/state_machine.py ViewComposer).  With this agent + a
RemotePropertyStore (controller/store_client.py), a server runs in its
own process connected to the controller only through the store — the
reference's ZK-mediated deployment shape.

Current states and the live-instance record are written ephemeral where
the store supports it, so a dying server's segments leave the external
view with its session (ZK ephemeral-node semantics).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from pinot_tpu.controller.state_machine import (CURRENT, IDEAL, LIVE,
                                                StateModel,
                                                apply_transitions)


class ParticipantAgent:
    def __init__(self, store, instance_id: str, model: StateModel,
                 tags: Optional[List[str]] = None,
                 endpoint: Optional[tuple] = None):
        """`endpoint`: (host, port) of this server's query service,
        published in the live-instance record so brokers can build their
        data-plane connections from the store (the reference encodes
        host/port in the Helix instance name)."""
        self.store = store
        self.instance_id = instance_id
        self.model = model
        self.tags = list(tags or ["DefaultTenant"])
        self.endpoint = endpoint
        self._lock = threading.Lock()
        self._watcher = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        rec = {"tags": self.tags}
        if self.endpoint is not None:
            rec["host"], rec["port"] = self.endpoint[0], self.endpoint[1]
        self._set(f"{LIVE}/{self.instance_id}", rec)
        with self._lock:
            self._watcher = self._on_ideal_change
            watcher = self._watcher
        self.store.watch(IDEAL + "/", watcher)
        self.reconcile_all()

    def stop(self) -> None:
        """Graceful departure (beyond the ephemeral-cleanup safety net)."""
        with self._lock:
            watcher, self._watcher = self._watcher, None
        if watcher is not None:
            self.store.unwatch(watcher)
        self.store.remove(f"{LIVE}/{self.instance_id}")
        for path in self.store.list_paths(
                f"{CURRENT}/{self.instance_id}/"):
            self.store.remove(path)

    # -- reconciliation ----------------------------------------------------
    def _on_ideal_change(self, path: str, record: Optional[dict]) -> None:
        table = path[len(IDEAL) + 1:]
        self.reconcile_table(table, (record or {}).get("segments", {}))

    def reconcile_all(self) -> None:
        for table in self.store.children(IDEAL):
            rec = self.store.get(f"{IDEAL}/{table}") or {}
            self.reconcile_table(table, rec.get("segments", {}))

    def reconcile_table(self, table: str,
                        ideal_segments: Dict[str, Dict[str, str]]) -> None:
        with self._lock:
            path = f"{CURRENT}/{self.instance_id}/{table}"
            current = (self.store.get(path) or {}).get("segments", {})
            wanted = {seg: states[self.instance_id]
                      for seg, states in ideal_segments.items()
                      if self.instance_id in states}
            if apply_transitions(self.model, table, self.instance_id,
                                 wanted, current):
                if current:
                    self._set(path, {"segments": current})
                else:
                    self.store.remove(path)

    def _set(self, path: str, record: dict) -> None:
        # both store implementations accept the flag; the in-process one
        # (no sessions) ignores it
        self.store.set(path, record, ephemeral=True)
