"""Per-segment plan maker + execution.

Parity: pinot-core/.../core/plan/maker/InstancePlanMakerImplV2.java — chooses
the per-segment execution strategy:
  - metadata-based COUNT with no filter (InstancePlanMakerImplV2.java:148)
  - dictionary-based MIN/MAX/MINMAXRANGE with no filter (:179-211)
  - inverted-index count fast path (BitmapBasedFilterOperator + count)
  - otherwise: one fused device kernel (filter+project+aggregate/group/select)
and FilterPlanNode.java:51 — converts the FilterQueryTree into a physical
filter, resolving each predicate against the column's dictionary host-side so
the device sees only integer compares / member-vector gathers.

The reference's `num.groups.limit` (100k, InstancePlanMakerImplV2.java:58)
becomes the static group-table bound; queries over it fall back to the host
executor (query/host_exec.py).
"""
from __future__ import annotations

import copy
import dataclasses
import os
import re as _re
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.request import (AggregationInfo, BrokerRequest,
                                      FilterOperator, FilterQueryTree)
from pinot_tpu.obs.profiler import count_path, profiled_device_get
from pinot_tpu.ops import kernels
from pinot_tpu.query.aggregation import AggregationFunction, make_functions
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.segment.loader import DataSource, ImmutableSegment

DEFAULT_NUM_GROUPS_LIMIT = 100_000     # parity: num.groups.limit
IN_LIST_MEMBER_THRESHOLD = 16          # small IN → broadcast compare, else
                                       # member-vector gather
MAX_SELECTION_K = 1 << 16


class GroupsLimitExceeded(Exception):
    pass


class UnsupportedOnDevice(Exception):
    """Raised when a query shape needs the host fallback executor."""


# ---------------------------------------------------------------------------
# Filter resolution: FilterQueryTree → (kernel spec, params)
# ---------------------------------------------------------------------------

MATCH_ALL = ("match_all",)
EMPTY = ("empty",)

# -- upsert validDocIds masking ---------------------------------------------
# A segment whose table runs primary-key upserts carries a ValidDocIds
# bitmap (realtime/upsert.py); superseded rows must be masked on EVERY
# result path. On device the mask rides as one more fused filter
# predicate over a pseudo-column lane ("$validDocIds.vdoc", a bool [P]
# runtime operand) so COUNT/SUM/GROUP BY/selection agree bit-for-bit
# with the host oracle without new kernel machinery.

VALID_DOC_COLUMN = "$validDocIds"
VALID_DOC_PRED = ("pred", "vdoc", VALID_DOC_COLUMN, "vdoc", None)


def upsert_mask_active(segment) -> bool:
    """True when the segment has superseded rows to mask. An upsert
    segment with zero invalidations plans mask-free (no lane upload);
    the first invalidation changes the static spec, which just compiles
    one more cached kernel variant."""
    vd = getattr(segment, "valid_doc_ids", None)
    return vd is not None and vd.num_invalid > 0


def has_valid_doc_mask(spec) -> bool:
    if spec == VALID_DOC_PRED:
        return True
    return spec is not None and spec[0] == "and" and \
        VALID_DOC_PRED in spec[1]


def with_valid_doc_mask(spec):
    """AND the validDocIds predicate into a resolved filter spec. The
    predicate consumes no params, so prepending it never perturbs the
    depth-first param order of the original tree."""
    if spec == EMPTY or has_valid_doc_mask(spec):
        return spec
    if spec is None or spec == MATCH_ALL:
        return VALID_DOC_PRED
    return ("and", (VALID_DOC_PRED, spec))


def resolve_filter(tree: Optional[FilterQueryTree], segment: ImmutableSegment
                   ) -> Tuple[tuple, List]:
    if tree is None:
        return MATCH_ALL, []
    params: List = []
    spec = _resolve(tree, segment, params)
    return spec, params


def _resolve(node: FilterQueryTree, segment: ImmutableSegment, params: List
             ) -> tuple:
    if node.operator in (FilterOperator.AND, FilterOperator.OR):
        is_and = node.operator == FilterOperator.AND
        children = []
        for c in node.children:
            sub_params: List = []
            spec = _resolve(c, segment, sub_params)
            if spec == EMPTY:
                if is_and:
                    return EMPTY
                continue
            if spec == MATCH_ALL:
                if not is_and:
                    return MATCH_ALL
                continue
            children.append((spec, sub_params))
        if not children:
            return MATCH_ALL if is_and else EMPTY
        if len(children) == 1:
            params.extend(children[0][1])
            return children[0][0]
        for _, p in children:
            params.extend(p)
        return ("and" if is_and else "or",
                tuple(spec for spec, _ in children))
    return _resolve_leaf(node, segment, params)


def _pred_over_values(node: FilterQueryTree, tv: np.ndarray) -> np.ndarray:
    """Apply a numeric predicate to an array of (transformed) values."""
    op = node.operator
    if op == FilterOperator.IS_NULL:
        return np.zeros(len(tv), dtype=bool)   # transforms never yield null
    if op == FilterOperator.IS_NOT_NULL:
        return np.ones(len(tv), dtype=bool)
    if op == FilterOperator.REGEXP_LIKE:
        pat = _re.compile(node.values[0])
        return np.array([bool(pat.search(str(v))) for v in tv])
    if op == FilterOperator.EQUALITY:
        return tv == float(node.values[0])
    if op == FilterOperator.NOT:
        return tv != float(node.values[0])
    if op == FilterOperator.IN:
        return np.isin(tv, [float(v) for v in node.values])
    if op == FilterOperator.NOT_IN:
        return ~np.isin(tv, [float(v) for v in node.values])
    if op == FilterOperator.RANGE:
        m = np.ones(len(tv), dtype=bool)
        if node.lower is not None:
            lo = float(node.lower)
            m &= (tv >= lo) if node.lower_inclusive else (tv > lo)
        if node.upper is not None:
            hi = float(node.upper)
            m &= (tv <= hi) if node.upper_inclusive else (tv < hi)
        return m
    raise UnsupportedOnDevice(f"expression filter operator {op}")


def _resolve_expr_leaf(node: FilterQueryTree, segment: ImmutableSegment,
                       params: List) -> tuple:
    """Expression filter → member vector over the transformed dictionary.

    TPU-first: the transform is evaluated once over the (cardinality-sized)
    dictionary value table host-side; the doc-scale work stays the plain
    member-gather kernel — the device never sees the expression. Parity:
    ExpressionFilterOperator.java:59 evaluates the transform per projected
    block instead (O(docs) work; here it is O(cardinality)).
    """
    expr = expr_mod.parse_expression(node.column)
    srcs = expr_mod.columns_of(expr)
    if len(srcs) != 1:
        raise UnsupportedOnDevice("multi-column expression filter")
    src = srcs[0]
    ds = segment.data_source(src)
    cm = ds.metadata
    if not (cm.has_dictionary and cm.single_value):
        raise UnsupportedOnDevice(
            f"expression over non-dictionary/MV column {src}")
    vals = np.asarray(ds.dictionary.values)
    tv = np.asarray(expr_mod.evaluate(expr, lambda c: vals),
                    dtype=np.float64)
    card = cm.cardinality
    card_pad = kernels.pow2_bucket(card + 1)
    member = np.zeros(card_pad, dtype=bool)
    member[:card] = _pred_over_values(node, tv)
    if not member.any():
        return EMPTY
    if member[:card].all():
        return MATCH_ALL
    params.append(member)
    return ("pred", "member", src, "sv", card_pad)


def _resolve_leaf(node: FilterQueryTree, segment: ImmutableSegment,
                  params: List) -> tuple:
    if expr_mod.is_expression(node.column):
        return _resolve_expr_leaf(node, segment, params)
    ds = segment.data_source(node.column)
    cm = ds.metadata
    if cm.data_type == DataType.VECTOR:
        # embeddings have no value order or equality semantics a WHERE
        # predicate could use; similarity is the VECTOR_SIMILARITY clause
        raise ValueError(
            f"column '{node.column}' is a VECTOR column — WHERE "
            "predicates over embeddings are not supported")
    op = node.operator

    if not cm.has_dictionary:
        return _resolve_raw_leaf(node, ds, params)

    source = "sv" if cm.single_value else "mv"
    dictionary = ds.dictionary
    card = dictionary.cardinality
    card_pad = kernels.pow2_bucket(card + 1)

    if op == FilterOperator.EQUALITY:
        i = dictionary.index_of(node.values[0])
        if i < 0:
            return EMPTY
        params.append(np.int32(i))
        return ("pred", "eq_id", node.column, source, None)

    if op == FilterOperator.NOT:
        i = dictionary.index_of(node.values[0])
        if i < 0:
            return MATCH_ALL
        if source == "mv":
            # see NOT_IN: member vector keeps padding entries non-matching
            member = np.zeros(card_pad, dtype=bool)
            member[:card] = True
            member[i] = False
            params.append(member)
            return ("pred", "member", node.column, source, card_pad)
        params.append(np.int32(i))
        return ("pred", "neq_id", node.column, source, None)

    if op in (FilterOperator.IN, FilterOperator.NOT_IN):
        ids = [dictionary.index_of(v) for v in node.values]
        ids = sorted({i for i in ids if i >= 0})
        negate = op == FilterOperator.NOT_IN
        if not ids:
            return MATCH_ALL if negate else EMPTY
        if negate and source == "mv":
            # negated MV predicates must go through a member vector: the
            # padded-id compare form would let padding entries (id == card)
            # satisfy the negation and match every doc
            member = np.zeros(card_pad, dtype=bool)
            member[:card] = True
            member[ids] = False
            params.append(member)
            return ("pred", "member", node.column, source, card_pad)
        if len(ids) <= IN_LIST_MEMBER_THRESHOLD:
            k = kernels.pow2_bucket(len(ids), floor=1)
            arr = np.full(k, -1, dtype=np.int32)
            arr[: len(ids)] = ids
            params.append(arr)
            return ("pred", "notin_ids" if negate else "in_ids",
                    node.column, source, k)
        member = np.zeros(card_pad, dtype=bool)
        member[ids] = True
        if negate:
            member = ~member
            member[card:] = False   # padding ids never match
        params.append(member)
        return ("pred", "member", node.column, source, card_pad)

    if op == FilterOperator.RANGE:
        lo, hi = dictionary.range_to_id_interval(
            node.lower, node.upper, node.lower_inclusive,
            node.upper_inclusive)
        if lo >= hi:
            return EMPTY
        if lo == 0 and hi >= card and source == "sv":
            return MATCH_ALL
        params.append(np.int32(lo))
        params.append(np.int32(hi))
        return ("pred", "range_ids", node.column, source, None)

    if op == FilterOperator.REGEXP_LIKE:
        # evaluate over the (small) dictionary host-side → member vector.
        # Parity: RegexpLikePredicateEvaluatorFactory uses Matcher.find()
        # semantics, i.e. pattern found anywhere in the value.
        pattern = _re.compile(node.values[0])
        member = np.zeros(card_pad, dtype=bool)
        for i in range(card):
            if pattern.search(str(dictionary.get(i))):
                member[i] = True
        if not member.any():
            return EMPTY
        params.append(member)
        return ("pred", "member", node.column, source, card_pad)

    if op == FilterOperator.IS_NULL:
        return EMPTY      # no null vector yet: nothing is null
    if op == FilterOperator.IS_NOT_NULL:
        return MATCH_ALL

    raise UnsupportedOnDevice(f"filter operator {op}")


def _resolve_raw_leaf(node: FilterQueryTree, ds: DataSource, params: List
                      ) -> tuple:
    dt = ds.metadata.data_type.np_dtype
    if dt.kind not in "iuf":
        # chunked raw string/bytes columns have no device lane; the host
        # executor evaluates their predicates on the decoded object array
        raise UnsupportedOnDevice(
            f"filter over non-numeric raw column {node.column}")
    op = node.operator
    col = node.column

    def cv(v):
        return dt.type(float(v)) if dt.kind == "f" else dt.type(int(str(v)))

    if op == FilterOperator.EQUALITY:
        params.append(cv(node.values[0]))
        return ("pred", "eq_raw", col, "raw", None)
    if op == FilterOperator.NOT:
        params.append(cv(node.values[0]))
        return ("pred", "neq_raw", col, "raw", None)
    if op in (FilterOperator.IN, FilterOperator.NOT_IN):
        vals = sorted({cv(v) for v in node.values})
        k = kernels.pow2_bucket(len(vals), floor=1)
        arr = np.full(k, vals[0], dtype=dt)
        arr[: len(vals)] = vals
        params.append(arr)
        return ("pred", "notin_raw" if op == FilterOperator.NOT_IN
                else "in_raw", col, "raw", k)
    if op == FilterOperator.RANGE:
        info = np.iinfo(dt) if dt.kind in "iu" else np.finfo(dt)
        lo = cv(node.lower) if node.lower is not None else dt.type(info.min)
        hi = cv(node.upper) if node.upper is not None else dt.type(info.max)
        lo_inc = node.lower_inclusive if node.lower is not None else True
        hi_inc = node.upper_inclusive if node.upper is not None else True
        params.append(lo)
        params.append(hi)
        return ("pred", "range_raw", col, "raw", (lo_inc, hi_inc))
    raise UnsupportedOnDevice(f"raw-column filter operator {op}")


# ---------------------------------------------------------------------------
# Join resolution (stage 2 of the multi-stage engine)
#
# The dim side arrives as a JoinContext (query/stages/join.py) — the
# exchanged, already-dim-filtered key/column arrays. The fact-side probe
# compiles to existing kernel primitives wherever possible:
# - dict-encoded fact key: the per-dictId translation (searchsorted of
#   the dictionary's values against the dim keys, O(cardinality) on
#   host) turns the join MATCH into a plain member-vector predicate and
#   each dim group key into a "jcode" gather table;
# - raw fact key: the dim (key, code) arrays ride as runtime operands
#   and the device builds the sorted probe itself ("join_raw"/"jraw" —
#   lax.sort is the build, searchsorted the probe).
# Either way the match predicate ANDs into the fused filter ahead of
# the upsert vdoc lane, so a dead upserted row can never join.
# ---------------------------------------------------------------------------


def _join_key_source(jctx, segment: ImmutableSegment):
    """→ ("sv"|"raw", DataSource) for the fact key column, with the
    integer-key contract enforced (typed StageCompileError)."""
    from pinot_tpu.query.stages.errors import StageCompileError
    if not segment.has_column(jctx.fact_key):
        raise StageCompileError(
            f"join key column '{jctx.fact_key}' does not exist on the "
            "fact table")
    ds = segment.data_source(jctx.fact_key)
    cm = ds.metadata
    if not cm.single_value or cm.data_type.np_dtype.kind not in "iu":
        raise StageCompileError(
            f"join keys must be single-value INTEGER columns; fact key "
            f"'{jctx.fact_key}' is {cm.data_type.name}"
            f"{'' if cm.single_value else ' (multi-value)'}")
    return ("sv" if cm.has_dictionary else "raw"), ds


def _resolve_join_pred(jctx, segment: ImmutableSegment):
    """(filter spec, params) for the join-match predicate."""
    if jctx.empty:
        return EMPTY, []
    source, ds = _join_key_source(jctx, segment)
    cm = ds.metadata
    if source == "sv":
        member = jctx.member_for(np.asarray(ds.dictionary.values))
        if not member.any():
            return EMPTY, []
        card_pad = kernels.pow2_bucket(cm.cardinality + 1)
        memb = np.zeros(card_pad, dtype=bool)
        memb[: cm.cardinality] = member
        return ("pred", "member", jctx.fact_key, "sv", card_pad), [memb]
    keys = jctx.padded_keys(cm.data_type.np_dtype)
    if keys is None:
        # no dim key is representable in the fact dtype — nothing can
        # match (the raw twin of the all-False member vector above)
        return EMPTY, []
    return ("pred", "join_raw", jctx.fact_key, "raw",
            len(keys)), [keys]


# ---------------------------------------------------------------------------
# Plan construction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SegmentPlan:
    segment: ImmutableSegment
    request: BrokerRequest
    # device kernel inputs (None when fast_path_result is set)
    filter_spec: Optional[tuple] = None
    params: Optional[List] = None
    agg_specs: Tuple = ()
    group_spec: Optional[tuple] = None
    select_spec: Optional[tuple] = None
    needed_cols: Tuple[Tuple[str, str], ...] = ()   # (column, lane-kind)
    functions: List[AggregationFunction] = dataclasses.field(
        default_factory=list)
    group_strides: Tuple[int, ...] = ()
    # per group column: None (decode via dictionary) or a transformed value
    # table aligned with the source column's dictIds (expression group-by)
    group_value_tables: Tuple = ()
    select_display: Optional[int] = None   # display cols (rest: order-only)
    fast_path_result: Optional[IntermediateResultsBlock] = None

    def execute(self) -> IntermediateResultsBlock:
        from pinot_tpu.query import execution
        return execution.execute_segment_plan(self)


def batch_signature(plan: SegmentPlan) -> Optional[tuple]:
    """The compiled-spec identity under which plans for ONE segment may
    share a batched dispatch, or None when this plan cannot batch.

    This is the ground truth behind the advisory plan_shape_key: two
    plans with equal signatures compile (get_batched_segment_kernel)
    to one executable and differ only in runtime param values. Fast
    paths never reach the device; group specs are excluded because
    drive_group_execution's scout phases are value-dependent per query.
    """
    if plan.fast_path_result is not None or plan.group_spec is not None:
        return None
    return (plan.segment.padded_docs, plan.filter_spec,
            tuple(plan.agg_specs or ()), plan.select_spec,
            tuple(plan.needed_cols))


def preprocess_request(segments, request):
    """Parity: core/plan/maker/BrokerRequestPreProcessor.preProcess —
    rewrite FASTHLL(col) to the derived serialized-HLL column recorded in
    segment metadata (consistency-checked across the segment set).

    Returns the request to plan against: the ORIGINAL when no rewrite
    applies, otherwise a shallow COPY with fresh AggregationInfo entries.
    The shared BrokerRequest is never mutated — with per-segment
    execution parallel (and hybrid sub-requests sharing structure), an
    in-place rewrite would be visible mid-plan to concurrently executing
    in-process servers.
    """
    if not request.aggregations:
        return request
    rewrites: Dict[int, str] = {}
    for idx, agg in enumerate(request.aggregations):
        if agg.function_name.upper() != "FASTHLL":
            continue
        derived = None
        first_name = None
        for i, seg in enumerate(segments):
            md = getattr(seg, "metadata", None)
            d = md.get_derived_column(agg.column, "HLL") \
                if hasattr(md, "get_derived_column") else None
            if i == 0:
                derived, first_name = d, getattr(seg, "segment_name", "?")
            elif d != derived:
                raise RuntimeError(
                    "Found inconsistency HLL derived column name. In "
                    f"segment {first_name}: {derived}; in segment "
                    f"{getattr(seg, 'segment_name', '?')}: {d}")
        if derived is not None:
            rewrites[idx] = derived
    if not rewrites:
        return request
    out = copy.copy(request)
    out.aggregations = [
        AggregationInfo(a.function_name, rewrites[i]) if i in rewrites
        else a
        for i, a in enumerate(request.aggregations)]
    return out


class InstancePlanMaker:
    """Builds a SegmentPlan per segment for a BrokerRequest.

    Parity: InstancePlanMakerImplV2.makeInnerSegmentPlan
    (InstancePlanMakerImplV2.java:97).
    """

    def __init__(self, num_groups_limit: int = DEFAULT_NUM_GROUPS_LIMIT,
                 allow_group_compaction: bool = True):
        self.num_groups_limit = num_groups_limit
        self.allow_group_compaction = allow_group_compaction

    def make_segment_plan(self, segment: ImmutableSegment,
                          request: BrokerRequest) -> SegmentPlan:
        if getattr(segment, "is_mutable", False):
            # consuming segments have arrival-order (unsorted) dictionaries,
            # which breaks the sorted-id-interval device predicates — they
            # take the host executor until committed
            raise UnsupportedOnDevice("mutable segment")
        plan = SegmentPlan(segment=segment, request=request)
        if request.is_aggregation:
            plan.functions = make_functions(request.aggregations)

        # stage-2 join context (query/stages/join.py attaches it to the
        # server-local request copy): the probe fuses into the filter,
        # so every whole-segment shortcut below is off — they would
        # count unjoined rows
        jctx = getattr(request, "_join_ctx", None)

        # upsert masking disables every whole-segment shortcut below:
        # metadata counts, star-tree cubes and inverted-index counts all
        # include superseded rows
        masked = upsert_mask_active(segment)
        no_fast = masked or jctx is not None

        # fast path: no filter, metadata/dictionary-answerable aggregations
        if request.is_aggregation and not request.is_group_by and \
                request.filter is None and not no_fast and \
                self._try_metadata_fast_path(plan, segment, request):
            return plan

        # star-tree: a covering pre-aggregated cube answers the query in
        # O(groups) host work (core/startree/ parity; startree/executor.py).
        # This hook serves the sharded path (which plans directly); the
        # sequential path already checked in ServerQueryExecutor.
        if request.is_aggregation and not request.is_selection and \
                not no_fast and \
                getattr(segment, "star_trees", None):
            from pinot_tpu.startree.executor import try_star_tree_execute
            blk = try_star_tree_execute(segment, request)
            if blk is not None:
                plan.fast_path_result = blk
                return plan

        filter_spec, params = resolve_filter(request.filter, segment)

        if jctx is not None and filter_spec != EMPTY:
            # the join-match predicate ANDs in FIRST (its params precede
            # the original tree's in depth-first order)
            jspec, jparams = _resolve_join_pred(jctx, segment)
            if jspec == EMPTY:
                filter_spec = EMPTY
            elif jspec != MATCH_ALL:
                params = jparams + params
                filter_spec = jspec if filter_spec == MATCH_ALL else \
                    ("and", (jspec, filter_spec))

        if filter_spec == EMPTY:
            plan.fast_path_result = _empty_block(plan, segment)
            return plan

        # fast path: COUNT(*) on a pure match-all filter
        if filter_spec == MATCH_ALL and request.is_aggregation and \
                not no_fast and not request.is_group_by and \
                all(f.info.base == "COUNT" and not f.info.is_mv
                    for f in plan.functions):
            blk = IntermediateResultsBlock(
                agg_intermediates=[segment.num_docs for _ in plan.functions])
            _fill_stats(blk, segment, segment.num_docs, 0, 0)
            plan.fast_path_result = blk
            return plan

        # fast path: COUNT(*) + single EQ/IN leaf answered by inverted index
        if request.is_aggregation and not no_fast and \
                not request.is_group_by and \
                all(f.info.base == "COUNT" and not f.info.is_mv
                    for f in plan.functions):
            cnt = self._try_inverted_count(segment, filter_spec, params)
            if cnt is not None:
                blk = IntermediateResultsBlock(
                    agg_intermediates=[cnt for _ in plan.functions])
                _fill_stats(blk, segment, cnt, 0, 0)
                plan.fast_path_result = blk
                return plan

        if masked:
            filter_spec = with_valid_doc_mask(filter_spec)
        plan.filter_spec = filter_spec
        plan.params = params

        needed: Dict[Tuple[str, str], None] = {}
        _collect_filter_cols(filter_spec, needed)

        if request.is_group_by:
            self._plan_group_by(plan, segment, request, needed)
        elif request.is_aggregation:
            plan.agg_specs = tuple(
                _agg_device_spec(f, segment, needed) for f in plan.functions)
        if request.vector is not None:
            self._plan_vector(plan, segment, request, needed)
        elif request.is_selection:
            self._plan_selection(plan, segment, request, needed)

        plan.needed_cols = tuple(needed.keys())
        return plan

    # -- helpers -----------------------------------------------------------
    def _try_metadata_fast_path(self, plan: SegmentPlan,
                                segment: ImmutableSegment,
                                request: BrokerRequest) -> bool:
        inters: List = []
        for f in plan.functions:
            base = f.info.base
            if base == "COUNT" and not f.info.is_mv:
                inters.append(segment.num_docs)
                continue
            if base in ("MIN", "MAX", "MINMAXRANGE") and \
                    segment.has_column(f.column):
                cm = segment.data_source(f.column).metadata
                if cm.has_dictionary and cm.single_value and \
                        cm.data_type.is_numeric:
                    mn, mx = float(cm.min_value), float(cm.max_value)
                    inters.append(mn if base == "MIN" else
                                  mx if base == "MAX" else (mn, mx))
                    continue
            return False
        blk = IntermediateResultsBlock(agg_intermediates=inters)
        _fill_stats(blk, segment, segment.num_docs, 0, 0)
        plan.fast_path_result = blk
        return True

    def _try_inverted_count(self, segment: ImmutableSegment, spec: tuple,
                            params: List) -> Optional[int]:
        if spec[0] != "pred":
            return None
        _, kind, col, source, extra = spec
        if source != "sv":
            return None
        ds = segment.data_source(col)
        if ds.inverted_index is not None:
            if kind == "eq_id":
                return ds.inverted_index.count(int(params[0]))
            if kind == "in_ids":
                ids = [int(i) for i in np.asarray(params[0]) if i >= 0]
                return sum(ds.inverted_index.count(i) for i in ids)
            if kind == "range_ids":
                return ds.inverted_index.count_range(int(params[0]),
                                                     int(params[1]))
        if ds.sorted_ranges is not None:
            r = ds.sorted_ranges
            if kind == "eq_id":
                s, e = r[int(params[0])]
                return int(e - s)
            if kind == "range_ids":
                lo, hi = int(params[0]), int(params[1])
                return int(r[lo:hi, 1].sum() - r[lo:hi, 0].sum())
        return None

    def _plan_group_by(self, plan: SegmentPlan, segment: ImmutableSegment,
                       request: BrokerRequest, needed: Dict) -> None:
        gcols = []
        value_tables = []
        cards = []
        jctx = getattr(request, "_join_ctx", None)
        for c in request.group_by.columns:
            if jctx is not None and request.join is not None and \
                    request.join.qualifies(c):
                # dim-side group key: the fact key lane group-codes
                # through the join translation (jcode gather table for
                # dict keys; device-probed jraw for raw keys); decode
                # goes through the dim value table like an expression key
                dcol = request.join.unqualify(c)
                codes, uniq = jctx.group_coding(dcol)
                source, ds = _join_key_source(jctx, segment)
                n = len(uniq)
                if source == "sv":
                    cm = ds.metadata
                    card_pad = kernels.pow2_bucket(cm.cardinality + 1)
                    plan.params.append(jctx.code_table_for(
                        np.asarray(ds.dictionary.values), dcol, card_pad))
                    gcols.append((jctx.fact_key, "jcode", 0, n))
                    needed[(jctx.fact_key, "ids")] = None
                else:
                    keys_p, codes_p = jctx.padded_key_codes(
                        dcol, ds.metadata.data_type.np_dtype)
                    plan.params.append(keys_p)
                    plan.params.append(codes_p)
                    gcols.append((jctx.fact_key, "jraw", 0, n))
                    needed[(jctx.fact_key, "raw")] = None
                value_tables.append(uniq)
                cards.append(n)
                continue
            if expr_mod.is_expression(c):
                # expression group key: group in the SOURCE column's id
                # domain on device; decode through the transformed value
                # table host-side (collapsing collisions there) — the
                # kernel is identical to a plain group-by
                expr = expr_mod.parse_expression(c)
                srcs = expr_mod.columns_of(expr)
                if len(srcs) != 1:
                    raise UnsupportedOnDevice(
                        "multi-column expression group key")
                src = srcs[0]
                ds = segment.data_source(src)
                vi = expr_mod.valuein_parts(expr)   # raises on malformed
                if vi is not None:
                    # valuein(mvcol, lits...): an MV group key restricted
                    # to the allowed value set — the kernel's MV row
                    # expansion masks disallowed entries via a member
                    # vector riding as a RUNTIME operand (one executable
                    # per template, any literal set)
                    cm = ds.metadata
                    if not cm.has_dictionary or cm.single_value:
                        raise UnsupportedOnDevice(
                            "valuein group key needs a dict MV column")
                    lits = vi[1]
                    card_pad = kernels.pow2_bucket(cm.cardinality + 1)
                    member = np.zeros(card_pad, dtype=bool)
                    ids = ds.dictionary.index_of_many(lits)
                    member[ids[ids >= 0]] = True
                    plan.params.append(member)
                    gcols.append((src, "mvin", 0, cm.cardinality))
                    value_tables.append(None)
                    cards.append(cm.cardinality)
                    needed[(src, "mv")] = None
                    continue
                if not ds.metadata.has_dictionary or \
                        not ds.metadata.single_value:
                    raise UnsupportedOnDevice(
                        f"expression group key over non-dict/MV column {src}")
                vals = np.asarray(ds.dictionary.values)
                tv = np.asarray(expr_mod.evaluate(expr, lambda _: vals))
                gcols.append((src, "ids", 0, ds.metadata.cardinality))
                value_tables.append(tv)
                cards.append(ds.metadata.cardinality)
                needed[(src, "ids")] = None
                continue
            ds = segment.data_source(c)
            cm = ds.metadata
            if cm.has_dictionary and cm.single_value:
                gcols.append((c, "ids", 0, cm.cardinality))
                value_tables.append(None)
                cards.append(cm.cardinality)
                needed[(c, "ids")] = None
                continue
            if cm.has_dictionary and not cm.single_value:
                # MV group key: the kernel expands the row space to one
                # row per (doc, entry) cross-combination before the
                # group machinery (kernels._expand_mv_group — reference
                # parity: DefaultGroupByExecutor.aggregateGroupByMV)
                gcols.append((c, "mvids", 0, cm.cardinality))
                value_tables.append(None)
                cards.append(cm.cardinality)
                needed[(c, "mv")] = None
                continue
            if not cm.has_dictionary and cm.single_value and \
                    cm.data_type.np_dtype.kind in "iu" and \
                    cm.min_value is not None and \
                    -2**31 <= int(cm.min_value) and int(cm.max_value) < 2**31:
                # no-dictionary integer group key: bin by (value - min) —
                # metadata min/max bound the id range (int32-safe: device
                # lanes are int32 when x64 is off); the groups-limit check
                # below rejects ranges too wide for the group table
                span = int(cm.max_value) - int(cm.min_value) + 1
                gcols.append((c, "rawoff", int(cm.min_value), span))
                value_tables.append(None)
                cards.append(span)
                needed[(c, "raw")] = None
                continue
            raise UnsupportedOnDevice(
                f"group-by on non-dictionary/MV column {c}")
        plan.group_value_tables = tuple(value_tables)
        g = int(np.prod(cards, dtype=np.int64))
        # per-query override (parity: the reference's numGroupsLimit query
        # option, InstancePlanMakerImplV2.java:58 + QueryOptionKey)
        limit = self.num_groups_limit
        opt = request.query_options.options.get("numGroupsLimit")
        if opt is not None:
            limit = int(opt)
        if g > limit:
            raise GroupsLimitExceeded(
                f"{g} potential groups > limit {limit}")
        strides = mixed_radix_strides(cards)
        g_pad = kernels.pow2_bucket(g)
        # sort-compaction for filtered group-bys (see kernels.py): start at
        # ~1.5% of the segment; the executor escalates via the overflow flag
        kmax = 0
        if self.allow_group_compaction and plan.filter_spec is not None \
                and plan.filter_spec != MATCH_ALL:
            kmax = initial_group_kmax(segment.padded_docs)
        agg_specs = tuple(
            _agg_device_spec(f, segment, needed, for_group=True, g_pad=g_pad,
                             compact=bool(kmax))
            for f in plan.functions)
        plan.group_spec = (tuple(gcols), strides, g_pad, agg_specs, kmax)
        plan.group_strides = strides

    def _plan_vector(self, plan: SegmentPlan, segment: ImmutableSegment,
                     request: BrokerRequest, needed: Dict) -> None:
        """Ranked vector selection: filtered batched top-k over the
        packed embedding block. The WHERE filter (and the upsert vdoc
        lane) is already fused into plan.filter_spec, so predicate
        pruning narrows the candidate mask BEFORE scores rank — a dead
        upserted row can never reach the top-k."""
        v = request.vector
        ds = segment.data_source(v.column)
        cm = ds.metadata
        if cm.data_type != DataType.VECTOR:
            raise ValueError(
                f"VECTOR_SIMILARITY over non-VECTOR column '{v.column}'")
        dim = cm.vector_dimension
        q_raw = np.asarray(v.query, dtype=np.float32)
        if q_raw.shape != (dim,):
            raise ValueError(
                f"query vector has {q_raw.shape[0] if q_raw.ndim == 1 else '?'}"
                f" dimensions; column '{v.column}' stores {dim}")
        if v.k <= 0:
            raise ValueError(f"VECTOR_SIMILARITY k must be positive, "
                             f"got {v.k}")
        metric = v.metric.lower()
        if metric == "mips":
            metric = "dot"
        if metric not in ("cosine", "dot"):
            raise ValueError(f"unknown similarity metric '{v.metric}' "
                             "(COSINE | DOT | MIPS)")
        gather = []
        for c in request.selection.columns if request.selection else []:
            cds = segment.data_source(c)
            ccm = cds.metadata
            if ccm.data_type == DataType.VECTOR:
                raise UnsupportedOnDevice(
                    f"selection of VECTOR column {c} (host path)")
            if not ccm.has_dictionary:
                if ccm.data_type.np_dtype.kind not in "iuf":
                    raise UnsupportedOnDevice(
                        f"selection over non-numeric raw column {c}")
                gather.append((c, "raw"))
                needed[(c, "raw")] = None
            elif ccm.single_value:
                gather.append((c, "sv"))
                needed[(c, "ids")] = None
            else:
                gather.append((c, "mv"))
                needed[(c, "mv")] = None
        dim_pad = kernels.pow2_bucket(max(dim, 1), floor=1)
        q = np.zeros(dim_pad, np.float32)
        q[:dim] = q_raw
        q_norm = np_vec_tree_norm(q)
        if metric == "cosine" and not q_norm > 0:
            raise ValueError("COSINE similarity needs a non-zero, finite "
                             "query vector")
        nprobe = int(getattr(v, "nprobe", 0) or 0)
        if nprobe > 0:
            cents = getattr(ds, "ivf_centroids", None)
            if cents is not None and \
                    getattr(ds, "ivf_assignments", None) is not None:
                from pinot_tpu.index import ivf as ivf_mod
                # clamp so lax.top_k never exceeds the padded codebook lane
                nprobe_eff = min(nprobe,
                                 ivf_mod.pad_centroids(cents.shape[0]))
                pred = ("pred", "ivf_probe", v.column, "ivf",
                        (nprobe_eff, metric))
                plan.filter_spec = pred if plan.filter_spec == MATCH_ALL \
                    else ("and", (pred, plan.filter_spec))
                # probe operands precede all other filter params: the pred
                # is the first AND child in depth-first evaluation order
                plan.params = [q, np.float32(q_norm)] + plan.params
                for lane in ("ivfa", "ivfc", "ivfv"):
                    needed[(v.column, lane)] = None
                count_path("ivfProbe")
            else:
                # nprobe requested but this segment has no built index:
                # exact scan keeps results correct (ANN is best-effort)
                count_path("ivfExactFallback")
        k = min(kernels.pow2_bucket(v.k, floor=1), segment.padded_docs)
        plan.select_spec = ("vector", k, ((v.column, metric, dim_pad),),
                            tuple(gather))
        plan.select_display = None
        needed[(v.column, "vec")] = None
        # runtime operands AFTER the filter params (depth-first order)
        plan.params.append(q)
        plan.params.append(np.float32(q_norm))

    def _plan_selection(self, plan: SegmentPlan, segment: ImmutableSegment,
                        request: BrokerRequest, needed: Dict) -> None:
        sel = request.selection
        cols = selection_columns(segment, request)
        plan.select_display = len(cols)
        # ORDER BY columns outside the display list ride along at the end
        # of each row so cross-segment merges can re-sort; the reducer
        # trims them via selection_display_cols
        extras = [ob.column for ob in (sel.order_by or [])
                  if ob.column not in cols]
        gather = []
        for c in cols + extras:
            ds = segment.data_source(c)
            if ds.metadata.data_type == DataType.VECTOR:
                # embedding rows have no device gather lane; the host
                # executor decodes them as per-row float lists
                raise UnsupportedOnDevice(
                    f"selection over VECTOR column {c}")
            if not ds.metadata.has_dictionary:
                if ds.metadata.data_type.np_dtype.kind not in "iuf":
                    # chunked raw string/bytes: object arrays have no
                    # device lane — the whole selection goes host-side
                    raise UnsupportedOnDevice(
                        f"selection over non-numeric raw column {c}")
                gather.append((c, "raw"))
                needed[(c, "raw")] = None
            elif ds.metadata.single_value:
                gather.append((c, "sv"))
                needed[(c, "ids")] = None
            else:
                gather.append((c, "mv"))
                needed[(c, "mv")] = None
        k = sel.offset + sel.size
        if k > MAX_SELECTION_K:
            raise UnsupportedOnDevice(f"selection k={k} too large")
        k = min(kernels.pow2_bucket(k, floor=1), segment.padded_docs)
        if not sel.order_by:
            plan.select_spec = ("limit", k, (), tuple(gather))
            return
        order = []
        packed_bits = 0
        all_dict = True
        single_lane_raw = False
        for ob in sel.order_by:
            ds = segment.data_source(ob.column)
            cm = ds.metadata
            if cm.has_dictionary and cm.single_value:
                # sorted dictionary ⇒ id order == value order: dictIds are
                # exact order keys for ANY dict column (incl. float/string)
                card_pad = cm.cardinality + 1
                packed_bits += int(np.ceil(np.log2(max(card_pad, 2))))
                order.append((ob.column, ob.ascending, card_pad, "sv"))
                needed[(ob.column, "ids")] = None
                continue
            if not cm.has_dictionary and cm.single_value and \
                    cm.data_type.is_numeric:
                all_dict = False
                # the device lane keeps int32/f32 width; wider types only
                # exist device-side under x64 (CPU) where hi/lo keys apply
                single_lane_raw = cm.data_type.np_dtype.itemsize <= 4
                order.append((ob.column, ob.ascending, 0, "raw"))
                needed[(ob.column, "raw")] = None
                continue
            raise UnsupportedOnDevice(
                f"order-by on MV/non-numeric-raw column {ob.column}")
        if all_dict and packed_bits <= 30:
            # fast path: one packed int32 key + top_k
            plan.select_spec = ("order", k, tuple(order), tuple(gather))
        elif len(order) == 1 and single_lane_raw:
            # fast path: single raw int32/f32 key, monotone map + top_k
            plan.select_spec = ("ordertk", k, tuple(order), tuple(gather))
        else:
            # general path: per-column int32 key lanes, full device sort —
            # covers >31-bit dict packings, raw columns, and mixes
            plan.select_spec = ("ordermk", k, tuple(order), tuple(gather))


def np_vec_tree_norm(q: np.ndarray) -> np.float32:
    """f32 balanced-tree norm of a (pow2-padded) query vector.

    Delegates to kernels.vec_tree_sum on a NUMPY operand (the helper is
    pure slicing + adds, backend-agnostic), so the engine has exactly
    ONE tree implementation: the q_norm operand the device divides by
    is the same contract the kernel applies to row norms. The host
    oracle (host_exec) keeps its independent twin by policy."""
    qf = np.asarray(q, np.float32)
    return np.float32(np.sqrt(kernels.vec_tree_sum(qf * qf)))


def mixed_radix_strides(cards) -> tuple:
    """Strides for the mixed-radix group key (last column fastest)."""
    strides = []
    acc = 1
    for c in reversed(list(cards)):
        strides.append(acc)
        acc *= c
    return tuple(reversed(strides))


def initial_group_kmax(padded: int) -> int:
    # ~0.8% selectivity tolerance per 8192-row block (r=64) — the MXU
    # block-compaction makes a rerun cheap, so start small and escalate
    return min(kernels.pow2_bucket(max(padded // 128, 1024)), padded)


def set_group_kmax(group_spec: tuple, padded: int) -> tuple:
    """Re-derive kmax for a different run-time padded size (a plan built
    against a small template segment but executed over bigger lanes)."""
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    if not kmax:
        return group_spec
    return (gcols, strides, g_pad, agg_specs, initial_group_kmax(padded))


def escalate_group_kmax(group_spec: tuple, padded: int):
    """Next rung of the compaction ladder; None when already at full size."""
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    if not kmax or kmax >= padded:
        return None
    nk = min(kernels.pow2_bucket(kmax * 4), padded)
    return (gcols, strides, g_pad, agg_specs, nk)


def run_with_group_escalation(run, group_spec, padded: int):
    """run(group_spec) → device outs; re-runs up the kmax ladder while
    the compacted group kernel reports overflow. Returns the HOST outs
    and the final spec — all of a dispatch's outputs come over in ONE
    explicit jax.device_get (per-scalar pulls like the old
    `int(np.asarray(outs[...]))` overflow probe stall the pipeline once
    per output; see docs/ANALYSIS.md host-sync)."""
    outs = profiled_device_get(run(group_spec))
    while group_spec is not None and int(outs.get("group.overflow", 0)) > 0:
        group_spec = escalate_group_kmax(group_spec, padded)
        assert group_spec is not None, "overflow at full kmax is impossible"
        outs = profiled_device_get(run(group_spec))
    return outs, group_spec


RANK_HIST_CARD_LIMIT = int(os.environ.get(
    "PINOT_TPU_RANK_HIST_CARD", "512"))    # hist scout + rank remap only
#                              when every group dim's card_pad fits this
#                              budget: both the scout histogram and the
#                              kernel's one-hot rank contraction are
#                              O(rows * card_pad). 0 disables the rung.
DENSE_RANK_HIST_CARD = int(os.environ.get(
    "PINOT_TPU_DENSE_RANK_HIST_CARD", "128"))  # within the DENSE regime the
#                              hist rung fires only when every dim's hist
#                              is VPU-cheap (card_pad <= 128 takes the
#                              fused compare+reduce histogram — ~10ms-class
#                              at 100M rows, vs ~230ms for a 1024-bin
#                              matmul histogram)
DENSE_RANK_HIST_G = int(os.environ.get(
    "PINOT_TPU_DENSE_RANK_HIST_G", "2048"))    # ...and the span key space
#                              exceeds this: below it the lane-concat
#                              dense kernel is already a single MXU pass
#                              (passes = ceil(n_lanes * g/128 / 128)), so
#                              shrinking g buys nothing


def adaptive_phase_a_specs(group_spec) -> Optional[tuple]:
    """Scout agg specs (masked MIN+MAX of each group column's dictIds)
    for the adaptive two-phase group-by, or None when the plan isn't
    eligible (no filter to narrow the key space, or non-dictionary
    keys). Min/max are streaming-rate tree reductions — the scout costs
    about one filter evaluation. (The HISTOGRAM scout for the densifying
    rank remap is a separate, conditional second rung —
    adaptive_hist_specs — because a wide-card histogram at full row
    scale costs ~5x the min/max scout; measured 229ms vs ~10ms for the
    1024-bin p_brand1 hist at 100M rows on v5e.)"""
    if group_spec is None or not group_spec[4]:
        return None
    specs = []
    for (c, gkind, _off, card) in group_spec[0]:
        if gkind != "ids":
            return None
        card_pad = kernels.pow2_bucket(card + 1)
        specs.append(("min", c, "sv", ("ids", card_pad)))
        specs.append(("max", c, "sv", ("ids", card_pad)))
    return tuple(specs)


def adaptive_hist_specs(group_spec, bounds) -> Optional[tuple]:
    """Conditional second scout rung: matched-id histograms, from which
    the host derives each dim's exact PRESENT id set for the densifying
    rank remap (parity intent: DictionaryBasedGroupKeyGenerator's
    map-based generators serve exactly this sparse-key regime — e.g.
    SSB q3.1's 'the 5 Asian nations in a 25-nation sorted dictionary').

    The rung dispatches in two regimes:
    - RANKED ESCAPE (span space > DENSE_G_LIMIT, dims fit
      RANK_HIST_CARD_LIMIT): densifying is the one layout change the
      offset spans can't buy — escaping the ranked sort layout.
    - DENSE SHRINK (span space > DENSE_RANK_HIST_G, every dim's
      card_pad <= DENSE_RANK_HIST_CARD): the lane-concat int8 dense
      kernel's cost scales with ceil(n_lanes * g/128 / 128) MXU
      passes, so collapsing e.g. q3.1's 32*32*8 offset-span space to
      the 8*8*8 present space (the 5 Asian nations scattered in a
      25-nation sorted dictionary) drops 3 row-stream passes to 1;
      the <=128-bin histograms are fused compare+reduce (~10ms-class
      at 100M rows), well under the pass saved. (The round-2 per-lane
      kernel was g-independent — 394ms at g=8192 vs 398ms at g=512 —
      which is why this regime was previously gated off.)
    Returns hist agg specs or None."""
    if not RANK_HIST_CARD_LIMIT:
        return None
    spans, cards = [], []
    for (c, _gkind, _off, card), (lo, hi) in zip(group_spec[0], bounds):
        card_pad = kernels.pow2_bucket(card + 1)
        if card_pad > RANK_HIST_CARD_LIMIT:
            return None
        cards.append(card_pad)
        spans.append(kernels.pow2_bucket(max(hi - lo + 1, 1), floor=1))
    g_span = int(np.prod(spans, dtype=np.int64))
    if kernels.pow2_bucket(g_span) <= kernels.DENSE_G_LIMIT:
        if not DENSE_RANK_HIST_CARD or g_span <= DENSE_RANK_HIST_G or \
                any(cp > DENSE_RANK_HIST_CARD for cp in cards):
            return None
    return tuple(("hist", c, "sv",
                  ("hist", kernels.pow2_bucket(card + 1)))
                 for (c, _gkind, _off, card) in group_spec[0])


def _adaptive_kmax(matched: int, padded: int, total_docs: int,
                   g_pad: int) -> int:
    """Compaction capacity from measured selectivity (per-2048-row-block
    Poisson mean plus tail headroom). NOTE: r (and hence kmax) is
    pow2-bucketed from the phase-A matched count, so literal stability
    holds only within a selectivity bucket — literals of the same
    template whose match rates land in different pow2 buckets (or cross
    the dense-flip threshold) still compile fresh variants."""
    t = max(padded // kernels.CBLOCK, 1)
    mu = matched * kernels.CBLOCK / max(total_docs, 1)
    r = kernels.pow2_bucket(max(16, int(2 * mu + 8)))
    if r > 128 and g_pad <= kernels.DENSE_G_LIMIT:
        # barely-selective filter: the block-compaction einsum degrades
        # past r=128 while the dense path's VMEM-tiled one-hot scan
        # keeps a flat per-element rate — measured crossover on v5e
        # (compact r<=128 beats dense g=512; compact r=256 loses)
        return 0
    return min(t * r, padded)


def adaptive_phase_b_spec(group_spec, scout, matched: int, padded: int,
                          total_docs: int):
    """Derive the remapped group spec from the phase-A scout.

    `scout` = per-gcol ("bounds", lo, hi) — matched dictId range for the
    OFFSET remap — or ("present", ids) — exact matched id set for the
    DENSIFYING RANK remap, used when its pow2 bucket is strictly smaller
    than the span's (scattered actives, e.g. the five Asian nations in a
    25-nation sorted dictionary, make spans 4-8x wider than the active
    set; parity intent: DictionaryBasedGroupKeyGenerator's map-based
    generators handle exactly this sparse-key regime).  Offsets and rank
    vectors are RUNTIME operands — one compiled executable serves every
    literal of the same query template (spans/present-counts bucket to
    the same widths).
    Returns (kernel_spec, finish_spec, extra_params, empty): the kernel
    spec carries placeholder remaps (static, hashable jit key); the
    finish spec carries the real offsets / present-id arrays for
    host-side group decode. The compaction capacity kmax is sized from
    the scout's matched count (per-2048-row-block Poisson mean plus tail
    headroom; the kernel's overflow flag still escalates on skew).
    """
    gcols, _strides, _g_pad, agg_specs, _kmax = group_spec
    dims = []                    # (span, n_rank | None, payload)
    for c, dim in zip(gcols, scout):
        if dim[0] == "present":
            present = dim[1]
            if len(present) == 0:
                return None, None, (), True
            span = kernels.pow2_bucket(
                int(present[-1]) - int(present[0]) + 1, floor=1)
            n = kernels.pow2_bucket(len(present), floor=1)
            dims.append((span, n if n < span else None, present))
        else:
            lo, hi = dim[1], dim[2]
            if hi < lo:
                return None, None, (), True
            span = kernels.pow2_bucket(hi - lo + 1, floor=1)
            dims.append((span, None, (lo, hi)))
    # The rank remap's one-hot contraction is O(rows); "present" scouts
    # only exist when drive_group_execution judged the hist rung worth
    # its cost (ranked-layout escape), so here any pow2 shrink of the
    # key space takes the rank remap.
    g_span = int(np.prod([d[0] for d in dims], dtype=np.int64))
    g_rank = int(np.prod([d[1] if d[1] is not None else d[0]
                          for d in dims], dtype=np.int64))
    use_rank = kernels.pow2_bucket(g_rank) < kernels.pow2_bucket(g_span)
    kernel_gcols, finish_gcols, spans, extra = [], [], [], []
    for c, (span, n, payload) in zip(gcols, dims):
        card_pad = kernels.pow2_bucket(c[3] + 1)
        if use_rank and n is not None:
            present = payload
            rank = np.zeros(card_pad, np.int32)
            rank[present] = np.arange(len(present), dtype=np.int32)
            kernel_gcols.append((c[0], "idrank", 0, n))
            finish_gcols.append((c[0], "idrank", present, n))
            spans.append(n)
            extra.append(rank)
            continue
        if isinstance(payload, tuple):
            lo, hi = payload
        else:                        # present set, contiguous enough
            lo, hi = int(payload[0]), int(payload[-1])
        kernel_gcols.append((c[0], "idoff", 0, span))
        finish_gcols.append((c[0], "idoff", lo, span))
        spans.append(span)
        extra.append(np.int32(lo))
    g = int(np.prod(spans, dtype=np.int64))
    kernel_gcols = tuple(kernel_gcols)
    finish_gcols = tuple(finish_gcols)
    strides = mixed_radix_strides(spans)
    g_pad = kernels.pow2_bucket(g)
    # compaction capacity from measured selectivity.  NOTE: r (and hence
    # kmax) is pow2-bucketed from the phase-A matched count, so literal
    # stability holds only within a selectivity bucket — literals of the
    # same template whose match rates land in different pow2 buckets (or
    # cross the dense-flip threshold below) still compile fresh variants.
    kmax = _adaptive_kmax(matched, padded, total_docs, g_pad)
    kernel_spec = (kernel_gcols, strides, g_pad, agg_specs, kmax)
    finish_spec = (finish_gcols, strides, g_pad, agg_specs, kmax)
    return kernel_spec, finish_spec, tuple(extra), False


def drive_group_execution(run, group_spec, padded: int, total_docs: int):
    """Execution policy for device group-bys.

    `run(agg_specs, group_spec, extra_params)` dispatches the kernel and
    returns DEVICE outs (extra_params are appended after the filter
    operands); this driver pulls each dispatch's outputs host-side in
    one explicit batched jax.device_get. Filtered dictionary-keyed
    group-bys take the ADAPTIVE path:

    - Phase A (scout): masked min/max of each group column's dictIds +
      the matched count — streaming tree reductions, about one filter
      evaluation.
    - Phase A2 (conditional hist rung, adaptive_hist_specs): matched-id
      histograms → exact present sets for the densifying rank remap,
      dispatched only when the span key space would need the ranked
      sort layout (> DENSE_G_LIMIT).
    - Phase B: group tables over the REMAPPED key space (product of the
      scout's active spans — or bucketed PRESENT counts where the rank
      remap applies), with MXU block-compaction sized from the measured
      selectivity. Small remapped spaces take the dense one-hot layout
      (device psum combine); big ones the ranked layout.

    No sorts or row-scale scatters anywhere on the hot path — those are
    TPU's slow primitives. The one row-scale gather is the idrank
    remap's rank-vector lookup (kernels._group_key), paid only when the
    hist rung proves it collapses the key space below the offset span.
    Non-eligible plans fall back to the compacted kernel with the kmax
    escalation ladder.

    Returns (outs, group_spec_for_finish); None finish spec means the
    filter matched nothing (outs still carries the stats).
    """
    pa = adaptive_phase_a_specs(group_spec) \
        if padded <= kernels.DENSE_ROWS_LIMIT else None
    if pa is not None:
        # one batched device→host transfer per scout dispatch; the
        # per-bound int() reads below are host numpy, not device pulls
        ha = profiled_device_get(run(pa, None, ()))
        bounds = [(int(ha[f"agg{2 * i}.min"]), int(ha[f"agg{2 * i + 1}.max"]))
                  for i in range(len(pa) // 2)]
        matched = int(ha["stats.num_docs_matched"])
        scout = [("bounds", lo, hi) for lo, hi in bounds]
        if matched > 0:
            ph = adaptive_hist_specs(group_spec, bounds)
            if ph is not None:
                hh = profiled_device_get(run(ph, None, ()))
                scout = [("present",
                          np.nonzero(np.asarray(hh[f"agg{i}"])[: c[3]])[0])
                         for i, c in enumerate(group_spec[0])]
        kspec, fspec, extra, empty = adaptive_phase_b_spec(
            group_spec, scout, matched, padded, total_docs)
        if empty:
            return ha, None
        outs, final = run_with_group_escalation(
            lambda gs: run((), gs, extra), kspec, padded)
        if final is not kspec:            # ladder escalated kmax
            fspec = fspec[:4] + (final[4],)
        return outs, fspec
    return run_with_group_escalation(lambda gs: run((), gs, ()),
                                     group_spec, padded)



def _agg_device_spec(f: AggregationFunction, segment: ImmutableSegment,
                     needed: Dict, for_group: bool = False,
                     g_pad: int = 0, compact: bool = False) -> tuple:
    base = f.info.base
    if base == "COUNT" and not f.info.is_mv:
        return ("count", "*", "none", None)
    col = f.column
    if expr_mod.is_expression(col):
        # expression aggregation argument: the device produces a plain
        # dictId histogram over the SOURCE column; the host finisher
        # evaluates the transform over the dictionary value table and
        # computes SUM/AVG/MIN/MAX/PERCENTILE/DISTINCTCOUNT from
        # (histogram, transformed values) — exact, O(cardinality) transform
        # work, zero doc-scale expression evaluation
        if f.info.is_mv:
            raise UnsupportedOnDevice("MV expression aggregation")
        if for_group:
            raise UnsupportedOnDevice(
                "expression metric inside group-by (host path)")
        srcs = expr_mod.columns_of(col)
        if len(srcs) != 1:
            raise UnsupportedOnDevice("multi-column expression aggregation")
        src = srcs[0]
        cm = segment.data_source(src).metadata
        if not (cm.has_dictionary and cm.single_value):
            raise UnsupportedOnDevice(
                f"expression over non-dictionary/MV column {src}")
        card_pad = kernels.pow2_bucket(cm.cardinality + 1)
        needed[(src, "ids")] = None
        return ("hist", src, "sv", ("hist", card_pad))
    ds = segment.data_source(col)
    cm = ds.metadata
    if cm.data_type == DataType.VECTOR:
        raise ValueError(
            f"aggregation {base} over VECTOR column '{col}' is not "
            "supported (use VECTOR_SIMILARITY for ranking)")
    fname = {
        "COUNT": "countmv" if f.info.is_mv else "count",
        "SUM": "sum", "MIN": "min", "MAX": "max", "AVG": "avg",
        "MINMAXRANGE": "minmaxrange",
        "DISTINCTCOUNT": "distinctcount",
        "DISTINCTCOUNTHLL": "distinctcount", "FASTHLL": "distinctcount",
        "DISTINCTCOUNTRAWHLL": "distinctcount",
        "PERCENTILE": "percentile", "PERCENTILEEST": "percentile",
        "PERCENTILETDIGEST": "percentile",
    }[base]
    if not cm.has_dictionary:
        if fname in ("percentile", "distinctcount"):
            # raw columns have no dictId histogram: percentile can't merge
            # exactly across segments and distinctcount needs the value set —
            # both take the host fallback path
            raise UnsupportedOnDevice(f"{fname} over no-dictionary column")
        needed[(col, "raw")] = None
        if for_group and fname in ("sum", "avg") and \
                (compact or (segment.padded_docs <= kernels.DENSE_ROWS_LIMIT
                             and g_pad <= kernels.DENSE_G_LIMIT)):
            return (fname, col, "raw", ("csums",))
        return (fname, col, "raw", None)
    card_pad = kernels.pow2_bucket(cm.cardinality + 1)
    if cm.single_value:
        # Strategy selection (see kernels.py "TPU reduction strategy"):
        # integer dict SUM/AVG reads bit-sliced part lanes (exact, no
        # scatter/gather); float dict SUM/AVG reads a decoded value lane;
        # DISTINCTCOUNT/PERCENTILE take the histogram (one-hot matmul);
        # MIN/MAX reduce dictIds. Group-by uses the dense one-hot MXU paths
        # when the group table and segment size allow, else scatter.
        is_int_dict = cm.data_type.np_dtype.kind in "iu"
        dense_ok = segment.padded_docs <= kernels.DENSE_ROWS_LIMIT and \
            g_pad <= kernels.DENSE_G_LIMIT
        if for_group:
            if fname in ("distinctcount", "percentile"):
                # the group kernel has no per-group histogram path; these
                # take the host executor (set/sketch intermediates)
                raise UnsupportedOnDevice(
                    f"group-by with {fname} aggregation")
            if fname in ("sum", "avg"):
                if (dense_ok or compact) and is_int_dict:
                    needed[(col, "parts")] = None
                    return (fname, col, "sv", ("psums", card_pad))
                if dense_ok or compact:
                    needed[(col, "vlane")] = None
                    return (fname, col, "sv", ("csums", card_pad))
                needed[(col, "ids")] = None
                needed[(col, "vals")] = None
                return (fname, col, "sv", ("vals", card_pad))
            needed[(col, "ids")] = None
            return (fname, col, "sv", ("ids", card_pad))
        if base in ("DISTINCTCOUNTHLL", "DISTINCTCOUNTRAWHLL") and \
                not f.info.is_mv:
            # device HLL sketch registers: the dictId histogram's
            # present set scatter-maxes the per-dictId (register index,
            # rank) tables — register-identical to the host
            # HyperLogLog.from_values by construction (shared hashing,
            # sketches.hll_tables), merged by elementwise max across
            # segments/shards/servers. FASTHLL keeps the histogram path
            # (its derived-column rewrite unions serialized sketches).
            from pinot_tpu.common.sketches import DEFAULT_LOG2M
            needed[(col, "ids")] = None
            needed[(col, "hllidx")] = None
            needed[(col, "hllrank")] = None
            return ("hll", col, "sv", ("hll", card_pad,
                                       1 << DEFAULT_LOG2M))
        if fname in ("sum", "avg"):
            if is_int_dict:
                needed[(col, "parts")] = None
                return (fname, col, "sv", ("parts", card_pad))
            # float dictionaries: the MXU histogram + host f64 dot stays
            # EXACT on device-f32 TPUs; the f32 value-lane sum is only for
            # cardinalities past the one-hot matmul cap
            if card_pad <= kernels.DENSE_CARD_LIMIT:
                needed[(col, "ids")] = None
                return (fname, col, "sv", ("hist", card_pad))
            needed[(col, "vlane")] = None
            return (fname, col, "sv", ("vlane", card_pad))
        if fname in ("distinctcount", "percentile"):
            needed[(col, "ids")] = None
            return (fname, col, "sv", ("hist", card_pad))
        needed[(col, "ids")] = None
        return (fname, col, "sv", ("ids", card_pad))
    needed[(col, "mv")] = None
    if for_group:
        raise UnsupportedOnDevice("group-by over MV metric")
    return (fname, col, "mv", (card_pad, cm.cardinality))


def _collect_filter_cols(spec: tuple, needed: Dict) -> None:
    if spec[0] in ("and", "or"):
        for c in spec[1]:
            _collect_filter_cols(c, needed)
    elif spec[0] == "pred":
        _, kind, col, source, _ = spec
        if source == "ivf":
            # three lanes: assignments + padded codebook + validity
            for lane in ("ivfa", "ivfc", "ivfv"):
                needed[(col, lane)] = None
            return
        needed[(col, {"sv": "ids", "mv": "mv", "raw": "raw",
                      "vdoc": "vdoc"}[source])] = None


def selection_columns(segment: ImmutableSegment, request: BrokerRequest
                      ) -> List[str]:
    """Expand SELECT * to the segment's physical columns."""
    cols = request.selection.columns
    if cols == ["*"]:
        return [c for c in segment.column_names if not c.startswith("$")]
    return list(cols)


def _empty_block(plan: SegmentPlan, segment: ImmutableSegment
                 ) -> IntermediateResultsBlock:
    blk = IntermediateResultsBlock()
    if plan.request.is_group_by:
        blk.group_map = {}
    elif plan.request.is_aggregation:
        blk.agg_intermediates = [None for _ in plan.functions]
    if plan.request.vector is not None:
        from pinot_tpu.common.request import VECTOR_RESULT_COLUMNS
        blk.selection_rows = []
        blk.selection_columns = list(plan.request.selection.columns) + \
            list(VECTOR_RESULT_COLUMNS)
    elif plan.request.is_selection:
        blk.selection_rows = []
        blk.selection_columns = selection_columns(segment, plan.request)
    _fill_stats(blk, segment, 0, 0, 0)
    return blk


def _fill_stats(blk: IntermediateResultsBlock, segment: ImmutableSegment,
                docs_scanned: int, entries_filter: int, entries_post: int
                ) -> None:
    blk.stats = ExecutionStats(
        num_docs_scanned=docs_scanned,
        num_entries_scanned_in_filter=entries_filter,
        num_entries_scanned_post_filter=entries_post,
        num_segments_processed=1,
        num_segments_matched=1 if docs_scanned else 0,
        total_docs=segment.num_docs)
