"""Primary-key upsert metadata: key map, validDocIds, crash-consistent
recovery.

Parity: the reference's later-version upsert machinery
(PartitionUpsertMetadataManager / TableUpsertMetadataManager): a
per-partition map primary-key → (segment sequence, docId) of the LATEST
row per key, maintained by the realtime consumer; every superseded row
is recorded in its segment's `ValidDocIds` bitmap, which masks results
at query time on both the host scan path and the device kernels
(query/plan.py wires the mask as one more fused filter predicate).

Durability — the crash-consistency story (ISSUE 6 tentpole):

- **Delta journal** (`journal.jsonl`, per partition): one JSON line per
  ingested batch — the key→(seq, doc) assignments the batch made, plus
  the stream offset it ends at. Appended by the consumer thread, torn
  final line tolerated and truncated on recovery (same contract as the
  PR 4 property-store WAL).
- **Key-map snapshot** (`keymap-<seq>.json`): the whole partition map,
  written atomically at every segment SEAL (commit success). The journal
  is truncated after the snapshot lands — a crash between the two just
  replays deltas the snapshot already holds (idempotent).
- **validDocIds sidecars** (`validdocids-<segment>.json`): one per
  committed segment, rewritten at seal when the bitmap changed since
  the last write (a later row superseding an older segment's doc
  mutates that older segment's bitmap).

Recovery (restore(), run once per partition at first use after boot):
load the latest snapshot, load the sidecars, replay the journal tail —
the map and every bitmap converge to the crash instant without reading
the topic. The consuming segment then re-consumes from its durable
startOffset (its in-memory rows died with the process); re-applying
those rows is idempotent because replay is deterministic. A committed
segment that arrives with NO durable coverage (a replica that never
consumed it — the completion-FSM loser's download path — or a crash
before its first seal ever wrote) is FOLDED: its primary-key column is
read from the local artifact and reconciled against the map, which both
contributes its keys and recomputes its bitmap exactly.

Crash points (common/faults.py): `upsert.seal` (at seal entry, after the
commit succeeded), `upsert.keymap_snapshot` (mid-snapshot-write, before
the atomic rename — the torn-write shape), `upsert.replay` (post-restart
journal replay). tests/test_upsert.py kills at each and asserts
exact-count + latest-value convergence after restart.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.table_config import UpsertConfig
from pinot_tpu.common.table_name import raw_table
from pinot_tpu.realtime.segment_name import LLCSegmentName

log = logging.getLogger(__name__)

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_PREFIX = "keymap-"
SIDECAR_PREFIX = "validdocids-"

#: store prefix where servers publish per-committed-segment deadness
#: (invalid doc ids + doc count + bitmap version) for the minion
#: plane's compaction generator/executor — any replica's snapshot is a
#: safe UNDER-approximation (bits only ever set when a newer row won,
#: a global fact), so compaction may drop exactly those docs and the
#: swap-time remap re-derives whatever died since
DEADNESS_ROOT = "/DEADNESS"


def deadness_path(table: str, segment: str) -> str:
    return f"{DEADNESS_ROOT}/{table}/{segment}"


class ValidDocIds:
    """Per-segment liveness bitmap: a doc is valid unless invalidated.

    Default-valid semantics (only invalidations are recorded) make the
    consumer's index-then-apply sequence safe: a freshly indexed row is
    visible to queries before its upsert delta is applied, and is never
    transiently masked. Single writer (the partition's consumer thread
    or the restore/fold path, both serialized by the partition lock);
    readers take consistent snapshot copies under the lock. `version`
    bumps on every invalidation so device-lane caches know to re-upload.
    """

    def __init__(self):
        self._invalid = np.zeros(0, dtype=bool)
        self._num_invalid = 0
        self.version = 0
        self._lock = threading.Lock()

    @property
    def num_invalid(self) -> int:
        return self._num_invalid

    def invalidate(self, doc: int) -> bool:
        """Mark `doc` superseded; True when the bit flipped."""
        with self._lock:
            if doc >= len(self._invalid):
                cap = max(len(self._invalid), 1024)
                while cap <= doc:
                    cap *= 2
                bigger = np.zeros(cap, dtype=bool)
                bigger[: len(self._invalid)] = self._invalid
                self._invalid = bigger
            if self._invalid[doc]:
                return False
            self._invalid[doc] = True
            self._num_invalid += 1
            self.version += 1
            return True

    def invalidate_many(self, docs) -> int:
        flipped = 0
        for d in docs:
            if self.invalidate(int(d)):
                flipped += 1
        return flipped

    def valid_mask(self, start: int, end: int) -> np.ndarray:
        """Consistent bool copy of [start, end): True = doc is live."""
        with self._lock:
            out = np.ones(end - start, dtype=bool)
            m = min(len(self._invalid), end)
            if m > start:
                out[: m - start] = ~self._invalid[start:m]
            return out

    def invalid_ids(self, n: int) -> np.ndarray:
        with self._lock:
            return np.flatnonzero(self._invalid[:n]).astype(np.int64)


def _normalizer(field) -> Callable:
    """Value normalizer for one primary-key column: the SAME function is
    applied to ingested row values and to values decoded back out of a
    committed segment, so keys compare equal across both paths (a FLOAT
    column's f32 round-trip would otherwise split one key in two)."""
    from pinot_tpu.common.datatype import DataType
    dt = field.data_type.np_dtype
    if dt.kind in "iu":
        return lambda v: int(v)
    if dt.kind == "f":
        return lambda v: float(dt.type(v))
    if field.data_type == DataType.BYTES:
        return lambda v: (v.hex() if isinstance(v, (bytes, bytearray))
                          else str(v))
    return lambda v: str(v)


class PartitionUpsertMetadata:
    """One stream partition's key map + bitmaps + durable state.

    Writers: the partition's single consumer thread (apply_batch, seal)
    and state-transition threads (on_committed_segment fold) — all
    mutations take `_lock`. Readers (query paths) never touch the map;
    they read per-segment ValidDocIds snapshots.
    """

    def __init__(self, data_dir: str, table: str, partition: int,
                 enable_snapshot: bool = True):
        self.table = table
        self.partition = partition
        self.data_dir = data_dir
        self.enable_snapshot = enable_snapshot
        self._lock = threading.RLock()
        # key tuple -> (segment sequence, docId) of the LATEST row
        self._map: Dict[tuple, Tuple[int, int]] = {}
        self._valid: Dict[int, ValidDocIds] = {}      # seq -> bitmap
        self._covered: Dict[int, int] = {}            # seq -> docs covered
        self._sidecar_versions: Dict[int, int] = {}   # seq -> last written
        self._journal_f = None
        self.snapshot_offset = -1       # stream offset the snapshot covers
        self.replayed_offset = -1       # ... advanced by journal replay
        self.upserted_rows = 0          # rows that superseded an older doc
        self.masked_docs = 0            # docs invalidated
        self.remapped_segments = 0      # compacted artifacts remapped in
        self.gced_keys = 0              # map entries dropped by segment GC
        self._snapshot_seq = -1         # filename seq of the last snapshot
        os.makedirs(data_dir, exist_ok=True)
        self._restore()

    # -- core fold ---------------------------------------------------------

    def _bitmap(self, seq: int) -> ValidDocIds:
        with self._lock:                  # RLock: reentrant from callers
            vd = self._valid.get(seq)
            if vd is None:
                vd = self._valid[seq] = ValidDocIds()
            return vd

    def _apply(self, key: tuple, seq: int, doc: int) -> bool:
        """Fold one row into the map; True when it superseded an older
        doc. Order-independent: applying rows in any order converges to
        the same map and bitmaps (newest (seq, doc) wins; losers are
        invalidated wherever they live)."""
        with self._lock:                  # RLock: reentrant from callers
            loc = (seq, doc)
            e = self._map.get(key)
            if e == loc:
                return False             # idempotent replay
            if e is not None and e > loc:
                # an even newer row already owns the key: this doc is dead
                if self._bitmap(seq).invalidate(doc):
                    self.masked_docs += 1
                return False
            if e is not None:
                if self._bitmap(e[0]).invalidate(e[1]):
                    self.masked_docs += 1
            self._map[key] = loc
            return e is not None

    # -- ingest path -------------------------------------------------------

    def register_consuming(self, seq: int) -> ValidDocIds:
        """Bitmap for the consuming segment (restored state reused so a
        restarted consumer's re-applied rows land on the same bits)."""
        with self._lock:
            return self._bitmap(seq)

    def apply_batch(self, seq: int, keys_docs: List[Tuple[tuple, int]],
                    end_offset: int) -> int:
        """Fold one consumed batch; journal the deltas; returns the
        number of rows that superseded an existing key."""
        if not keys_docs:
            return 0
        with self._lock:
            upserts = 0
            for key, doc in keys_docs:
                if self._apply(key, seq, doc):
                    upserts += 1
            top = max(doc for _k, doc in keys_docs) + 1
            self._covered[seq] = max(self._covered.get(seq, 0), top)
            self.upserted_rows += upserts
            self._journal_append(seq, end_offset, keys_docs)
        return upserts

    def key_map_size(self) -> int:
        return len(self._map)

    # -- durability --------------------------------------------------------

    def _journal_path(self) -> str:
        return os.path.join(self.data_dir, JOURNAL_FILE)

    def _journal_append(self, seq: int, end_offset: int,
                        keys_docs: List[Tuple[tuple, int]]) -> None:
        if not self.enable_snapshot:
            return
        with self._lock:                  # RLock: reentrant from callers
            # seeded crash point: die before the append — the batch is
            # in memory but neither journaled nor offset-acked, so the
            # restarted consumer re-fetches and re-applies it (the
            # order-independent fold makes the replay idempotent)
            crash_points.hit("upsert.journal_append")
            try:
                if self._journal_f is None:
                    self._journal_f = open(self._journal_path(), "a")  # tpulint: disable=lock-blocking -- crash-consistency: the key-map mutation and its journal record must be atomic; append cadence is per consume batch, not per query
                rec = {"seq": int(seq), "off": int(end_offset),
                       "d": [[list(k), int(doc)] for k, doc in keys_docs]}
                self._journal_f.write(json.dumps(rec) + "\n")
                self._journal_f.flush()
            except OSError:
                log.warning("upsert journal append failed for %s/p%d",
                            self.table, self.partition, exc_info=True)

    def seal(self, seq: int, end_offset: int, num_docs: int) -> None:
        """Segment SEAL hook (commit succeeded): snapshot the key map,
        write/update validDocIds sidecars, truncate the journal.

        Write order is crash-safe at every instruction: sidecars and the
        snapshot are staged + atomically renamed; the journal is only
        truncated AFTER the snapshot landed, so a crash in between
        replays deltas the snapshot already contains (idempotent)."""
        if not self.enable_snapshot:
            return
        crash_points.hit("upsert.seal")
        with self._lock:
            self._covered[seq] = max(self._covered.get(seq, 0),
                                     int(num_docs))
            entries = [[list(k), int(s), int(d)]
                       for k, (s, d) in self._map.items()]
            covered = dict(self._covered)
            bitmaps = {s: (self._valid[s].version,
                           self._valid[s].invalid_ids(covered.get(s, 0)))
                       for s in self._valid}
        for s, (ver, invalid) in sorted(bitmaps.items()):
            if self._sidecar_versions.get(s) == ver and \
                    os.path.exists(self._sidecar_path(s)):
                continue
            self._write_sidecar(s, covered.get(s, 0), invalid, ver)
        snap = {"seq": int(seq), "offset": int(end_offset),
                "entries": entries}
        path = os.path.join(self.data_dir, f"{SNAPSHOT_PREFIX}{seq}.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(snap, fh)
            fh.flush()
            os.fsync(fh.fileno())
        # seeded torn-write point: the process dies with the snapshot
        # staged but not renamed — recovery ignores the .tmp and falls
        # back to the previous snapshot + the (untruncated) journal
        crash_points.hit("upsert.keymap_snapshot")
        os.replace(tmp, path)
        with self._lock:
            self.snapshot_offset = int(end_offset)
            self._snapshot_seq = int(seq)
        for name in os.listdir(self.data_dir):
            if name.startswith(SNAPSHOT_PREFIX) and \
                    name.endswith(".json") and \
                    name != os.path.basename(path):
                try:
                    os.remove(os.path.join(self.data_dir, name))
                except OSError:
                    pass
        with self._lock:
            try:
                if self._journal_f is not None:
                    self._journal_f.close()
                self._journal_f = open(self._journal_path(), "w")  # tpulint: disable=lock-blocking -- seal(): journal truncate must pair atomically with the just-written key-map snapshot
            except OSError:
                self._journal_f = None

    def _sidecar_path(self, seq: int) -> str:
        name = LLCSegmentName(raw_table(self.table), self.partition,
                              seq).name
        return os.path.join(self.data_dir, f"{SIDECAR_PREFIX}{name}.json")

    def _write_sidecar(self, seq: int, num_docs: int,
                       invalid: np.ndarray, version: int) -> None:
        path = self._sidecar_path(seq)
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump({"seq": int(seq), "numDocs": int(num_docs),
                           "invalid": [int(i) for i in invalid]}, fh)
            os.replace(tmp, path)
            with self._lock:
                self._sidecar_versions[seq] = version
        except OSError:
            log.warning("sidecar write failed for %s/p%d seq %d",
                        self.table, self.partition, seq, exc_info=True)

    # -- recovery ----------------------------------------------------------

    def _restore(self) -> None:
        if not self.enable_snapshot:
            return
        # boot-time single-threaded, but take the lock anyway so every
        # mutation site in this class is lexically guarded (RLock:
        # reentrant into _bitmap/_apply/_replay_journal)
        with self._lock:
            snaps = []
            for name in os.listdir(self.data_dir):
                if name.startswith(SNAPSHOT_PREFIX) and \
                        name.endswith(".json"):
                    try:
                        snaps.append(
                            (int(name[len(SNAPSHOT_PREFIX):-5]), name))
                    except ValueError:
                        continue
            snapshot_lost = False
            if snaps:
                _seq, name = max(snaps)
                self._snapshot_seq = int(_seq)
                try:
                    with open(os.path.join(self.data_dir, name)) as fh:  # tpulint: disable=lock-blocking -- _restore runs once at boot before the consumer starts; nothing else can hold or want this lock yet
                        snap = json.load(fh)
                    for k, s, d in snap.get("entries", ()):
                        self._map[tuple(k)] = (int(s), int(d))
                    self.snapshot_offset = int(snap.get("offset", -1))
                except (OSError, ValueError):
                    snapshot_lost = True
                    log.warning("unreadable upsert snapshot %s; folding "
                                "from segments instead", name,
                                exc_info=True)
            for name in sorted(os.listdir(self.data_dir)):
                if not (name.startswith(SIDECAR_PREFIX) and
                        name.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(self.data_dir, name)) as fh:  # tpulint: disable=lock-blocking -- same boot-time-only invariant as the snapshot read above
                        side = json.load(fh)
                    seq = int(side["seq"])
                    vd = self._bitmap(seq)
                    self.masked_docs += vd.invalidate_many(side["invalid"])
                    # a LOST snapshot means sidecar-covered segments'
                    # map entries are gone too: leave them uncovered so
                    # attach_or_fold re-folds their keys (keeping the
                    # sidecar bits is still sound — masks never
                    # resurrect, and a superseded doc stays superseded)
                    if not snapshot_lost:
                        self._covered[seq] = max(self._covered.get(seq, 0),
                                                 int(side["numDocs"]))
                    self._sidecar_versions[seq] = vd.version
                except (OSError, ValueError, KeyError):
                    log.warning("unreadable validDocIds sidecar %s; the "
                                "segment will be folded from its keys",
                                name, exc_info=True)
            self._replay_journal()

    def _replay_journal(self) -> None:
        path = self._journal_path()
        if not os.path.exists(path):
            return
        # post-restart replay crash point: dying HERE (map partially
        # rebuilt) must leave the durable state replayable again
        crash_points.hit("upsert.replay")
        with self._lock:                  # RLock: reentrant from _restore
            good = 0
            try:
                with open(path, "rb") as fh:  # tpulint: disable=lock-blocking -- journal replay is boot-time-only (see _restore); held lock is uncontended by construction
                    raw = fh.read()
            except OSError:
                # IO failures are advisory (module contract): the fold
                # path re-derives masks — never block transitions
                log.warning("unreadable upsert journal for %s/p%d; "
                            "relying on segment folds", self.table,
                            self.partition, exc_info=True)
                return
            lines = raw.split(b"\n")
            unterminated_ok = False
            for i, line in enumerate(lines):
                last = i == len(lines) - 1
                if not line.strip():
                    good += len(line) + (0 if last else 1)
                    continue
                try:
                    rec = json.loads(line)
                    seq, off = int(rec["seq"]), int(rec["off"])
                    deltas = [(tuple(k), int(doc)) for k, doc in rec["d"]]
                except (ValueError, KeyError, TypeError):
                    break                   # torn tail: drop + truncate
                for key, doc in deltas:
                    self._apply(key, seq, doc)
                if deltas:
                    top = max(doc for _k, doc in deltas) + 1
                    self._covered[seq] = max(self._covered.get(seq, 0),
                                             top)
                self.replayed_offset = max(self.replayed_offset, off)
                good += len(line) + (0 if last else 1)
                if last:                    # split: last piece has no \n
                    unterminated_ok = True
            try:
                if good < len(raw):
                    with open(path, "ab") as fh:  # tpulint: disable=lock-blocking -- boot-time torn-tail repair, same uncontended-lock invariant
                        fh.truncate(good)
                elif unterminated_ok:
                    # crash cut the write exactly between the record and
                    # its newline: repair the terminator so the next
                    # append can't merge two records into one torn line
                    with open(path, "ab") as fh:  # tpulint: disable=lock-blocking -- boot-time newline repair, same uncontended-lock invariant
                        fh.write(b"\n")
            except OSError:
                pass

    # -- committed-segment attach / fold / remap ---------------------------

    def attach_or_fold(self, seq: int, segment,
                       keys_fn: Callable[[], List[tuple]]) -> ValidDocIds:
        """Give `segment` its ValidDocIds. When durable state exactly
        covers the segment's docs (local consume, or snapshot+journal
        restore), the registered bitmap attaches as-is; when it covers
        FEWER docs, the segment's primary keys (``keys_fn``) are folded
        into the map — the loser-download / lost-durable-state
        convergence path. When it covers MORE docs than the artifact
        holds, the artifact is a compacted (or discard-truncated)
        rewrite: its doc ids shifted, so the stale bitmap is discarded
        and every row is REMAPPED against the key map (same-key map
        entries move to the new doc id; rows whose key a newer segment
        owns are invalidated fresh)."""
        with self._lock:
            vd = self._valid.get(seq)
            covered = self._covered.get(seq, 0)
            if vd is not None and covered == segment.num_docs:
                return vd
            needs_remap = covered > segment.num_docs
        keys = keys_fn()                  # heavy decode outside the lock
        if needs_remap:
            return self._remap_segment(seq, keys)
        with self._lock:
            vd = self._bitmap(seq)
            upserts = 0
            for doc, key in enumerate(keys):
                if self._apply(key, seq, doc):
                    upserts += 1
            self.upserted_rows += upserts
            self._covered[seq] = max(self._covered.get(seq, 0), len(keys))
            return vd

    def _remap_segment(self, seq: int, keys: List[tuple]) -> ValidDocIds:
        """Compaction swap: rebuild seq's bitmap and re-point its map
        entries at the rewritten artifact's doc ids. The fold stays
        order-independent: a key some NEWER segment owns masks the
        compacted row; a key an OLDER segment owns is superseded by it
        (the compacted row is the same logical row that already won).
        Idempotent — re-running over an already-remapped map is a
        no-op — and persisted (snapshot + sidecar) so a crash after the
        swap does not resurrect stale doc ids on restart."""
        with self._lock:
            vd = ValidDocIds()
            self._valid[seq] = vd
            for doc, key in enumerate(keys):
                loc = (seq, doc)
                e = self._map.get(key)
                if e is None or e[0] == seq:
                    # this key's winner lives (or lived) in this segment:
                    # the compacted row IS that winner, at its new id
                    self._map[key] = loc
                elif e > loc:
                    # a newer segment superseded the key since compaction
                    if vd.invalidate(doc):
                        self.masked_docs += 1
                else:
                    # an older segment held the key: compacted row wins
                    if self._bitmap(e[0]).invalidate(e[1]):
                        self.masked_docs += 1
                    self._map[key] = loc
            self._covered[seq] = len(keys)
            self._sidecar_versions.pop(seq, None)
            self.remapped_segments += 1
            invalid = vd.invalid_ids(len(keys))
            version = vd.version
            num_docs = len(keys)
        # persist OUTSIDE the lock: snapshot first (remapped entries),
        # then the sidecar — a crash anywhere here re-runs the remap on
        # restart from whatever durable state survived; every path is
        # idempotent by the fold above. Seeded crash point: die with the
        # remap applied in memory but nothing persisted.
        crash_points.hit("upsert.compact_snapshot")
        self.snapshot_now(seq)
        self._write_sidecar(seq, num_docs, invalid, version)
        return vd

    def snapshot_now(self, seq_hint: int = 0) -> None:
        """Write a key-map snapshot outside the seal path (compaction
        remap / GC persistence). Same staged + fsync + atomic-rename
        discipline as seal; the journal is NOT truncated — its replay
        is idempotent over the newer snapshot, and offset bookkeeping
        belongs to seal alone. Deliberate twin of seal()'s snapshot
        block, NOT a shared helper: seal's own `open(tmp…)` stage and
        `os.replace(tmp…)` rename statements are the protocol tier's
        extraction anchors (analysis/protocol.py extract_seal) — moving
        them into a callee would break the shape contract the
        upsert-seal model is built from."""
        if not self.enable_snapshot:
            return
        with self._lock:
            seq = max(self._snapshot_seq, int(seq_hint))
            entries = [[list(k), int(s), int(d)]
                       for k, (s, d) in self._map.items()]
            offset = int(self.snapshot_offset)
        snap = {"seq": int(seq), "offset": offset, "entries": entries}
        path = os.path.join(self.data_dir, f"{SNAPSHOT_PREFIX}{seq}.json")
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump(snap, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            with self._lock:
                self._snapshot_seq = seq
        except OSError:
            # advisory (module contract): the remap re-derives on boot
            log.warning("compact snapshot write failed for %s/p%d",
                        self.table, self.partition, exc_info=True)

    def gc_segment(self, seq: int) -> int:
        """Table-wide segment deletion (TTL retention / merge-away):
        drop every key-map entry whose winner lived in `seq`, its
        bitmap, coverage and sidecar — the key no longer exists in the
        table, so the map must stop carrying it (the `upsertKeyMapSize`
        growth story). Masks never resurrect: other segments' bits for
        keys this segment once superseded stay set. Returns the number
        of entries dropped."""
        dropped = self._gc_segment_inner(seq)
        if dropped:
            self._persist_gc()
        return dropped

    def _gc_segment_inner(self, seq: int) -> int:
        with self._lock:
            doomed = [k for k, loc in self._map.items() if loc[0] == seq]
            for k in doomed:
                del self._map[k]
            self._valid.pop(seq, None)
            self._covered.pop(seq, None)
            self._sidecar_versions.pop(seq, None)
            self.gced_keys += len(doomed)
        try:
            os.remove(self._sidecar_path(seq))
        except OSError:
            pass                          # never written / already gone
        return len(doomed)

    def _persist_gc(self) -> None:
        """Persist the shrunken map NOW: the record-removal event fires
        exactly once, so waiting for the next seal would let a crash
        resurrect the dropped entries from the old snapshot forever on
        a low-traffic partition. Seeded crash point: dying HERE leaves
        zombie entries in the old snapshot — a bounded metric skew
        (key_map_size overcounts), never a correctness loss (the
        deleted segment is unrouted and masks never resurrect); the
        boot-time `gc_missing` reconcile re-converges them."""
        crash_points.hit("upsert.gc_snapshot")
        self.snapshot_now()

    def gc_missing(self, live_seqs) -> int:
        """Boot/build-time reconcile: garbage-collect every seq this
        partition's durable state still tracks whose segment RECORD no
        longer exists in the cluster state. The record-removal watch
        (the online GC path) is in-memory and one-shot — a server that
        was down, restarting, or had not yet built the table's upsert
        manager when retention deleted a segment would otherwise carry
        its zombie keys forever. Returns entries dropped."""
        live = set(live_seqs)
        with self._lock:
            known = set(self._covered) | set(self._valid) | \
                {loc[0] for loc in self._map.values()}
        dropped = 0
        for seq in sorted(known - live):
            dropped += self._gc_segment_inner(seq)
        if dropped:
            self._persist_gc()
        return dropped

    def deadness_report(self, skip_versions: Optional[Dict[int, int]]
                        = None) -> Dict[int, dict]:
        """Per-seq deadness snapshot (invalid doc ids + covered docs +
        bitmap version) for obs-plane publication — the compaction
        generator's scheduling signal and the executor's drop list.
        `skip_versions` (seq → already-published version) suppresses
        unchanged bitmaps BEFORE their invalid-id lists are
        materialized, so a per-seal publication sweep is O(changed),
        not O(all segments × invalid docs)."""
        with self._lock:
            out = {}
            for seq, vd in self._valid.items():
                if skip_versions is not None and \
                        skip_versions.get(seq) == vd.version:
                    continue
                n = int(self._covered.get(seq, 0))
                out[seq] = {"version": int(vd.version), "numDocs": n,
                            "invalid": [int(i) for i in
                                        vd.invalid_ids(n)]}
            return out

    def close(self) -> None:
        with self._lock:
            if self._journal_f is not None:
                try:
                    self._journal_f.close()
                except OSError:
                    pass
                self._journal_f = None


class TableUpsertMetadataManager:
    """All partitions' upsert metadata for one realtime table on one
    server. Owns key extraction (schema-normalized so ingested rows and
    decoded segment columns produce identical key tuples)."""

    def __init__(self, table: str, config: UpsertConfig, schema,
                 data_dir: str, metrics=None, live_seqs_fn=None):
        """`live_seqs_fn`: partition -> set of sequences with a LIVE
        segment record — when wired, a freshly built/restored
        partition reconciles its durable key-map state against the
        cluster state (gc_missing), catching table-wide deletions this
        server's one-shot record watch missed while down."""
        self.table = table
        self.config = config
        self.data_dir = data_dir
        self.metrics = metrics
        self._live_seqs_fn = live_seqs_fn
        self._parts: Dict[int, PartitionUpsertMetadata] = {}
        self._lock = threading.Lock()
        self._normalizers: List[Tuple[str, Callable]] = []
        for col in config.primary_key_columns:
            field = next((f for f in schema.fields if f.name == col), None)
            if field is None:
                raise ValueError(
                    f"upsert primary key column '{col}' not in schema "
                    f"'{schema.schema_name}'")
            if not field.single_value:
                raise ValueError(
                    f"upsert primary key column '{col}' must be "
                    "single-value")
            self._normalizers.append((col, _normalizer(field)))
        if metrics is not None:
            self.register_metrics(metrics)

    def register_metrics(self, metrics) -> None:
        """Bind the key-map size gauge to THIS instance. Callers that
        race on construction must register only the winning instance —
        a discarded loser's callable would pin the gauge at 0."""
        with self._lock:
            self.metrics = metrics
        from pinot_tpu.common.metrics import ServerGauge
        metrics.gauge(ServerGauge.UPSERT_KEY_MAP_SIZE,
                      self.table).set_callable(self.key_map_size)

    def partition(self, partition: int) -> PartitionUpsertMetadata:
        with self._lock:
            part = self._parts.get(partition)
            created = part is None
            if created:
                part = PartitionUpsertMetadata(
                    os.path.join(self.data_dir, f"partition_{partition}"),
                    self.table, partition,
                    enable_snapshot=self.config.enable_snapshot)
                self._parts[partition] = part
        if created and self._live_seqs_fn is not None:
            # reconcile restored state against the cluster records:
            # segments deleted while this server was away leave no
            # watch event — their keys must not resurrect
            try:
                dropped = part.gc_missing(self._live_seqs_fn(partition))
            except Exception:  # noqa: BLE001 — advisory reconcile:
                dropped = 0    # a flaky store read must not block boot
                log.warning("upsert GC reconcile failed for %s/p%d",
                            self.table, partition, exc_info=True)
            if dropped and self.metrics is not None:
                from pinot_tpu.common.metrics import ServerMeter
                self.metrics.meter(ServerMeter.UPSERT_KEYS_GCED,
                                   self.table).mark(dropped)
        return part

    def key_of(self, row: dict) -> Optional[tuple]:
        """Normalized primary-key tuple, or None when any key value is
        missing or unconvertible — callers DROP such rows before
        indexing (the poison-row policy: one bad record must never kill
        the partition consumer, and an unindexed row needs no map
        entry so ingest and segment-fold stay consistent)."""
        out = []
        for col, norm in self._normalizers:
            v = row.get(col)
            if v is None:
                return None
            try:
                out.append(norm(v))
            except (TypeError, ValueError):
                return None
        return tuple(out)

    def segment_keys(self, segment) -> List[tuple]:
        """Primary-key tuples per docId, decoded from a loaded segment's
        columns (same normalization as the ingest path)."""
        cols = []
        for name, norm in self._normalizers:
            ds = segment.data_source(name)
            if getattr(ds, "dictionary", None) is not None:
                vals = np.asarray(ds.dictionary.values)[ds.dict_ids]
            else:
                vals = ds.raw_values
            cols.append([norm(v) for v in vals])
        if not cols:
            return []
        return list(zip(*cols))

    def on_committed_segment(self, segment_name: str, segment) -> None:
        """CONSUMING→ONLINE swap / cold-start load: attach (or fold, or
        — for a compacted rewrite whose doc ids shifted — remap) the
        committed segment's validDocIds and mark superseded rows."""
        try:
            llc = LLCSegmentName.parse(segment_name)
        except ValueError:
            return                         # non-LLC segment: not upserted
        part = self.partition(llc.partition)
        before = part.remapped_segments
        segment.valid_doc_ids = part.attach_or_fold(
            llc.sequence, segment, lambda: self.segment_keys(segment))
        if part.remapped_segments > before and self.metrics is not None:
            from pinot_tpu.common.metrics import ServerMeter
            self.metrics.meter(ServerMeter.UPSERT_SEGMENTS_REMAPPED,
                               self.table).mark()

    def gc_segment_record(self, segment_name: str) -> int:
        """A segment's durable record left the cluster state (TTL
        retention / table-wide delete): garbage-collect its key-map
        entries so the map stops growing. No-op for partitions this
        server never built metadata for."""
        try:
            llc = LLCSegmentName.parse(segment_name)
        except ValueError:
            return 0
        with self._lock:
            part = self._parts.get(llc.partition)
        if part is None:
            return 0
        dropped = part.gc_segment(llc.sequence)
        if dropped and self.metrics is not None:
            from pinot_tpu.common.metrics import ServerMeter
            self.metrics.meter(ServerMeter.UPSERT_KEYS_GCED,
                               self.table).mark(dropped)
        return dropped

    def deadness_reports(self, skip_versions: Optional[Dict[str, int]]
                         = None) -> Dict[str, dict]:
        """segment name → deadness record for every partition/seq this
        manager tracks (the obs-plane publication payload).
        `skip_versions` (segment name → already-published version)
        suppresses unchanged bitmaps before their lists are built."""
        with self._lock:
            parts = dict(self._parts)
        out: Dict[str, dict] = {}
        raw = raw_table(self.table)
        for partition, part in parts.items():
            per_seq = None
            if skip_versions is not None:
                per_seq = {}
                for name, ver in skip_versions.items():
                    try:
                        llc = LLCSegmentName.parse(name)
                    except ValueError:
                        continue
                    if llc.partition == partition:
                        per_seq[llc.sequence] = ver
            for seq, info in part.deadness_report(per_seq).items():
                name = LLCSegmentName(raw, partition, seq).name
                out[name] = dict(info, segment=name)
        return out

    def key_map_size(self) -> int:
        with self._lock:
            parts = list(self._parts.values())
        return sum(p.key_map_size() for p in parts)

    def close(self) -> None:
        with self._lock:
            parts = list(self._parts.values())
            self._parts.clear()
        for p in parts:
            p.close()
