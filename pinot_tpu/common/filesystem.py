"""PinotFS: the deep-store filesystem abstraction.

Parity: pinot-common/.../filesystem/PinotFS.java (copy/move/delete/mkdir/
exists/listFiles + factory by URI scheme) with LocalPinotFS as the default
implementation. Segment directories are the durable artifacts; servers
fetch them from the deep store on ONLINE transitions.
"""
from __future__ import annotations

import os
import shutil
from typing import Dict, List, Type


class PinotFS:
    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        raise NotImplementedError

    def move(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_files(self, path: str) -> List[str]:
        raise NotImplementedError

    def is_directory(self, path: str) -> bool:
        raise NotImplementedError


class LocalPinotFS(PinotFS):
    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> bool:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
            return True
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def move(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.move(src, dst)
        return True

    def copy(self, src: str, dst: str) -> bool:
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            shutil.copy2(src, dst)
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def list_files(self, path: str) -> List[str]:
        return sorted(os.path.join(path, f) for f in os.listdir(path))

    def is_directory(self, path: str) -> bool:
        return os.path.isdir(path)


_REGISTRY: Dict[str, Type[PinotFS]] = {"file": LocalPinotFS}


def register_fs(scheme: str, cls: Type[PinotFS]) -> None:
    _REGISTRY[scheme] = cls


def get_fs(uri: str = "file://") -> PinotFS:
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    try:
        return _REGISTRY[scheme]()
    except KeyError:
        raise ValueError(f"no PinotFS registered for scheme '{scheme}'")
