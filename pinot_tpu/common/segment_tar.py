"""Segment artifact packing: segment dir <-> tar.gz bytes.

The wire format for segment artifacts everywhere they travel — the
controller upload endpoint, the deep-store HTTP download, the LLC
split-commit upload (parity: the reference's TarGzCompressionUtils,
pinot-common/.../utils/TarGzCompressionUtils.java)."""
from __future__ import annotations

import io
import os
import tarfile


def pack_segment_dir(segment_dir: str) -> bytes:
    """Segment directory → tar.gz bytes (the upload artifact format)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for entry in sorted(os.listdir(segment_dir)):
            tar.add(os.path.join(segment_dir, entry), arcname=entry)
    return buf.getvalue()


def unpack_segment_tar(data: bytes, dest_dir: str) -> None:
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        for member in tar.getmembers():
            # flat segment artifacts only: refuse path traversal
            name = os.path.normpath(member.name)
            if name.startswith("..") or os.path.isabs(name) or \
                    not (member.isfile() or member.isdir()):
                raise ValueError(f"unsafe tar member: {member.name}")
        try:
            tar.extractall(dest_dir, filter="data")
        except TypeError:            # Python < 3.12: no filter kwarg
            tar.extractall(dest_dir)
