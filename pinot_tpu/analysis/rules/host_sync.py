"""host-sync: silent device→host transfers on the kernel path.

``.item()``, ``int()/float()`` over ``np.asarray(...)``, and
``np.nonzero`` applied to a device array each force a blocking
device→host copy — per call. Inside a jit trace they are worse:
numpy on a tracer is a trace-time concretization error, or silently
constant-folds. On the (non-jitted) kernel path the fix is batching:
ONE explicit ``jax.device_get`` per dispatch, host math after.

Host-evidence dataflow: a name assigned from ``jax.device_get(...)``
or any ``numpy.*`` call is proven host-side and never flagged; a name
assigned from a ``jax.*``/``jax.numpy.*`` call is device-tainted. The
rule stays quiet on values it can't classify except for the explicit
sync idioms (``.item()``, ``int(np.asarray(..))``, ``np.nonzero``)
whose only purpose is pulling data to the host.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

_NP_SYNC = {"numpy.asarray", "numpy.array", "numpy.nonzero"}
_NP_ASARRAY = {"numpy.asarray", "numpy.array"}


def _classify_names(fn: ast.AST, aliases: Dict[str, str]
                    ) -> (Set[str], Set[str]):
    """(host-proven names, device-tainted names) for one function body."""
    host: Set[str] = set()
    device: Set[str] = set()
    for node in astutil.walk_shallow(fn):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        # hist = np.asarray(outs[...])[: n] — classify through slicing
        while isinstance(val, ast.Subscript):
            val = val.value
        if not isinstance(val, ast.Call):
            continue
        callee = astutil.resolve(val.func, aliases)
        if callee is None and isinstance(val.func, ast.Call):
            # e.g. jax.vmap(f)(x): classify by the inner callee
            callee = astutil.resolve(val.func.func, aliases)
        if callee is None:
            continue
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if callee == "jax.device_get" or callee.startswith("numpy."):
                host.add(tgt.id)
            elif callee == "jax.device_put":
                device.add(tgt.id)
            elif callee.split(".")[0] == "jax":
                device.add(tgt.id)
    return host - device, device


def _np_call(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    d = astutil.resolve(call.func, aliases)
    return d if d in _NP_SYNC else None


@register
class HostSyncRule(Rule):
    id = "host-sync"
    description = ("device→host sync (.item/int/float/np.asarray/"
                   "np.nonzero on device values) on the kernel path or "
                   "inside a jitted function")

    def check(self, ctx) -> Iterator[Finding]:
        on_kernel_path = ctx.in_prefixes(ctx.config.kernel_path_prefixes)
        for fn in astutil.iter_functions(ctx.tree):
            jitted = astutil.is_jitted(fn, ctx.aliases)
            if not (jitted or on_kernel_path):
                continue
            yield from self._check_fn(ctx, fn, jitted)

    def _check_fn(self, ctx, fn, jitted: bool) -> Iterator[Finding]:
        host, device = _classify_names(fn, ctx.aliases)

        def is_host(node: ast.AST) -> bool:
            while isinstance(node, ast.Subscript):
                node = node.value
            if isinstance(node, ast.Call):
                # a numpy call's RESULT is host by construction (any
                # device→host sync it performs is flagged at ITS site)
                callee = astutil.resolve(node.func, ctx.aliases)
                if callee is not None and callee.startswith("numpy."):
                    return True
            r = astutil.root_name(node)
            return r in host

        def is_device(node: ast.AST) -> bool:
            r = astutil.root_name(node)
            return r in device

        for node in astutil.walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            # x.item()
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                if not is_host(node.func.value):
                    yield ctx.finding(
                        self.id, node,
                        ".item() forces a blocking device→host transfer "
                        "per element — batch with one jax.device_get per "
                        "dispatch")
                continue
            callee = astutil.resolve(node.func, ctx.aliases)
            # np.asarray / np.array / np.nonzero
            if callee in _NP_SYNC:
                arg = node.args[0] if node.args else None
                if jitted:
                    yield ctx.finding(
                        self.id, node,
                        f"{callee.replace('numpy.', 'np.')} inside a "
                        "jitted function concretizes the tracer (host "
                        "round-trip or trace error)")
                elif arg is not None and is_device(arg):
                    yield ctx.finding(
                        self.id, node,
                        f"{callee.replace('numpy.', 'np.')} on a device "
                        "array syncs device→host — use an explicit "
                        "batched jax.device_get")
                elif callee == "numpy.nonzero" and arg is not None and \
                        not is_host(arg):
                    yield ctx.finding(
                        self.id, node,
                        "np.nonzero on a possibly-device value syncs "
                        "device→host — device_get the operand first")
                continue
            # int(...) / float(...) / bool(...)
            if isinstance(node.func, ast.Name) and \
                    node.func.id in ("int", "float", "bool") and \
                    len(node.args) == 1:
                arg = node.args[0]
                if isinstance(arg, ast.Constant):
                    continue
                wrapped_np = (isinstance(arg, ast.Call) and
                              astutil.resolve(arg.func, ctx.aliases)
                              in _NP_ASARRAY)
                if jitted:
                    yield ctx.finding(
                        self.id, node,
                        f"{node.func.id}() on a traced value inside a "
                        "jitted function forces concretization")
                elif wrapped_np and arg.args and not is_host(arg.args[0]):
                    yield ctx.finding(
                        self.id, node,
                        f"{node.func.id}(np.asarray(..)) pulls one scalar "
                        "device→host per call — batch the transfers into "
                        "one jax.device_get per combine")
                elif is_device(arg):
                    yield ctx.finding(
                        self.id, node,
                        f"{node.func.id}() on a device array blocks on a "
                        "device→host transfer")
