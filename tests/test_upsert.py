"""Realtime primary-key upsert: dedup semantics + crash-consistent
recovery (ISSUE 6).

Four tiers:

1. **Config + bitmap semantics** — UpsertConfig JSON round-trip,
   controller-side validation, ValidDocIds default-valid snapshots.
2. **Query masking parity** — device scan path, sharded kernel path and
   the host oracle return identical masked COUNT/SUM/GROUP BY/selection
   results; whole-segment fast paths (metadata counts, inverted-index
   counts) are disabled once a mask is active.
3. **Durability units** — snapshot + journal restore, torn journal
   tail, sidecar loss → key-column fold fallback.
4. **Kill-and-restart convergence** — the cluster dies mid upsert
   stream at each seeded crash point (segment seal, key-map snapshot
   write, post-restart replay) and a restart over the same durable
   state converges to exact row count and latest value per key.
"""
import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

from fixtures import make_columns, make_schema, make_table_config

from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.table_config import TableConfig, UpsertConfig
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.query.reduce import BrokerReduceService
from pinot_tpu.realtime import registry
from pinot_tpu.realtime.stream import (MemoryStream,
                                       MemoryStreamConsumerFactory)
from pinot_tpu.realtime.upsert import (PartitionUpsertMetadata,
                                       ValidDocIds)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.tools.cluster import EmbeddedCluster

from test_realtime import make_rows, rt_config

RT_TABLE = "baseballStats_REALTIME"


def wait_until(cond, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:  # noqa: BLE001 — condition not ready yet
            pass
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _clean_crash_points():
    crash_points.clear()
    yield
    crash_points.clear()


@pytest.fixture
def work_dir():
    return tempfile.mkdtemp()


def upsert_rt_config(factory, topic, flush_rows=300,
                     pk=("playerName",)):
    cfg = rt_config(factory, topic, flush_rows=flush_rows)
    cfg.upsert_config = UpsertConfig(mode="FULL",
                                     primary_key_columns=list(pk))
    return cfg


def latest_by_key(rows):
    latest = {}
    for r in rows:
        latest[r["playerName"]] = r
    return latest


def _register(topic, batch_size=50, num_partitions=1):
    stream = MemoryStream(topic, num_partitions=num_partitions)
    registry.register_stream_factory(
        f"mem_{topic}", MemoryStreamConsumerFactory(stream,
                                                    batch_size=batch_size))
    return stream


def count_and_sum(cluster):
    resp = cluster.query("SELECT COUNT(*), SUM(runs) FROM baseballStats")
    if resp.exceptions or not resp.aggregation_results:
        return (-1, -1.0)
    return (int(resp.aggregation_results[0].value),
            float(resp.aggregation_results[1].value))


# ---------------------------------------------------------------------------
# tier 1: config + bitmap semantics
# ---------------------------------------------------------------------------


def test_upsert_config_json_roundtrip():
    cfg = upsert_rt_config("f", "t")
    again = TableConfig.from_json_str(cfg.to_json_str())
    assert again.upsert_config is not None
    assert again.upsert_config.enabled
    assert again.upsert_config.primary_key_columns == ["playerName"]
    # absent upsertConfig stays None
    plain = TableConfig.from_json_str(make_table_config().to_json_str())
    assert plain.upsert_config is None


def test_controller_rejects_bad_upsert_configs(work_dir):
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.controller.manager import InvalidTableConfigError
    ctrl = Controller(os.path.join(work_dir, "ds"))
    mgr = ctrl.manager
    # schema must exist first
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(upsert_rt_config("f", "t"))
    mgr.add_schema(make_schema())
    # OFFLINE table cannot upsert
    bad = make_table_config()
    bad.upsert_config = UpsertConfig(mode="FULL",
                                     primary_key_columns=["teamID"])
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(bad)
    # missing / multi-value primary key columns
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(upsert_rt_config("f", "t", pk=("nosuch",)))
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(upsert_rt_config("f", "t", pk=("position",)))
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(upsert_rt_config("f", "t", pk=()))
    # an unrecognized mode must fail loudly, never silently disable dedup
    partial = rt_config("f", "t")
    partial.upsert_config = UpsertConfig(mode="PARTIAL",
                                         primary_key_columns=["teamID"])
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(partial)


def test_valid_doc_ids_default_valid_and_versioned():
    vd = ValidDocIds()
    assert vd.num_invalid == 0
    # docs are valid by default, even past any recorded bit
    assert vd.valid_mask(0, 10).all()
    assert vd.invalidate(3)
    assert not vd.invalidate(3)          # idempotent
    v1 = vd.version
    assert vd.invalidate(40_000)         # growth
    assert vd.version > v1
    m = vd.valid_mask(0, 40_001)
    assert not m[3] and not m[40_000] and m.sum() == 40_001 - 2
    # windowed (tail view) slice
    t = vd.valid_mask(2, 6)
    assert list(t) == [True, False, True, True]
    assert list(vd.invalid_ids(50_000)) == [3, 40_000]


# ---------------------------------------------------------------------------
# tier 2: query masking parity (device scan / sharded / host oracle)
# ---------------------------------------------------------------------------


def _masked_segment(tmp, n, seed, name, kill):
    cols = make_columns(n, seed)
    d = os.path.join(tmp, name)
    os.makedirs(d, exist_ok=True)
    SegmentCreator(make_schema(), make_table_config(),
                   segment_name=name).build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    vd = ValidDocIds()
    rng = np.random.default_rng(seed)
    dead = rng.choice(n, kill, replace=False)
    vd.invalidate_many(dead)
    seg.valid_doc_ids = vd
    alive = np.ones(n, bool)
    alive[dead] = False
    return seg, cols, alive


def test_masked_results_host_vs_device_vs_sharded(work_dir):
    from pinot_tpu.parallel.sharded import ShardedQueryExecutor, make_mesh
    from pinot_tpu.query import host_exec
    from pinot_tpu.query.combine import combine_blocks

    segs, colsets, alives = [], [], []
    for i in range(2):
        seg, cols, alive = _masked_segment(work_dir, 3000, 11 + i,
                                           f"mseg{i}", 300 + 57 * i)
        segs.append(seg)
        colsets.append(cols)
        alives.append(alive)

    dev = ServerQueryExecutor(use_device=True)
    host = ServerQueryExecutor(use_device=False)
    shard = ShardedQueryExecutor(mesh=make_mesh())
    red = BrokerReduceService()

    pqls = [
        "SELECT COUNT(*) FROM baseballStats",
        "SELECT SUM(runs), AVG(hits) FROM baseballStats",
        "SELECT COUNT(*) FROM baseballStats WHERE league = 'AL'",
        "SELECT MIN(runs), MAX(hits) FROM baseballStats "
        "WHERE yearID >= 2000",
        "SELECT SUM(hits) FROM baseballStats WHERE yearID >= 1995 "
        "GROUP BY league, teamID TOP 200",
        "SELECT playerName, runs FROM baseballStats "
        "ORDER BY runs DESC LIMIT 7",
    ]
    for pql in pqls:
        req = compile_pql(pql)
        oracle = combine_blocks(
            req, [host_exec.execute_host(s, req) for s in segs])
        r_dev = red.reduce(req, [dev.execute(req, segs)]).to_json()
        r_host = red.reduce(req, [host.execute(req, segs)]).to_json()
        blk_sh = shard.execute(req, segs)
        r_sh = red.reduce(req, [blk_sh]).to_json()
        r_or = red.reduce(req, [oracle]).to_json()
        for r in (r_dev, r_host, r_sh):
            assert r.get("aggregationResults") == \
                r_or.get("aggregationResults"), (pql, r, r_or)
            assert r.get("selectionResults") == \
                r_or.get("selectionResults"), (pql, r, r_or)

    # COUNT agrees with the python ground truth too
    req = compile_pql("SELECT COUNT(*) FROM baseballStats")
    total = sum(int(a.sum()) for a in alives)
    got = red.reduce(req, [dev.execute(req, segs)])
    assert int(got.aggregation_results[0].value) == total


def test_mask_disables_whole_segment_fast_paths(work_dir):
    from pinot_tpu.query.plan import InstancePlanMaker
    seg, cols, alive = _masked_segment(work_dir, 2000, 3, "fseg", 200)
    maker = InstancePlanMaker()
    # metadata COUNT fast path must NOT fire (it would count dead rows)
    plan = maker.make_segment_plan(
        seg, compile_pql("SELECT COUNT(*) FROM baseballStats"))
    assert plan.fast_path_result is None
    blk = plan.execute()
    assert blk.agg_intermediates[0] == int(alive.sum())
    # inverted-index count fast path must NOT fire either
    plan = maker.make_segment_plan(
        seg, compile_pql(
            "SELECT COUNT(*) FROM baseballStats WHERE teamID = 'BOS'"))
    assert plan.fast_path_result is None
    blk = plan.execute()
    exp = int((alive & (cols["teamID"] == "BOS")).sum())
    assert blk.agg_intermediates[0] == exp
    # a bitmap with ZERO invalidations keeps the fast paths
    seg.valid_doc_ids = ValidDocIds()
    plan = maker.make_segment_plan(
        seg, compile_pql("SELECT COUNT(*) FROM baseballStats"))
    assert plan.fast_path_result is not None


def test_mutable_frozen_tail_boundary_with_straddling_mask():
    """Satellite regression: a tail view taken while the writer appends
    never double-counts or drops rows at the `start` boundary — and a
    validDocIds mask STRADDLING the boundary masks exactly once."""
    seg_impl = __import__("pinot_tpu.realtime.mutable_segment",
                          fromlist=["MutableSegmentImpl"])
    seg = seg_impl.MutableSegmentImpl(make_schema(), make_table_config(),
                                      "cons_upsert")
    seg.valid_doc_ids = ValidDocIds()
    rows = [{"teamID": "BOS", "league": "AL", "playerName": f"p{i}",
             "position": ["P"], "runs": 1, "hits": 1, "average": 0.5,
             "salary": 1.0, "yearID": 2000} for i in range(12_000)]
    for r in rows[:9_000]:
        seg.index_row(r)
    frozen, tail = seg.device_view()
    assert frozen is not None and frozen.num_docs == 9_000
    boundary = frozen.num_docs

    for r in rows[9_000:11_000]:
        seg.index_row(r)
    # mask straddles the frozen/tail boundary
    dead = [boundary - 3, boundary - 1, boundary, boundary + 2]
    for d in dead:
        seg.valid_doc_ids.invalidate(d)

    ex = ServerQueryExecutor()
    red = BrokerReduceService()

    def ask():
        req = compile_pql(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats")
        resp = red.reduce(req, [ex.execute(req, [seg])])
        assert resp.num_segments_processed == 1     # one LOGICAL segment
        return (int(resp.aggregation_results[0].value),
                float(resp.aggregation_results[1].value))

    cnt, s = ask()
    assert cnt == 11_000 - len(dead)
    assert s == cnt                                  # runs == 1 per row

    # now RACE the writer: every snapshot must stay self-consistent
    # (COUNT == SUM) and monotonically include the masked boundary
    stop = threading.Event()

    def writer():
        for r in rows[11_000:]:
            seg.index_row(r)
            if stop.is_set():
                return

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(20):
            cnt, s = ask()
            assert s == cnt, (s, cnt)
            assert 11_000 - len(dead) <= cnt <= 12_000 - len(dead)
    finally:
        stop.set()
        t.join()
    cnt, s = ask()
    assert cnt == 12_000 - len(dead) and s == cnt


# ---------------------------------------------------------------------------
# tier 3: durability units (snapshot + journal + sidecars + fold)
# ---------------------------------------------------------------------------


def _kd(keys_docs):
    return [((k,), d) for k, d in keys_docs]


def test_partition_metadata_snapshot_journal_restore(work_dir):
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    assert p.key_map_size() == 2
    assert p.upserted_rows == 1
    p.seal(0, 3, 3)                       # segment 0 commits
    p.apply_batch(1, _kd([("b", 0), ("c", 1)]), 5)    # consuming seq 1
    p.close()

    # "kill -9": a fresh instance over the same durable directory
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 3
    assert r._map[("a",)] == (0, 2)
    assert r._map[("b",)] == (1, 0)       # journal replay superseded seq 0
    assert r._map[("c",)] == (1, 1)
    # bitmap of the committed segment carries both invalidations:
    # a@0 (in-segment, from the sidecar) and b@1 (cross-segment, from
    # the journal replay)
    vd0 = r.register_consuming(0)
    assert list(vd0.invalid_ids(3)) == [0, 1]
    assert r.snapshot_offset == 3
    assert r.replayed_offset == 5
    r.close()


def test_partition_metadata_torn_journal_tail(work_dir):
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1)]), 2)
    p.close()
    path = os.path.join(work_dir, "journal.jsonl")
    with open(path, "a") as fh:
        fh.write('{"seq": 0, "off": 9, "d": [[["c"')     # torn record
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 2                          # tail dropped
    # the torn bytes were truncated: new appends form valid records
    r.apply_batch(0, _kd([("c", 2)]), 3)
    r.close()
    r2 = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r2.key_map_size() == 3
    r2.close()


def test_key_of_missing_or_unconvertible_values_returns_none(work_dir):
    from pinot_tpu.realtime.upsert import TableUpsertMetadataManager
    mgr = TableUpsertMetadataManager(
        RT_TABLE, UpsertConfig(mode="FULL",
                               primary_key_columns=["runs"]),
        make_schema(), os.path.join(work_dir, "u"))
    assert mgr.key_of({"runs": 5}) == (5,)
    assert mgr.key_of({"runs": "7"}) == (7,)
    assert mgr.key_of({}) is None                    # missing
    assert mgr.key_of({"runs": None}) is None        # explicit null
    assert mgr.key_of({"runs": "xyz"}) is None       # unconvertible
    mgr.close()


def test_poison_primary_key_rows_are_dropped_not_fatal(work_dir):
    """A row whose primary key is missing/unconvertible is dropped like
    any poison record — it must never kill the partition consumer."""
    topic = "topic_poison_pk"
    stream = _register(topic)
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        # NUMERIC pk: unconvertible values exercise the normalizer path
        cluster.add_table(upsert_rt_config(f"mem_{topic}", topic,
                                           flush_rows=100_000,
                                           pk=("yearID",)))
        good = make_rows(60, seed=2)
        for r in good[:30]:
            stream.publish(r, partition=0)
        # poison: unconvertible pk value (the transformer passes it
        # through; int("not-a-year") raises inside key extraction)
        bad = dict(good[0])
        bad["yearID"] = "not-a-year"
        stream.publish(bad, partition=0)
        for r in good[30:]:
            stream.publish(r, partition=0)
        exp = len({r["yearID"] for r in good})
        assert wait_until(
            lambda: count_and_sum(cluster)[0] == exp, timeout=30), \
            count_and_sum(cluster)
        # the consumer survived the poison row and kept consuming
        rdm = cluster.participants["Server_0"].realtime._consuming[
            "baseballStats__0__0"]
        assert rdm.state == "CONSUMING"
    finally:
        cluster.stop()


def test_unterminated_final_journal_line_is_repaired(work_dir):
    """A crash that cuts the write between the record and its newline:
    the record is kept, the terminator repaired — a later append can't
    merge two records into one torn line (which a second recovery would
    drop together with everything after it)."""
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1)]), 2)
    p.close()
    path = os.path.join(work_dir, "journal.jsonl")
    with open(path, "rb+") as fh:
        fh.seek(0, 2)
        fh.truncate(fh.tell() - 1)           # chop the trailing \n
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 2             # the record survived
    r.apply_batch(0, _kd([("c", 2)]), 3)     # next append after repair
    r.close()
    r2 = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r2.key_map_size() == 3            # nothing merged or dropped
    r2.close()


def test_lost_snapshot_forces_fold_despite_sidecars(work_dir):
    """When the key-map snapshot is unreadable, sidecar coverage must
    NOT suppress the fold — otherwise committed segments' keys would
    never re-enter the (empty) map and dedup would silently stop."""
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    p.seal(0, 3, 3)                           # snapshot + sidecar land
    p.close()
    snap = [f for f in os.listdir(work_dir)
            if f.startswith("keymap-") and f.endswith(".json")][0]
    with open(os.path.join(work_dir, snap), "w") as fh:
        fh.write("{ corrupt")
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    folds = []

    class _Seg:
        num_docs = 3

    vd = r.attach_or_fold(0, _Seg(),
                          lambda: folds.append(1) or
                          [("a",), ("b",), ("a",)])
    assert folds, "fold must run when the snapshot is lost"
    assert r.key_map_size() == 2
    assert r._map[("a",)] == (0, 2)
    # sidecar bits are retained (masks never resurrect) and the fold
    # re-derives the same mask
    assert list(vd.invalid_ids(3)) == [0]
    r.close()


def test_committed_segment_fold_when_durable_state_lost(work_dir):
    """The loser-download path: a replica that never consumed the rows
    (no journal, no snapshot) folds the committed segment's primary-key
    column and converges to the exact same mask."""
    from pinot_tpu.realtime.upsert import TableUpsertMetadataManager
    cols = make_columns(1000, seed=5)
    d = os.path.join(work_dir, "seg")
    os.makedirs(d)
    SegmentCreator(make_schema(), make_table_config(),
                   segment_name="baseballStats__0__0").build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    mgr = TableUpsertMetadataManager(
        RT_TABLE, UpsertConfig(mode="FULL",
                               primary_key_columns=["playerName"]),
        make_schema(), os.path.join(work_dir, "upsert"))
    mgr.on_committed_segment("baseballStats__0__0", seg)
    # ground truth: last doc per playerName wins
    last = {}
    for i, name in enumerate(cols["playerName"]):
        last[str(name)] = i
    alive = np.zeros(1000, bool)
    alive[list(last.values())] = True
    got = seg.valid_doc_ids.valid_mask(0, 1000)
    assert (got == alive).all()
    assert mgr.key_map_size() == len(last)
    # a LATER consuming row supersedes a committed doc
    part = mgr.partition(0)
    key = (str(cols["playerName"][0]),)
    part.apply_batch(1, [(key, 0)], 1)
    assert not seg.valid_doc_ids.valid_mask(0, 1000)[last[key[0]]]
    mgr.close()


# ---------------------------------------------------------------------------
# tier 4: kill -9 mid upsert stream → restart → exact convergence
# ---------------------------------------------------------------------------


def _converged(cluster, exp_cnt, exp_sum):
    cluster.controller.realtime.ensure_all_partitions_consuming()
    cnt, s = count_and_sum(cluster)
    return cnt == exp_cnt and s == exp_sum


def _assert_latest_values(cluster, latest, probe=3):
    """Spot-check latest-value convergence per key over a few keys."""
    for name, row in list(latest.items())[:probe]:
        resp = cluster.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats "
            f"WHERE playerName = '{name}'")
        assert not resp.exceptions, resp.exceptions
        assert int(resp.aggregation_results[0].value) == 1, name
        assert float(resp.aggregation_results[1].value) == \
            float(row["runs"]), name


def test_upsert_end_to_end_latest_row_wins(work_dir):
    stream = _register("topic_ups_e2e")
    cluster = EmbeddedCluster(work_dir, num_servers=1,
                              store_dir=os.path.join(work_dir, "store"))
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(upsert_rt_config("mem_topic_ups_e2e",
                                           "topic_ups_e2e",
                                           flush_rows=300))
        rows = make_rows(900, seed=3)
        for r in rows:
            stream.publish(r, partition=0)
        latest = latest_by_key(rows)
        exp_cnt = len(latest)
        exp_sum = float(sum(r["runs"] for r in latest.values()))
        # duplicates span committed AND consuming segments
        assert wait_until(lambda: _converged(cluster, exp_cnt, exp_sum),
                          timeout=40), count_and_sum(cluster)
        mgr = cluster.controller.manager
        done = [s for s in mgr.segment_names(RT_TABLE)
                if (mgr.segment_metadata(RT_TABLE, s) or {}).get(
                    "status") == "DONE"]
        assert len(done) >= 2, "updates must straddle committed segments"
        _assert_latest_values(cluster, latest)
        # obs: the key-map gauge and upsert meters are live
        from pinot_tpu.common.metrics import ServerGauge, ServerMeter
        metrics = cluster.servers["Server_0"].metrics
        assert metrics.gauge(ServerGauge.UPSERT_KEY_MAP_SIZE,
                             RT_TABLE).value == exp_cnt
        assert metrics.meter(ServerMeter.UPSERTED_ROWS,
                             RT_TABLE).count == 900 - exp_cnt
        assert metrics.meter(ServerMeter.MASKED_DOCS,
                             RT_TABLE).count >= 900 - exp_cnt
    finally:
        cluster.stop()


@pytest.mark.parametrize("crash_point", ["upsert.seal",
                                         "upsert.keymap_snapshot",
                                         "upsert.journal_append"])
def test_kill_during_seal_restart_converges(work_dir, crash_point):
    """kill -9 at the seal / mid-snapshot-write / pre-journal-append
    instant: the restarted server rebuilds the key map from snapshots +
    journal + stream tail and converges to exact counts and latest
    values (a batch that died before its journal append was never
    offset-acked, so it is simply re-consumed)."""
    topic = f"topic_{crash_point.split('.')[-1]}"
    stream = _register(topic)
    cluster = EmbeddedCluster(work_dir, num_servers=1,
                              store_dir=os.path.join(work_dir, "store"))
    rows = make_rows(700, seed=7)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(upsert_rt_config(f"mem_{topic}", topic,
                                           flush_rows=250))
        crash_points.arm(crash_point)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: crash_points.fired.get(crash_point),
                          timeout=30), "seal never reached the crash point"
    finally:
        cluster.stop()

    latest = latest_by_key(rows)
    exp_cnt = len(latest)
    exp_sum = float(sum(r["runs"] for r in latest.values()))
    c2 = EmbeddedCluster(work_dir, num_servers=1,
                         store_dir=os.path.join(work_dir, "store"))
    try:
        # 120s like test_restart_does_not_rewind_before_snapshot_offset:
        # kill-restart re-consumption is load-sensitive on a shared CI
        # box; the convergence CONTRACT lives in the exact-count/value
        # assertions, not the wait
        assert wait_until(lambda: _converged(c2, exp_cnt, exp_sum),
                          timeout=120), \
            (count_and_sum(c2), exp_cnt, exp_sum)
        _assert_latest_values(c2, latest)
    finally:
        c2.stop()


def test_kill_during_post_restart_replay_converges(work_dir):
    """Crash DURING recovery (journal replay) on the restarted server:
    a second restart still converges — replay is idempotent."""
    topic = "topic_replaycrash"
    stream = _register(topic)
    cluster = EmbeddedCluster(work_dir, num_servers=1,
                              store_dir=os.path.join(work_dir, "store"))
    rows = make_rows(500, seed=9)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(upsert_rt_config(f"mem_{topic}", topic,
                                           flush_rows=200))
        for r in rows:
            stream.publish(r, partition=0)
        # at least one seal + some journaled consuming rows
        mgr = cluster.controller.manager
        assert wait_until(lambda: any(
            (mgr.segment_metadata(RT_TABLE, s) or {}).get("status")
            == "DONE" for s in mgr.segment_names(RT_TABLE)), timeout=30)
        assert wait_until(
            lambda: count_and_sum(cluster)[0] == len(latest_by_key(rows)),
            timeout=30)
    finally:
        cluster.stop()

    # restart #1 dies mid-replay
    crash_points.arm("upsert.replay")
    c2 = EmbeddedCluster(work_dir, num_servers=1,
                         store_dir=os.path.join(work_dir, "store"))
    try:
        assert wait_until(
            lambda: crash_points.fired.get("upsert.replay"), timeout=30)
    finally:
        c2.stop()

    latest = latest_by_key(rows)
    exp_cnt = len(latest)
    exp_sum = float(sum(r["runs"] for r in latest.values()))
    # restart #2 over the same durable state converges
    c3 = EmbeddedCluster(work_dir, num_servers=1,
                         store_dir=os.path.join(work_dir, "store"))
    try:
        assert wait_until(lambda: _converged(c3, exp_cnt, exp_sum),
                          timeout=120), \
            (count_and_sum(c3), exp_cnt, exp_sum)
        _assert_latest_values(c3, latest)
    finally:
        c3.stop()


def test_restart_does_not_rewind_before_snapshot_offset(work_dir):
    """The checkpoint contract: after a restart, consumption resumes at
    the last committed boundary (== the key-map snapshot offset) — the
    topic is never re-read before it."""
    topic = "topic_noreread"
    stream = _register(topic)
    cluster = EmbeddedCluster(work_dir, num_servers=1,
                              store_dir=os.path.join(work_dir, "store"))
    rows = make_rows(600, seed=13)
    part_dir = os.path.join(work_dir, "server_work", "Server_0",
                            "upsert", RT_TABLE, "partition_0")

    def _snaps():
        # staged .tmp files are a seal caught mid-rename — not durable
        return [f for f in os.listdir(part_dir)
                if f.startswith("keymap-") and f.endswith(".json")]

    try:
        cluster.add_schema(make_schema())
        cluster.add_table(upsert_rt_config(f"mem_{topic}", topic,
                                           flush_rows=250))
        for r in rows:
            stream.publish(r, partition=0)
        mgr = cluster.controller.manager
        assert wait_until(lambda: any(
            (mgr.segment_metadata(RT_TABLE, s) or {}).get("status")
            == "DONE" for s in mgr.segment_names(RT_TABLE)), timeout=30)
        assert wait_until(
            lambda: count_and_sum(cluster)[0] == len(latest_by_key(rows)),
            timeout=30)
        # the seal finishes its key-map snapshot asynchronously after
        # the segment commits — wait for it to land before stopping,
        # or the shutdown races the staged-rename (a crash-equivalent
        # state the RECOVERY tests cover; this test needs the snapshot)
        assert wait_until(lambda: bool(_snaps()), timeout=30), \
            "seal must have written a key-map snapshot"
    finally:
        cluster.stop()

    # durable snapshot offset == the committed boundary
    snaps = _snaps()
    snap = json.load(open(os.path.join(
        part_dir, max(snaps, key=lambda n: int(n[7:-5])))))
    mgr_offsets = []

    c2 = EmbeddedCluster(work_dir, num_servers=1,
                         store_dir=os.path.join(work_dir, "store"))
    try:
        latest = latest_by_key(rows)
        # 120s: restart + journal replay + re-consumption from the
        # snapshot boundary is load-sensitive — on a shared CI box a
        # 60s window flaked while the same run converges in seconds
        # when the box is quiet (the offset assertions below, not this
        # wait, carry the no-rewind contract)
        assert wait_until(lambda: _converged(
            c2, len(latest),
            float(sum(r["runs"] for r in latest.values()))), timeout=120)
        rtdm = c2.participants["Server_0"].realtime
        for seg, rdm in rtdm._consuming.items():
            mgr_offsets.append((seg, rdm))
        # every restarted consumer started AT or AFTER the snapshot
        # offset — zero topic re-reads before it
        mgr = c2.controller.manager
        for seg, _rdm in mgr_offsets:
            meta = mgr.segment_metadata(RT_TABLE, seg)
            assert int(meta["startOffset"]) >= int(snap["offset"]), \
                (seg, meta, snap["offset"])
    finally:
        c2.stop()


def test_stats_history_tolerates_torn_file(work_dir):
    """Satellite: RealtimeSegmentStatsHistory persistence is torn-write
    safe — a corrupt file (or leftover .tmp) loads empty and the next
    save atomically repairs it."""
    from pinot_tpu.realtime.stats_history import RealtimeSegmentStatsHistory
    path = os.path.join(work_dir, "stats_history.json")
    with open(path, "w") as fh:
        fh.write('{"baseballStats_REALTIME": [{"numRo')      # torn
    with open(path + ".tmp", "w") as fh:
        fh.write("{ half a snapshot")
    h = RealtimeSegmentStatsHistory(path)
    assert h.entries(RT_TABLE) == []
    h.add_segment_stats(RT_TABLE, {"numRowsIndexed": 5000, "columns": {}})
    # the save repaired the file: a reload sees the entry
    r = RealtimeSegmentStatsHistory(path)
    assert r.entries(RT_TABLE)[0]["numRowsIndexed"] == 5000
    assert r.estimate(RT_TABLE) == {"rows": 5000}
