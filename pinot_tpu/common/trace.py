"""Per-request trace: named phase spans collected along the query path.

Parity: pinot-core/.../util/trace/TraceContext.java:46 (request-scoped trace
tree enabled by the query's `trace` option, serialized into response
metadata) and the phase timings that BaseBrokerRequestHandler /
ScheduledRequestHandler attach per query. We carry an explicit Trace object
through the call path instead of a thread-registered context — the broker
path is async and the server path hops a scheduler thread pool, so
explicit threading is the honest structure.
"""
from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Dict, List, Optional


class Trace:
    """Ordered (phase → milliseconds) spans for one request."""

    def __init__(self) -> None:
        self.spans: List[Dict[str, object]] = []

    def record(self, name: str, ms: float) -> None:
        self.spans.append({"name": name, "ms": round(ms, 3)})

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3)

    def to_list(self) -> List[Dict[str, object]]:
        return list(self.spans)

    def to_json_str(self) -> str:
        return json.dumps(self.spans)

    @staticmethod
    def from_json_str(s: str) -> "Trace":
        t = Trace()
        t.spans = json.loads(s)
        return t


class NoopTrace(Trace):
    """Zero-cost stand-in when tracing is disabled."""

    def record(self, name: str, ms: float) -> None:
        pass

    @contextmanager
    def span(self, name: str):
        yield


def make_trace(enabled: bool) -> Trace:
    return Trace() if enabled else NoopTrace()
