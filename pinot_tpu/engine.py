"""In-process query engine facade: PQL in, BrokerResponse out.

Parity: the BaseQueriesTest harness pattern
(pinot-core/src/test/.../queries/BaseQueriesTest.java:43-122) — compile →
optimize → per-segment execute → broker reduce, all in one process with no
network/cluster machinery. This is also the building block the server and
broker planes wrap.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.pql.optimizer import BrokerRequestOptimizer
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.query.reduce import BrokerReduceService
from pinot_tpu.segment.loader import ImmutableSegment, ImmutableSegmentLoader


class QueryEngine:
    def __init__(self, segments: Sequence[ImmutableSegment],
                 use_device: bool = True, mesh=None):
        """`mesh`: optional jax.sharding.Mesh — when given, multi-segment
        queries run the sharded executor (segment DP with ICI combine,
        parallel/sharded.py) and fall back to sequential per-segment
        execution when segments aren't homogeneous enough."""
        self.segments = list(segments)
        self.executor = ServerQueryExecutor(use_device=use_device)
        self.sharded = None
        if mesh is not None:
            from pinot_tpu.parallel.sharded import ShardedQueryExecutor
            self.sharded = ShardedQueryExecutor(mesh=mesh)
        self.optimizer = BrokerRequestOptimizer()
        self.reducer = BrokerReduceService()

    @classmethod
    def from_dirs(cls, segment_dirs: Sequence[str], **kw) -> "QueryEngine":
        return cls([ImmutableSegmentLoader.load(d) for d in segment_dirs],
                   **kw)

    def query(self, pql: str) -> BrokerResponse:
        t0 = time.perf_counter()
        request = self.optimizer.optimize(compile_pql(pql))
        from pinot_tpu.query.plan import preprocess_request
        # FASTHLL derived rewrite, once, while the request is still
        # private to this query — the executors preprocess defensively
        # too (on copies), but the rewritten column name must be visible
        # to the reduce for result naming (reference parity)
        request = preprocess_request(self.segments, request)
        block = self._execute(request)
        resp = self.reducer.reduce(request, [block])
        resp.time_used_ms = (time.perf_counter() - t0) * 1e3
        return resp

    def _execute(self, request):
        if self.sharded is not None and len(self.segments) > 1:
            from pinot_tpu.parallel.sharded import NotShardable
            from pinot_tpu.query.plan import (GroupsLimitExceeded,
                                              UnsupportedOnDevice)
            try:
                return self.sharded.execute(request, self.segments)
            except (NotShardable, GroupsLimitExceeded, UnsupportedOnDevice):
                pass
        return self.executor.execute(request, self.segments)
