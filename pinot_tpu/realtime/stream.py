"""Stream ingestion SPI + in-memory stream implementation.

Parity: pinot-core/.../realtime/stream/ — StreamConfig,
StreamConsumerFactory, PartitionLevelConsumer.fetchMessages(startOffset,
endOffset, timeout) (PartitionLevelConsumer.java:41), StreamMetadataProvider
(partition count / offsets), StreamMessageDecoder SPI. The reference ships a
Kafka 0.9 connector; here the bundled implementation is MemoryStream (an
in-process partitioned log, the embedded-Kafka analogue the reference's
tests use) — external connectors plug in via the same factory SPI.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, List, Optional

SMALLEST_OFFSET = "smallest"
LARGEST_OFFSET = "largest"


@dataclasses.dataclass
class StreamConfig:
    topic: str
    consumer_factory: "StreamConsumerFactory"
    decoder: "StreamMessageDecoder"
    offset_criteria: str = SMALLEST_OFFSET
    # consuming-segment end criteria (parity: realtime.segment.flush.*)
    flush_threshold_rows: int = 100_000
    flush_threshold_time_ms: int = 6 * 3600 * 1000
    fetch_timeout_ms: int = 5000


@dataclasses.dataclass
class StreamMessage:
    offset: int
    value: bytes


@dataclasses.dataclass
class MessageBatch:
    messages: List[StreamMessage]
    next_offset: int


class PartitionLevelConsumer:
    def fetch_messages(self, start_offset: int, end_offset: Optional[int],
                       timeout_ms: int) -> MessageBatch:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamMetadataProvider:
    def partition_count(self) -> int:
        raise NotImplementedError

    def fetch_offset(self, partition: int, criteria: str) -> int:
        raise NotImplementedError


class StreamLevelConsumer:
    """High-level (HLC) group consumer SPI (parity:
    core/realtime/stream/StreamLevelConsumer used by
    HLRealtimeSegmentDataManager.java:61): the stream, not the server,
    owns partition assignment; the server just drains messages and
    checkpoints a consumer-group position after each durable flush."""

    def next_messages(self, max_count: int) -> List[StreamMessage]:
        """Up to max_count payload messages across partitions; empty
        list when nothing is available right now."""
        raise NotImplementedError

    def checkpoint(self) -> Dict[int, int]:
        """Current per-partition positions covering every message this
        consumer has returned (persist AFTER the rows are durable)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamConsumerFactory:
    def create_partition_consumer(self, config: StreamConfig,
                                  partition: int) -> PartitionLevelConsumer:
        raise NotImplementedError

    def create_metadata_provider(self, config: StreamConfig
                                 ) -> StreamMetadataProvider:
        raise NotImplementedError

    def create_stream_consumer(self, config: StreamConfig,
                               checkpoint: Optional[Dict[int, int]] = None
                               ) -> StreamLevelConsumer:
        """HLC entry: a group consumer resuming from `checkpoint`
        (per-partition positions) or the config's offset criteria."""
        raise NotImplementedError


class StreamMessageDecoder:
    def decode(self, payload: bytes) -> Optional[dict]:
        """bytes → row dict; None drops the message (parity: decoder
        returning null)."""
        raise NotImplementedError


class JsonMessageDecoder(StreamMessageDecoder):
    def decode(self, payload: bytes) -> Optional[dict]:
        try:
            row = json.loads(payload.decode("utf-8"))
            return row if isinstance(row, dict) else None
        except (ValueError, UnicodeDecodeError):
            return None


# ---------------------------------------------------------------------------
# In-memory stream
# ---------------------------------------------------------------------------


class MemoryStream:
    """A partitioned in-process log: the embedded test/quickstart stream."""

    def __init__(self, topic: str, num_partitions: int = 1):
        self.topic = topic
        self._partitions: List[List[bytes]] = [[] for _ in
                                               range(num_partitions)]
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    def publish(self, row: dict, partition: Optional[int] = None) -> None:
        payload = json.dumps(row).encode("utf-8")
        self.publish_bytes(payload, partition)

    def publish_bytes(self, payload: bytes,
                      partition: Optional[int] = None) -> None:
        with self._lock:
            if partition is None:
                sizes = [len(p) for p in self._partitions]
                partition = sizes.index(min(sizes))
            self._partitions[partition].append(payload)
            self._data.notify_all()

    def latest_offset(self, partition: int) -> int:
        with self._lock:
            return len(self._partitions[partition])

    def wake(self) -> None:
        """Wake long-poll readers (consumer close / shutdown path)."""
        with self._lock:
            self._data.notify_all()

    def read(self, partition: int, start: int, max_count: int,
             timeout_ms: int = 0, stop=None) -> List[StreamMessage]:
        """Long-poll read (Kafka consumer.poll semantics): when nothing
        is available past `start`, block up to timeout_ms for a publish —
        freshness is then publish-driven, not poll-cadence-driven.
        `stop`: zero-arg callable; a True return (after wake()) aborts
        the wait so consumer close never blocks on the full timeout."""
        deadline = time.monotonic() + timeout_ms / 1e3 if timeout_ms else 0
        with self._lock:
            log_part = self._partitions[partition]
            while timeout_ms and len(log_part) <= start and \
                    not (stop is not None and stop()):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._data.wait(remaining):
                    break
            end = min(len(log_part), start + max_count)
            return [StreamMessage(i, log_part[i]) for i in range(start, end)]


class MemoryStreamConsumerFactory(StreamConsumerFactory):
    def __init__(self, stream: MemoryStream, batch_size: int = 1000):
        self.stream = stream
        self.batch_size = batch_size

    def create_partition_consumer(self, config: StreamConfig,
                                  partition: int) -> PartitionLevelConsumer:
        return _MemoryPartitionConsumer(self.stream, partition,
                                        self.batch_size)

    def create_metadata_provider(self, config: StreamConfig
                                 ) -> StreamMetadataProvider:
        return _MemoryMetadataProvider(self.stream)

    def create_stream_consumer(self, config: StreamConfig,
                               checkpoint: Optional[Dict[int, int]] = None
                               ) -> StreamLevelConsumer:
        return _MemoryStreamLevelConsumer(self.stream, config, checkpoint,
                                          self.batch_size)


class _MemoryStreamLevelConsumer(StreamLevelConsumer):
    """Round-robin group consumer over the in-memory log."""

    def __init__(self, stream: MemoryStream, config: StreamConfig,
                 checkpoint: Optional[Dict[int, int]], batch_size: int):
        self.stream = stream
        self.batch_size = batch_size
        self._pos: Dict[int, int] = {}
        for p in range(stream.num_partitions):
            if checkpoint and p in checkpoint:
                self._pos[p] = int(checkpoint[p])
            elif config.offset_criteria == SMALLEST_OFFSET:
                self._pos[p] = 0
            else:
                self._pos[p] = stream.latest_offset(p)
        self._next_part = 0

    def next_messages(self, max_count: int) -> List[StreamMessage]:
        out: List[StreamMessage] = []
        parts = self.stream.num_partitions
        for _ in range(parts):
            if len(out) >= max_count:
                break
            p = self._next_part
            self._next_part = (self._next_part + 1) % parts
            msgs = self.stream.read(p, self._pos[p],
                                    min(self.batch_size,
                                        max_count - len(out)))
            if msgs:
                self._pos[p] = msgs[-1].offset + 1
                out.extend(msgs)
        return out

    def checkpoint(self) -> Dict[int, int]:
        return dict(self._pos)


class _MemoryPartitionConsumer(PartitionLevelConsumer):
    def __init__(self, stream: MemoryStream, partition: int,
                 batch_size: int):
        self.stream = stream
        self.partition = partition
        self.batch_size = batch_size
        self._closed = False

    def fetch_messages(self, start_offset: int, end_offset: Optional[int],
                       timeout_ms: int) -> MessageBatch:
        limit = self.batch_size if end_offset is None else \
            min(self.batch_size, end_offset - start_offset)
        msgs = self.stream.read(self.partition, start_offset,
                                max(limit, 0), timeout_ms=timeout_ms,
                                stop=lambda: self._closed)
        next_off = msgs[-1].offset + 1 if msgs else start_offset
        return MessageBatch(msgs, next_off)

    def close(self) -> None:
        self._closed = True
        self.stream.wake()


class _MemoryMetadataProvider(StreamMetadataProvider):
    def __init__(self, stream: MemoryStream):
        self.stream = stream

    def partition_count(self) -> int:
        return self.stream.num_partitions

    def fetch_offset(self, partition: int, criteria: str) -> int:
        if criteria == SMALLEST_OFFSET:
            return 0
        return self.stream.latest_offset(partition)


class FlakyConsumerFactory(StreamConsumerFactory):
    """Wraps a factory with a consumer that randomly throws / returns
    garbage (parity: FlakyConsumerRealtimeClusterIntegrationTest)."""

    def __init__(self, inner: StreamConsumerFactory, seed: int = 0,
                 failure_rate: float = 0.3):
        self.inner = inner
        self.seed = seed
        self.failure_rate = failure_rate

    def create_partition_consumer(self, config: StreamConfig,
                                  partition: int) -> PartitionLevelConsumer:
        import random
        inner = self.inner.create_partition_consumer(config, partition)
        rng = random.Random(self.seed + partition)

        class Flaky(PartitionLevelConsumer):
            def fetch_messages(self, start, end, timeout_ms):
                roll = rng.random()
                if roll < 0.15:
                    raise RuntimeError("flaky consumer exception")
                batch = inner.fetch_messages(start, end, timeout_ms)
                if roll < 0.3 and batch.messages:
                    # corrupt a message payload
                    m = batch.messages[0]
                    batch.messages[0] = StreamMessage(m.offset, b"\xff garbage")
                return batch

        return Flaky()

    def create_metadata_provider(self, config: StreamConfig
                                 ) -> StreamMetadataProvider:
        return self.inner.create_metadata_provider(config)

    def create_stream_consumer(self, config: StreamConfig,
                               checkpoint: Optional[Dict[int, int]] = None
                               ) -> StreamLevelConsumer:
        import random
        inner = self.inner.create_stream_consumer(config, checkpoint)
        rng = random.Random(self.seed)

        class FlakyHL(StreamLevelConsumer):
            def next_messages(self, max_count):
                roll = rng.random()
                if roll < 0.15:
                    raise RuntimeError("flaky consumer exception")
                msgs = inner.next_messages(max_count)
                if roll < 0.3 and msgs:
                    m = msgs[0]
                    msgs[0] = StreamMessage(m.offset, b"\xff garbage")
                return msgs

            def checkpoint(self):
                return inner.checkpoint()

            def close(self):
                inner.close()

        return FlakyHL()
