"""Networked property store: serve a PropertyStore over framed TCP.

Parity: the ZooKeeper server role in the reference deployment — every
process (controller, brokers, servers, minions) connects to one store
for cluster state, watches push change notifications, and
connection-scoped *ephemeral* paths vanish when their owner disconnects
(ZK ephemeral znodes — the liveness mechanism behind Helix LIVEINSTANCES,
docs/architecture.rst:35-120).

Wire protocol: 4-byte-length JSON frames (same framing as the data plane,
transport/tcp.py). Requests carry an `id` echoed in the response; watch
events are pushed as id-less `{"event": {"path", "record"}}` frames.

Ops: get, set, cas, remove, children, list, watch, unwatch, ping.
`set` takes `"ephemeral": true` to bind the path's lifetime to the
connection.
"""
from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Optional, Set

from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.transport.tcp import read_frame, write_frame

log = logging.getLogger(__name__)


class _Connection:
    """One client: request handling + ordered event/response writer."""

    def __init__(self, server: "PropertyStoreServer",
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.queue: asyncio.Queue = asyncio.Queue()
        self.watched_prefixes: Set[str] = set()
        self.ephemeral_paths: Set[str] = set()
        self._store_watcher = None

    # store watcher callbacks arrive on arbitrary threads
    def on_store_event(self, path: str, record: Optional[dict]) -> None:
        try:
            self.server.loop.call_soon_threadsafe(
                self.queue.put_nowait,
                {"event": {"path": path, "record": record}})
        except RuntimeError:
            pass  # loop already shut down; connection is being reaped

    async def run(self) -> None:
        writer_task = asyncio.create_task(self._drain())
        try:
            while True:
                try:
                    frame = await read_frame(self.reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = None
                try:
                    req = json.loads(frame)
                    resp = self._handle(req)
                except Exception as e:  # noqa: BLE001 — report, keep serving
                    resp = {"id": req.get("id") if isinstance(req, dict)
                            else None, "ok": False, "error": str(e)}
                await self.queue.put(resp)
        finally:
            writer_task.cancel()
            try:
                # let the drain task actually unwind — cancelling and
                # abandoning it leaves a "Task was destroyed but it is
                # pending!" if the loop stops right after
                await writer_task
            except BaseException:  # noqa: BLE001 — incl. our own cancel
                pass
            self._cleanup()

    async def _drain(self) -> None:
        while True:
            msg = await self.queue.get()
            write_frame(self.writer, json.dumps(msg).encode("utf-8"))
            await self.writer.drain()

    def _cleanup(self) -> None:
        store = self.server.store
        if self._store_watcher is not None:
            store.unwatch(self._store_watcher)
        for path in sorted(self.ephemeral_paths):
            store.remove(path)
        self.server.connections.discard(self)
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass

    def _handle(self, req: dict) -> dict:
        store = self.server.store
        op = req["op"]
        rid = req.get("id")
        ok = {"id": rid, "ok": True}
        if op == "ping":
            return ok
        if op == "get":
            return {**ok, "record": store.get(req["path"])}
        if op == "set":
            # the ephemeral flag travels down to the local store so its
            # durability journal skips session-scoped records; a durable
            # write over a once-ephemeral path unbinds it from this
            # session (latest write wins — session death must not remove
            # a record that was made durable afterwards)
            store.set(req["path"], req["record"],
                      ephemeral=bool(req.get("ephemeral")))
            if req.get("ephemeral"):
                self.ephemeral_paths.add(req["path"])
            else:
                self.ephemeral_paths.discard(req["path"])
            return ok
        if op == "cas":
            applied = store.cas(req["path"], req.get("expected"),
                                req["record"],
                                ephemeral=bool(req.get("ephemeral")))
            if applied:
                if req.get("ephemeral"):
                    self.ephemeral_paths.add(req["path"])
                else:
                    self.ephemeral_paths.discard(req["path"])
            return {**ok, "applied": applied}
        if op == "remove":
            existed = store.remove(req["path"])
            self.ephemeral_paths.discard(req["path"])
            return {**ok, "existed": existed}
        if op == "children":
            return {**ok, "result": store.children(req["prefix"])}
        if op == "list":
            return {**ok, "result": store.list_paths(req["prefix"])}
        if op == "watch":
            if self._store_watcher is None:
                # one fan-in watcher per connection; client-side code
                # routes events to per-prefix callbacks
                def fanin(path: str, record: Optional[dict],
                          conn=self) -> None:
                    if any(path.startswith(p)
                           for p in conn.watched_prefixes):
                        conn.on_store_event(path, record)
                self._store_watcher = fanin
                store.watch("", fanin)
            self.watched_prefixes.add(req["prefix"])
            return ok
        if op == "unwatch":
            self.watched_prefixes.discard(req["prefix"])
            return ok
        raise ValueError(f"unknown op {op!r}")


class PropertyStoreServer:
    """Serve `store` on host:port from a daemon event-loop thread."""

    def __init__(self, store: Optional[PropertyStore] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None):
        """`data_dir`: when constructing the store internally, enable
        WAL + snapshot durability under this directory."""
        self.store = store if store is not None else \
            PropertyStore(data_dir=data_dir)
        self.host = host
        self.port = port
        self.connections: Set[_Connection] = set()
        self.loop = asyncio.new_event_loop()
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def start(self) -> int:
        started = threading.Event()
        boot: dict = {"err": None}

        def run() -> None:
            asyncio.set_event_loop(self.loop)
            try:
                self._server = self.loop.run_until_complete(
                    asyncio.start_server(self._serve, self.host, self.port))
            except BaseException as e:  # noqa: BLE001 — surface bind errors
                boot["err"] = e
                started.set()
                return
            self.port = self._server.sockets[0].getsockname()[1]
            started.set()
            self.loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        started.wait()
        if boot["err"] is not None:
            raise OSError(
                f"property store cannot bind {self.host}:{self.port}: "
                f"{boot['err']}") from boot["err"]
        return self.port

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        conn = _Connection(self, reader, writer)
        self.connections.add(conn)
        await conn.run()

    def stop(self) -> None:
        async def shutdown() -> None:
            if self._server is not None:
                self._server.close()
            for conn in list(self.connections):
                conn._cleanup()
            # cancel every connection/drain task and WAIT for it to
            # unwind before stopping the loop: stop() used to race the
            # pending tasks, leaving them "destroyed but pending" and
            # their exceptions unraisable at interpreter shutdown
            tasks = [t for t in asyncio.all_tasks(self.loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(shutdown(), self.loop)
        except RuntimeError:
            return                      # loop already gone
        if self._thread is not None:
            self._thread.join(timeout=5)
        if not self.loop.is_running() and not self.loop.is_closed():
            self.loop.close()
