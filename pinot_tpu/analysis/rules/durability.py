"""durability-order / crash-coverage: the staged-write discipline gate.

Every crash-recovery guarantee in this repo rests on ONE write
discipline (docs/ROBUSTNESS.md): durable state is mutated as

    temp-file write -> flush/fsync (per policy) -> atomic os.replace
    -> only then journal truncate / in-memory publish

and every such mutation carries a seeded crash point so the kill -9
suites can split it. Both halves rot silently — a refactor that moves
the journal truncate above the snapshot rename still passes every
existing test (each test explores one interleaving), and a new durable
mutation without a crash point is simply never killed mid-flight. These
two rules make the discipline mechanical:

- **durability-order** walks each function of the protocol-bearing
  writers (`DURABILITY_FILES`) in statement order and flags: an
  `os.replace` whose staged source was never written; an in-memory
  `self.*` publish between the staged write and its rename; a journal
  truncate (mode-"w" reopen or `.truncate()` of a WAL/journal path)
  that precedes the covering snapshot's rename; a staged file that is
  never renamed; and an in-place rewrite of a durable file that was
  read earlier in the same function (read-modify-write without staging
  — a crash mid-write destroys the only copy).
- **crash-coverage** cross-references three registries: durable-mutation
  functions in `DURABILITY_FILES` must reach a `crash_points.hit`
  (directly, via a one-level self-call, or via every in-file caller);
  every crash point hit anywhere in the tree must be armed by at least
  one test/script; and every name a test arms must exist in the code —
  a renamed point must fail loudly, not silently test nothing.

Both run in the `--protocol` tier; suppressions work exactly like the
AST tier (`# tpulint: disable=durability-order -- <invariant>`).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import (Finding, Rule, is_suppressed,
                                     parse_suppressions, register)

#: the protocol-bearing durable writers the ordering rule audits
DURABILITY_FILES = (
    "pinot_tpu/controller/property_store.py",
    "pinot_tpu/realtime/data_manager.py",
    "pinot_tpu/realtime/upsert.py",
    "pinot_tpu/segment/integrity.py",
)

#: substrings identifying append-only journal/WAL paths
JOURNAL_MARKERS = ("wal", "journal")

_DOTTED_NAME = re.compile(r"^[a-z_][a-z0-9_]*(\.[a-z_][a-z0-9_]*)+$")


# ---------------------------------------------------------------------------
# Shared repo scanning (used by metrics_contract / protocol_check too)
# ---------------------------------------------------------------------------


#: one read+decode of each tree per process — the three protocol-tier
#: rules (and the live-tree tests) share it instead of re-walking the
#: repo per rule. Safe: the CLI is one-shot, and nothing mutates
#: sources on disk mid-run.
_SOURCE_CACHE: Dict[tuple, Dict[str, str]] = {}


def repo_sources(paths, sources: Optional[Dict[str, str]] = None
                 ) -> Dict[str, str]:
    """path -> source for every requested file/tree. `sources` overrides
    the filesystem entirely when given (test fixtures)."""
    if sources is not None:
        return dict(sources)
    key = tuple(paths)
    cached = _SOURCE_CACHE.get(key)
    if cached is not None:
        return dict(cached)
    out: Dict[str, str] = {}
    for p in paths:
        if os.path.isfile(p):
            files = [p]
        elif os.path.isdir(p):
            files = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        else:
            continue
        for f in sorted(files):
            try:
                with open(f, encoding="utf-8") as fh:
                    out[f.replace(os.sep, "/")] = fh.read()
            except OSError:
                continue
    _SOURCE_CACHE[key] = out
    return dict(out)


def missing_audited_files(sources: Dict[str, str], rule_id: str
                          ) -> List[Finding]:
    """A configured durable writer that no longer resolves is itself a
    finding — the anti-rot rule must not rot silently when a refactor
    moves/renames one of the files it audits."""
    return [Finding(path, 1, rule_id,
                    "configured durable writer is missing — a rename/"
                    "move must update DURABILITY_FILES in "
                    "analysis/rules/durability.py or this audit "
                    "silently shrinks")
            for path in DURABILITY_FILES if path not in sources]


def unsuppressed(findings: List[Finding],
                 sources: Dict[str, str]) -> List[Finding]:
    """Apply the standard in-source suppression machinery to global-tier
    findings (the per-file runner only does this for the AST tier)."""
    parsed: Dict[str, Tuple[dict, set]] = {}
    kept = []
    for f in findings:
        src = sources.get(f.path)
        if src is None:
            kept.append(f)
            continue
        if f.path not in parsed:
            parsed[f.path] = parse_suppressions(src)
        per_line, per_file = parsed[f.path]
        if not is_suppressed(f, per_line, per_file):
            kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# Statement-ordered durable-write event extraction
# ---------------------------------------------------------------------------


def _ordered(fn: ast.AST) -> Iterator[ast.AST]:
    for child in ast.iter_child_nodes(fn):
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue            # nested defs are their own functions
        yield from _ordered(child)


from pinot_tpu.analysis.astutil import safe_unparse as _u  # noqa: E402


def _iter_functions(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _open_mode(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    return "r"


def _is_journalish(text: str) -> bool:
    low = text.lower()
    return any(m in low for m in JOURNAL_MARKERS)


def function_events(fn: ast.AST) -> List[Tuple[str, str, int]]:
    """(kind, detail, line) in statement order. Kinds: stage, rename,
    truncate_journal, journal_append, write_open, read_open, publish."""
    tmp_vars: Set[str] = set()
    events: List[Tuple[str, str, int]] = []
    fn_text = _u(fn)
    for node in _ordered(fn):
        line = getattr(node, "lineno", 1)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                ".tmp" in _u(node.value):
            tmp_vars.add(node.targets[0].id)
            continue
        if isinstance(node, ast.Assign) and \
                _u(node.targets[0]).startswith("self.") and \
                "open(" not in _u(node.value):
            events.append(("publish", _u(node.targets[0]), line))
            continue
        if not isinstance(node, ast.Call):
            continue
        text = _u(node)
        func_text = _u(node.func)
        if func_text == "os.replace" and node.args:
            src = _u(node.args[0])
            if src in tmp_vars or ".tmp" in src:
                events.append(("rename", src, line))
            continue
        if func_text.endswith(".truncate") and _is_journalish(fn_text):
            events.append(("truncate_journal", func_text, line))
            continue
        if func_text.endswith(".write") and \
                _is_journalish(func_text):
            events.append(("journal_append", func_text, line))
            continue
        if func_text == "open" and node.args:
            target = _u(node.args[0])
            mode = _open_mode(node)
            if target in tmp_vars or ".tmp" in target:
                if "w" in mode:
                    events.append(("stage", target, line))
            elif "w" in mode and _is_journalish(target):
                events.append(("truncate_journal", target, line))
            elif "a" in mode and _is_journalish(target):
                events.append(("journal_append", target, line))
            elif "w" in mode:
                events.append(("write_open", target, line))
            elif "r" in mode or mode == "r":
                events.append(("read_open", target, line))
            continue
        if "crash_points.hit" in text or "crash_points.consume" in text:
            events.append(("crash_hit", text, line))
    return events


# ---------------------------------------------------------------------------
# durability-order
# ---------------------------------------------------------------------------


def check_durability_order(sources: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(sources):
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        for fn in _iter_functions(tree):
            events = function_events(fn)
            stages = {d: ln for k, d, ln in events if k == "stage"}
            renames = {d: ln for k, d, ln in events if k == "rename"}
            for var, ln in sorted(renames.items()):
                if var not in stages:
                    findings.append(Finding(
                        path, ln, "durability-order",
                        f"`{fn.name}` renames staged file `{var}` that "
                        "was never written in this function — the "
                        "rename publishes bytes whose completeness "
                        "nothing here guarantees"))
                elif stages[var] > ln:
                    findings.append(Finding(
                        path, ln, "durability-order",
                        f"`{fn.name}` renames `{var}` BEFORE the staged "
                        "write — a crash publishes a torn file under "
                        "the durable name"))
            for var, ln in sorted(stages.items()):
                if var not in renames:
                    findings.append(Finding(
                        path, ln, "durability-order",
                        f"`{fn.name}` stages `{var}` but never "
                        "atomically renames it — the durable copy is "
                        "never updated (or is updated non-atomically "
                        "elsewhere)"))
            if stages and renames:
                first_stage = min(stages.values())
                last_rename = max(renames.values())
                for kind, detail, ln in events:
                    if kind == "publish" and first_stage < ln < last_rename:
                        findings.append(Finding(
                            path, ln, "durability-order",
                            f"`{fn.name}` publishes in-memory state "
                            f"`{detail}` before the staged file is "
                            "renamed — a crash leaves memory ahead of "
                            "the durable copy"))
                    if kind == "truncate_journal" and ln < last_rename:
                        findings.append(Finding(
                            path, ln, "durability-order",
                            f"`{fn.name}` truncates a journal before "
                            "the covering snapshot rename is durable — "
                            "a crash in between loses every journaled "
                            "delta (the PR-4/PR-6 write discipline)"))
            reads: Dict[str, int] = {}
            for kind, detail, ln in events:
                if kind == "read_open":
                    reads.setdefault(detail, ln)
                elif kind == "write_open" and detail in reads:
                    findings.append(Finding(
                        path, ln, "durability-order",
                        f"`{fn.name}` rewrites `{detail}` in place "
                        "after reading it (read-modify-write without a "
                        "staged rename) — a crash mid-write destroys "
                        "the only durable copy"))
    return findings


@register
class DurabilityOrderRule(Rule):
    id = "durability-order"
    description = ("staged-write discipline at every durable-mutation "
                   "site: write -> fsync -> atomic rename -> only then "
                   "truncate/publish (protocol tier)")
    tier = "protocol"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        sources = repo_sources(DURABILITY_FILES)
        return (missing_audited_files(sources, self.id) +
                unsuppressed(check_durability_order(sources), sources))


# ---------------------------------------------------------------------------
# crash-coverage
# ---------------------------------------------------------------------------


def collect_crash_points(sources: Dict[str, str]
                         ) -> Dict[str, Tuple[str, int]]:
    """name -> (path, line) for every `crash_points.hit/consume` with a
    literal name anywhere in the given sources."""
    out: Dict[str, Tuple[str, int]] = {}
    for path in sorted(sources):
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _u(node.func).endswith(("crash_points.hit",
                                            "crash_points.consume")) and \
                    node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.setdefault(node.args[0].value,
                               (path, node.lineno))
    return out


def _armed_strings(sources: Dict[str, str],
                   registry: Dict[str, Tuple[str, int]]
                   ) -> Tuple[Set[str], List[Tuple[str, str, int]]]:
    """(strings that appear in tests, suspicious armed-but-unknown
    names). A name counts as armed when it appears as ANY string
    literal in a test/script (parametrize lists feed `arm(point)`
    through a variable, so call-literal matching alone is blind)."""
    seen: Set[str] = set()
    unknown: List[Tuple[str, str, int]] = []
    for path in sorted(sources):
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        consts: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                consts.add(node.value)
            # a literal armed directly, or a literal list/tuple that
            # mixes known and unknown dotted names (a parametrize list
            # with one renamed entry) — the unknowns are findings
            if isinstance(node, ast.Call) and \
                    _u(node.func).endswith(".arm") and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    node.args[0].value not in registry:
                unknown.append((node.args[0].value, path, node.lineno))
            if isinstance(node, (ast.List, ast.Tuple)):
                vals = [e.value for e in node.elts
                        if isinstance(e, ast.Constant) and
                        isinstance(e.value, str)]
                if any(v in registry for v in vals):
                    for v in vals:
                        if v not in registry and _DOTTED_NAME.match(v):
                            unknown.append((v, path, node.lineno))
        seen |= consts
    return seen, unknown


def check_crash_coverage(prod_sources: Dict[str, str],
                         test_sources: Dict[str, str],
                         durability_sources: Dict[str, str]
                         ) -> List[Finding]:
    findings: List[Finding] = []
    registry = collect_crash_points(prod_sources)
    armed, unknown = _armed_strings(test_sources, registry)

    for name in sorted(registry):
        path, line = registry[name]
        if name not in armed:
            findings.append(Finding(
                path, line, "crash-coverage",
                f"crash point `{name}` is armed by no test or smoke "
                "script — the interleaving it splits is never "
                "exercised"))
    for name, path, line in sorted(set(unknown)):
        findings.append(Finding(
            path, line, "crash-coverage",
            f"tests arm unknown crash point `{name}` — the production "
            "hit was renamed or removed, so the test now exercises "
            "nothing"))

    # durable-mutation sites must be crash-splittable
    for path in sorted(durability_sources):
        try:
            tree = ast.parse(durability_sources[path], filename=path)
        except SyntaxError:
            continue
        fns = {fn.name: fn for fn in _iter_functions(tree)}
        events = {name: function_events(fn) for name, fn in fns.items()}
        hits = {name for name, evs in events.items()
                if any(k == "crash_hit" for k, _d, _l in evs)}
        calls: Dict[str, Set[str]] = {}
        for name, fn in fns.items():
            edges = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    t = _u(node.func)
                    ref = t[5:] if t.startswith("self.") else t
                    if ref in fns and ref != name:
                        edges.add(ref)
            calls[name] = edges
        callers: Dict[str, Set[str]] = {}
        for caller, callees in calls.items():
            for c in callees:
                callers.setdefault(c, set()).add(caller)
        durable_kinds = {"stage", "rename", "truncate_journal",
                         "journal_append"}
        for name in sorted(fns):
            evs = events[name]
            durable = [(k, d, ln) for k, d, ln in evs
                       if k in durable_kinds]
            if not durable:
                continue
            covered = (name in hits or
                       any(c in hits for c in calls[name]) or
                       (callers.get(name) and
                        all(c in hits for c in callers[name])))
            if not covered:
                findings.append(Finding(
                    path, durable[0][2], "crash-coverage",
                    f"durable mutation in `{name}` has no reachable "
                    "crash point — kill-restart tests cannot split "
                    "this write sequence (add a crash_points.hit and "
                    "arm it)"))
    return findings


@register
class CrashCoverageRule(Rule):
    id = "crash-coverage"
    description = ("every durable mutation reaches an armed crash "
                   "point; every crash point is armed by a test; no "
                   "test arms a phantom point (protocol tier)")
    tier = "protocol"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        prod = repo_sources(["pinot_tpu"])
        tests = repo_sources(["tests", "scripts"])
        dur = {p: s for p, s in prod.items() if p in DURABILITY_FILES}
        return (missing_audited_files(dur, self.id) +
                unsuppressed(check_crash_coverage(prod, tests, dur),
                             prod))
