"""Batch ingestion: build one segment per input file, push to controller.

Parity: pinot-hadoop — SegmentCreationJob (one mapper per input file runs
the segment build) + SegmentTarPushJob (POST artifacts to the controller).
MapReduce becomes a thread pool; the "push" is the resource manager's
segment upload (or any callable for remote push).
"""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from pinot_tpu.common.schema import Schema, TimeUnit
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.tools.create_segment import create_segment_from_file


def batch_build_segments(
        input_paths: Sequence[str], fmt: str, schema: Schema,
        out_base: str, table_config: Optional[TableConfig] = None,
        segment_name_prefix: Optional[str] = None,
        expressions: Optional[Dict[str, str]] = None,
        incoming_time_unit: Optional[TimeUnit] = None,
        max_workers: int = 4) -> List[str]:
    """Build one segment per input file (parallel); returns segment dirs."""
    prefix = segment_name_prefix or schema.schema_name

    def build(i_path):
        i, path = i_path
        seg_dir = os.path.join(out_base, f"{prefix}_{i}")
        create_segment_from_file(
            path, fmt, schema, seg_dir, table_config,
            segment_name=f"{prefix}_{i}", expressions=expressions,
            incoming_time_unit=incoming_time_unit)
        return seg_dir

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(build, enumerate(input_paths)))


def push_segments(segment_dirs: Sequence[str],
                  push: Callable[[str], str]) -> List[str]:
    """Push built segments (parity: SegmentTarPushJob). `push(seg_dir)` is
    typically `lambda d: manager.add_segment(table, d)` or an HTTP upload."""
    return [push(d) for d in segment_dirs]


def batch_ingest(input_paths: Sequence[str], fmt: str, schema: Schema,
                 out_base: str, table: str, manager,
                 table_config: Optional[TableConfig] = None,
                 **kw) -> List[str]:
    """Build + push in one call against a ResourceManager."""
    dirs = batch_build_segments(input_paths, fmt, schema, out_base,
                                table_config, **kw)
    return push_segments(dirs, lambda d: manager.add_segment(table, d))
