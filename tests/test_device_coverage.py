"""Device-coverage tests for former host-fallback cliffs.

Round-1 verdict called out three UnsupportedOnDevice cliffs (plan.py):
ORDER BY on raw/float columns, order keys past 31-bit packing, and
group-by over no-dictionary columns. These tests pin the new device paths
(monotone-int32 top_k, multi-key lax.sort, raw-value binning) against the
numpy oracle AND against the host executor.
"""
import tempfile

import numpy as np
import pytest

from fixtures import make_columns, make_schema, make_table_config
from oracle import Oracle

from pinot_tpu.engine import QueryEngine
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.plan import InstancePlanMaker, UnsupportedOnDevice
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader

N = 6000


@pytest.fixture(scope="module")
def setup():
    tmp = tempfile.mkdtemp()
    cols = make_columns(N, seed=42)
    # runs raw (no-dictionary int32), salary raw (no-dictionary float32)
    cfg = make_table_config(no_dict=["salary", "runs"])
    SegmentCreator(make_schema(), cfg, segment_name="cov_0").build(cols, tmp)
    segment = ImmutableSegmentLoader.load(tmp)
    engine = QueryEngine([segment])
    host = QueryEngine([segment], use_device=False)
    return segment, engine, host, Oracle(cols)


def _plan(segment, pql):
    return InstancePlanMaker().make_segment_plan(segment, compile_pql(pql))


def _sel_rows(resp):
    return resp.selection_results.results


# -- ORDER BY over raw columns ----------------------------------------------

def test_order_by_raw_float_plans_topk(setup):
    segment, _, _, _ = setup
    plan = _plan(segment, "SELECT salary FROM baseballStats "
                 "ORDER BY salary DESC LIMIT 10")
    assert plan.select_spec[0] == "ordertk"


def test_order_by_raw_float_matches_oracle(setup):
    _, engine, host, oracle = setup
    for e in (engine, host):
        resp = e.query("SELECT salary FROM baseballStats "
                       "ORDER BY salary DESC LIMIT 10")
        got = [float(r[0]) for r in _sel_rows(resp)]
        exp = sorted(oracle.vals("salary", oracle.mask(lambda r: True)),
                     reverse=True)[:10]
        assert got == pytest.approx([float(v) for v in exp])


def test_order_by_raw_int_asc_with_filter(setup):
    segment, engine, host, oracle = setup
    plan = _plan(segment, "SELECT runs FROM baseballStats "
                 "ORDER BY runs LIMIT 15")
    assert plan.select_spec[0] == "ordertk"
    m = oracle.mask(lambda r: r["league"] == "NL")
    exp = sorted(oracle.vals("runs", m))[:15]
    for e in (engine, host):
        resp = e.query("SELECT runs FROM baseballStats WHERE league = 'NL' "
                       "ORDER BY runs LIMIT 15")
        got = [int(r[0]) for r in _sel_rows(resp)]
        assert got == [int(v) for v in exp]


def test_order_by_mixed_dict_and_raw_uses_sort(setup):
    segment, engine, host, oracle = setup
    plan = _plan(segment, "SELECT teamID, salary FROM baseballStats "
                 "ORDER BY teamID, salary DESC LIMIT 25")
    assert plan.select_spec[0] == "ordermk"
    m = oracle.mask(lambda r: True)
    pairs = sorted(zip(oracle.vals("teamID", m), oracle.vals("salary", m)),
                   key=lambda p: (p[0], -float(p[1])))[:25]
    for e in (engine, host):
        resp = e.query("SELECT teamID, salary FROM baseballStats "
                       "ORDER BY teamID, salary DESC LIMIT 25")
        rows = _sel_rows(resp)
        assert [r[0] for r in rows] == [p[0] for p in pairs]
        assert [float(r[1]) for r in rows] == pytest.approx(
            [float(p[1]) for p in pairs])


def test_order_by_wide_dict_key_uses_sort(setup):
    segment, engine, host, oracle = setup
    pql = ("SELECT playerName, average, hits, yearID FROM baseballStats "
           "ORDER BY playerName, average DESC, hits, yearID LIMIT 20")
    plan = _plan(segment, pql)
    # 997 * 1001 * 251 * 31 distinct values ≈ 2^37 — beyond int32 packing
    assert plan.select_spec[0] == "ordermk"
    m = oracle.mask(lambda r: True)
    quads = sorted(zip(oracle.vals("playerName", m),
                       oracle.vals("average", m),
                       oracle.vals("hits", m),
                       oracle.vals("yearID", m)),
                   key=lambda q: (q[0], -q[1], q[2], q[3]))[:20]
    for e in (engine, host):
        resp = e.query(pql)
        rows = _sel_rows(resp)
        assert [r[0] for r in rows] == [q[0] for q in quads]
        assert [float(r[1]) for r in rows] == pytest.approx(
            [float(q[1]) for q in quads])
        assert [int(r[2]) for r in rows] == [int(q[2]) for q in quads]
        assert [int(r[3]) for r in rows] == [int(q[3]) for q in quads]


# -- GROUP BY over no-dictionary columns ------------------------------------

def test_group_by_raw_int_plans_on_device(setup):
    segment, _, _, _ = setup
    plan = _plan(segment, "SELECT COUNT(*) FROM baseballStats "
                 "GROUP BY runs TOP 1000")
    assert plan.group_spec is not None
    (col, kind, off, card), = plan.group_spec[0]
    assert (col, kind) == ("runs", "rawoff")
    assert card >= 1


def test_group_by_raw_int_matches_oracle(setup):
    _, engine, host, oracle = setup
    m = oracle.mask(lambda r: True)
    exp_cnt = oracle.group_by(["runs"], m, ("count", None))
    exp_sum = oracle.group_by(["runs"], m, ("sum", "hits"))
    for e in (engine, host):
        resp = e.query("SELECT COUNT(*), SUM(hits) FROM baseballStats "
                       "GROUP BY runs TOP 1000")
        got_cnt = {g["group"][0]: float(g["value"]) for g in
                   resp.aggregation_results[0].group_by_result}
        got_sum = {g["group"][0]: float(g["value"]) for g in
                   resp.aggregation_results[1].group_by_result}
        assert got_cnt == {int(k[0]): float(v) for k, v in exp_cnt.items()}
        assert got_sum == {int(k[0]): pytest.approx(float(v))
                           for k, v in exp_sum.items()}


def test_group_by_raw_int_with_dict_dim(setup):
    _, engine, host, oracle = setup
    m = oracle.mask(lambda r: r["yearID"] >= 2005)
    exp = oracle.group_by(["league", "runs"], m, ("count", None))
    pql = ("SELECT COUNT(*) FROM baseballStats WHERE yearID >= 2005 "
           "GROUP BY league, runs TOP 2000")
    for e in (engine, host):
        resp = e.query(pql)
        got = {(g["group"][0], int(g["group"][1])): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert got == {(k[0], int(k[1])): float(v) for k, v in exp.items()}


def test_group_by_raw_float_still_falls_back(setup):
    segment, engine, _, oracle = setup
    with pytest.raises(UnsupportedOnDevice):
        _plan(segment, "SELECT COUNT(*) FROM baseballStats "
              "GROUP BY salary TOP 10000")
    # the engine still answers via the host executor
    resp = engine.query("SELECT COUNT(*) FROM baseballStats "
                        "GROUP BY salary TOP 20000")
    total = sum(float(g["value"]) for g in
                resp.aggregation_results[0].group_by_result)
    assert total == N


# -- sharded (mesh) execution of the new paths ------------------------------

@pytest.fixture(scope="module")
def sharded_setup():
    import os
    from fixtures import build_shared_segments
    from pinot_tpu.parallel import make_mesh
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, n_segs=8, n=2048, seed=9)
    engine = QueryEngine(segs, mesh=make_mesh())
    seq = QueryEngine(segs)
    return engine, seq, Oracle(merged)


def test_sharded_order_by_raw_float(sharded_setup):
    engine, seq, oracle = sharded_setup
    pql = ("SELECT salary FROM baseballStats ORDER BY salary DESC LIMIT 12")
    exp = sorted(oracle.vals("salary", oracle.mask(lambda r: True)),
                 reverse=True)[:12]
    for e in (engine, seq):
        got = [float(r[0]) for r in _sel_rows(e.query(pql))]
        assert got == pytest.approx([float(v) for v in exp])


def test_sharded_wide_key_order_by(sharded_setup):
    engine, seq, oracle = sharded_setup
    pql = ("SELECT playerName, average, hits, yearID FROM baseballStats "
           "ORDER BY playerName, average DESC, hits, yearID LIMIT 15")
    m = oracle.mask(lambda r: True)
    quads = sorted(zip(oracle.vals("playerName", m),
                       oracle.vals("average", m),
                       oracle.vals("hits", m),
                       oracle.vals("yearID", m)),
                   key=lambda q: (q[0], -q[1], q[2], q[3]))[:15]
    for e in (engine, seq):
        rows = _sel_rows(e.query(pql))
        assert [r[0] for r in rows] == [q[0] for q in quads]
        assert [float(r[1]) for r in rows] == pytest.approx(
            [float(q[1]) for q in quads])


# -- ranked (wide-key) compacted group-by -----------------------------------

@pytest.fixture(scope="module")
def wide_group_setup():
    """Group-key cross-product past DENSE_G_LIMIT: the kernel must take
    the ranked layout (rank-addressed tables + key lane, host merge)."""
    import os

    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import Schema, dimension, metric
    base = tempfile.mkdtemp()
    rng = np.random.default_rng(5)
    n = 4096
    schema = Schema("w", [dimension("a", DataType.STRING),
                          dimension("b", DataType.STRING),
                          metric("v", DataType.INT),
                          metric("f", DataType.FLOAT)])
    avals = np.array([f"a{i:03d}" for i in range(300)], dtype=object)
    bvals = np.array([f"b{i:03d}" for i in range(250)], dtype=object)
    segs, datas = [], []
    for s in range(4):
        cols = {"a": avals[rng.integers(0, 300, n)],
                "b": bvals[rng.integers(0, 250, n)],
                "v": rng.integers(-50, 100000, n).astype(np.int32),
                "f": rng.random(n).astype(np.float32)}
        d = os.path.join(base, f"w{s}")
        os.makedirs(d)
        SegmentCreator(schema, None, segment_name=f"w{s}",
                       fixed_dictionaries={"a": avals, "b": bvals}
                       ).build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        datas.append(cols)
    merged = {k: np.concatenate([c[k] for c in datas]) for k in datas[0]}
    return segs, merged


def test_wide_key_group_by_takes_ranked_path(wide_group_setup):
    segs, _ = wide_group_setup
    plan = _plan(segs[0], "SELECT SUM(v) FROM w WHERE v >= 0 "
                          "GROUP BY a, b TOP 20000")
    from pinot_tpu.ops.kernels import DENSE_G_LIMIT
    assert plan.group_spec is not None
    assert plan.group_spec[2] > DENSE_G_LIMIT   # g_pad → ranked layout
    assert plan.group_spec[4] > 0               # compacted (kmax set)


def test_wide_key_group_by_matches_oracle(wide_group_setup):
    from pinot_tpu.parallel import make_mesh
    segs, merged = wide_group_setup
    pql = ("SELECT SUM(v), COUNT(*), MIN(v), MAX(v), AVG(f) FROM w "
           "WHERE v >= 0 GROUP BY a, b TOP 20000")
    m = merged["v"] >= 0
    exp_sum, exp_cnt, exp_min, exp_max, exp_favg = {}, {}, {}, {}, {}
    for a, b, v, f, ok in zip(merged["a"], merged["b"], merged["v"],
                              merged["f"], m):
        if not ok:
            continue
        k = (a, b)
        exp_sum[k] = exp_sum.get(k, 0) + int(v)
        exp_cnt[k] = exp_cnt.get(k, 0) + 1
        exp_min[k] = min(exp_min.get(k, 1 << 40), int(v))
        exp_max[k] = max(exp_max.get(k, -(1 << 40)), int(v))
        exp_favg[k] = exp_favg.get(k, 0.0) + float(f)
    for engine in (QueryEngine(segs),
                   QueryEngine(segs, mesh=make_mesh()),
                   QueryEngine(segs, use_device=False)):
        resp = engine.query(pql)
        aggs = resp.aggregation_results
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in aggs[0].group_by_result}
        assert got_sum == {k: float(v) for k, v in exp_sum.items()}
        got_cnt = {tuple(g["group"]): float(g["value"])
                   for g in aggs[1].group_by_result}
        assert got_cnt == {k: float(v) for k, v in exp_cnt.items()}
        got_min = {tuple(g["group"]): float(g["value"])
                   for g in aggs[2].group_by_result}
        assert got_min == {k: float(v) for k, v in exp_min.items()}
        got_max = {tuple(g["group"]): float(g["value"])
                   for g in aggs[3].group_by_result}
        assert got_max == {k: float(v) for k, v in exp_max.items()}
        got_avg = {tuple(g["group"]): float(g["value"])
                   for g in aggs[4].group_by_result}
        for k, tot in exp_favg.items():
            assert got_avg[k] == pytest.approx(tot / exp_cnt[k], rel=1e-5)


def test_adaptive_dense_remap_group_by(wide_group_setup):
    """Filter narrows the active key space: the executor's two-phase
    adaptive path (phase-A histograms → remapped dense tables) must be
    taken and agree with the host executor."""
    from pinot_tpu.parallel import make_mesh
    from pinot_tpu.query.plan import (adaptive_hist_specs,
                                      adaptive_phase_a_specs,
                                      adaptive_phase_b_spec)
    segs, merged = wide_group_setup
    plan = _plan(segs[0], "SELECT SUM(v), COUNT(*) FROM w "
                          "WHERE a BETWEEN 'a100' AND 'a105' "
                          "GROUP BY a, b TOP 20000")
    pa = adaptive_phase_a_specs(plan.group_spec)
    # phase A scouts min/max bounds per dim (streaming-rate)
    assert pa is not None and [s[1] for s in pa] == ["a", "a", "b", "b"]
    assert {s[0] for s in pa} == {"min", "max"}
    # hist rung gating: a selective filter with a small span space skips
    # the histograms (their one-hots are O(rows)); a span space needing
    # the ranked layout dispatches them
    assert adaptive_hist_specs(
        plan.group_spec, [(100, 105), (0, 249)]) is None
    ph = adaptive_hist_specs(plan.group_spec, [(0, 299), (0, 249)])
    assert ph is not None and [s[0] for s in ph] == ["hist", "hist"]
    # simulated scout: a's matched ids contiguous [100..105], b full
    # range — contiguous actives keep the OFFSET remap
    scout = [("present", np.arange(100, 106)),
             ("present", np.arange(0, 250))]
    kspec, fspec, extra, empty = adaptive_phase_b_spec(
        plan.group_spec, scout, matched=2,
        padded=segs[0].padded_docs, total_docs=segs[0].num_docs)
    assert not empty and kspec is not None
    # kernel spec: placeholder offset (literal-stable jit key), bucketed
    # span; finish spec carries the real offset; offsets ride as params
    assert kspec[0][0][1] == "idoff" and kspec[0][0][2] == 0
    assert kspec[0][0][3] == 8                 # span 6 → pow2 bucket
    assert fspec[0][0][2] == 100
    assert tuple(int(x) for x in extra) == (100, 0)
    assert kspec[4] > 0                        # compacted (very selective)
    # same template, different literal → SAME kernel spec (no recompile)
    scout2 = [("present", np.arange(200, 206)),
              ("present", np.arange(0, 250))]
    kspec2, _, extra2, _ = adaptive_phase_b_spec(
        plan.group_spec, scout2, matched=2,
        padded=segs[0].padded_docs, total_docs=segs[0].num_docs)
    assert kspec2 == kspec and tuple(int(x) for x in extra2) == (200, 0)
    # SCATTERED actives: the densifying rank remap collapses the key
    # space to the bucketed present count (8 << pow2-span 128) and ships
    # the rank vector as a runtime operand
    scat = np.array([3, 40, 77, 101, 130], dtype=np.int64)
    kspec3, fspec3, extra3, _ = adaptive_phase_b_spec(
        plan.group_spec, [("present", scat), ("present", np.arange(250))],
        matched=2, padded=segs[0].padded_docs, total_docs=segs[0].num_docs)
    assert kspec3[0][0][1] == "idrank" and kspec3[0][0][3] == 8
    assert np.array_equal(fspec3[0][0][2], scat)
    rank = np.asarray(extra3[0])
    assert rank[scat[2]] == 2 and rank[scat[-1]] == len(scat) - 1
    # same-shape scattered literal → same kernel spec (rank is operand)
    scat2 = scat + 7
    kspec4, _, _, _ = adaptive_phase_b_spec(
        plan.group_spec, [("present", scat2), ("present", np.arange(250))],
        matched=2, padded=segs[0].padded_docs, total_docs=segs[0].num_docs)
    assert kspec4 == kspec3
    # barely-selective: the cost model flips to the direct dense layout
    dense_spec, _, _, _ = adaptive_phase_b_spec(
        plan.group_spec, scout, matched=2000,
        padded=segs[0].padded_docs, total_docs=segs[0].num_docs)
    assert dense_spec[4] == 0

    pql = ("SELECT SUM(v), COUNT(*) FROM w WHERE a BETWEEN 'a100' AND "
           "'a105' GROUP BY a, b TOP 20000")
    m = (merged["a"] >= "a100") & (merged["a"] <= "a105")
    exp = {}
    for a, b, v, ok in zip(merged["a"], merged["b"], merged["v"], m):
        if ok:
            k = (a, b)
            e = exp.setdefault(k, [0, 0])
            e[0] += int(v)
            e[1] += 1
    for engine in (QueryEngine(segs),
                   QueryEngine(segs, mesh=make_mesh()),
                   QueryEngine(segs, use_device=False)):
        resp = engine.query(pql)
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[0].group_by_result}
        got_cnt = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_sum == {k: float(v[0]) for k, v in exp.items()}
        assert got_cnt == {k: float(v[1]) for k, v in exp.items()}


def test_rank_remap_scattered_actives_end_to_end(wide_group_setup):
    """IN-filter selecting SCATTERED dict ids + group-by on the same
    column: phase A's histogram finds the present set, the rank remap
    collapses the key space, and results must match the host executor
    (the q3.1-class regression: non-contiguous actives made offset spans
    4-8x wider than the active set)."""
    from pinot_tpu.parallel import make_mesh
    segs, merged = wide_group_setup
    picks = ["a003", "a091", "a155", "a202", "a249"]   # scattered ids
    lst = ", ".join(f"'{p}'" for p in picks)
    pql = (f"SELECT SUM(v), COUNT(*) FROM w WHERE a IN ({lst}) "
           "GROUP BY a, b TOP 20000")
    m = np.isin(merged["a"], picks)
    exp = {}
    for a, b, v, ok in zip(merged["a"], merged["b"], merged["v"], m):
        if ok:
            e = exp.setdefault((a, b), [0, 0])
            e[0] += int(v)
            e[1] += 1
    for engine, label in ((QueryEngine(segs), "device"),
                          (QueryEngine(segs, mesh=make_mesh()), "mesh"),
                          (QueryEngine(segs, use_device=False), "host")):
        resp = engine.query(pql)
        assert not resp.exceptions, (label, resp.exceptions)
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[0].group_by_result}
        got_cnt = {tuple(g["group"]): int(g["value"])
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_sum == {k: float(v[0]) for k, v in exp.items()}, label
        assert got_cnt == {k: v[1] for k, v in exp.items()}, label


def test_mv_group_by_takes_device_path(wide_group_setup):
    """MV dictionary group keys plan as 'mvids' (kernel row expansion,
    aggregateGroupByMV parity) — no host fallback, and the device,
    mesh, and host paths agree."""
    import os
    import tempfile

    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (FieldSpec, FieldType, Schema,
                                         dimension, metric)
    from pinot_tpu.parallel import make_mesh

    base = tempfile.mkdtemp()
    rng = np.random.default_rng(9)
    n = 4096
    schema = Schema("mvw", [dimension("k", DataType.STRING),
                            FieldSpec("tags", DataType.STRING,
                                      FieldType.DIMENSION,
                                      single_value=False),
                            metric("v", DataType.INT)])
    kvals = np.array([f"k{i:02d}" for i in range(40)], dtype=object)
    tvals = np.array([f"t{i:02d}" for i in range(12)], dtype=object)
    segs, datas = [], []
    for s in range(2):
        cols = {"k": kvals[rng.integers(0, 40, n)],
                "tags": [list(rng.choice(tvals, rng.integers(1, 4),
                                         replace=False))
                         for _ in range(n)],
                "v": rng.integers(0, 1000, n).astype(np.int32)}
        d = os.path.join(base, f"s{s}")
        os.makedirs(d)
        SegmentCreator(schema, None, segment_name=f"mvw{s}",
                       fixed_dictionaries={"k": kvals, "tags": tvals}
                       ).build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        datas.append(cols)

    pql = ("SELECT COUNT(*), SUM(v) FROM mvw WHERE v >= 100 "
           "GROUP BY tags, k TOP 5000")
    plan = _plan(segs[0], pql)
    assert plan.group_spec is not None
    assert [g[1] for g in plan.group_spec[0]] == ["mvids", "ids"]

    exp = {}
    for cols in datas:
        for lst, k, v in zip(cols["tags"], cols["k"], cols["v"]):
            if v >= 100:
                for t in lst:
                    e = exp.setdefault((t, k), [0, 0])
                    e[0] += 1
                    e[1] += int(v)
    for engine, label in ((QueryEngine(segs), "device"),
                          (QueryEngine(segs, mesh=make_mesh()), "mesh"),
                          (QueryEngine(segs, use_device=False), "host")):
        resp = engine.query(pql)
        assert not resp.exceptions, (label, resp.exceptions)
        got_cnt = {tuple(g["group"]): int(float(g["value"]))
                   for g in resp.aggregation_results[0].group_by_result}
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_cnt == {k: v[0] for k, v in exp.items()}, label
        assert got_sum == {k: float(v[1]) for k, v in exp.items()}, label


def test_valuein_group_key_takes_device_path(tmp_path):
    """valuein(mvcol, ...) group keys plan as 'mvin' — the kernel's MV
    row expansion masks disallowed entries via a runtime member vector;
    device, mesh, and host paths agree, and a different literal set
    reuses the same kernel spec."""
    import os

    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (FieldSpec, FieldType, Schema,
                                         metric)
    from pinot_tpu.parallel import make_mesh

    rng = np.random.default_rng(13)
    n = 4096
    schema = Schema("vw", [FieldSpec("tags", DataType.STRING,
                                     FieldType.DIMENSION,
                                     single_value=False),
                           metric("v", DataType.INT)])
    tvals = np.array([f"t{i:02d}" for i in range(16)], dtype=object)
    segs, datas = [], []
    for s in range(2):
        cols = {"tags": [list(rng.choice(tvals, rng.integers(1, 4),
                                         replace=False))
                         for _ in range(n)],
                "v": rng.integers(0, 1000, n).astype(np.int32)}
        d = str(tmp_path / f"s{s}")
        os.makedirs(d)
        SegmentCreator(schema, None, segment_name=f"vw{s}",
                       fixed_dictionaries={"tags": tvals}).build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        datas.append(cols)

    pql = ("SELECT COUNT(*), SUM(v) FROM vw WHERE v >= 100 "
           "GROUP BY valuein(tags, 't03', 't07', 't12') TOP 100")
    plan = _plan(segs[0], pql)
    assert [g[1] for g in plan.group_spec[0]] == ["mvin"]
    pql2 = pql.replace("'t03', 't07', 't12'", "'t01', 't15'")
    # same template, different literals → identical kernel group spec
    assert _plan(segs[0], pql2).group_spec == plan.group_spec

    def oracle(allowed):
        exp = {}
        for cols in datas:
            for lst, v in zip(cols["tags"], cols["v"]):
                if v >= 100:
                    for t in lst:
                        if t in allowed:
                            e = exp.setdefault((t,), [0, 0])
                            e[0] += 1
                            e[1] += int(v)
        return exp

    for engine, label in ((QueryEngine(segs), "device"),
                          (QueryEngine(segs, mesh=make_mesh()), "mesh"),
                          (QueryEngine(segs, use_device=False), "host")):
        # BOTH literal sets execute (the second reuses the compiled
        # executable with a different member-vector operand)
        for q, allowed in ((pql, {"t03", "t07", "t12"}),
                           (pql2, {"t01", "t15"})):
            exp = oracle(allowed)
            resp = engine.query(q)
            assert not resp.exceptions, (label, resp.exceptions)
            got_cnt = {tuple(g["group"]): int(float(g["value"]))
                       for g in resp.aggregation_results[0].group_by_result}
            got_sum = {tuple(g["group"]): float(g["value"])
                       for g in resp.aggregation_results[1].group_by_result}
            assert got_cnt == {k: v[0] for k, v in exp.items()}, (label, q)
            assert got_sum == {k: float(v[1])
                               for k, v in exp.items()}, (label, q)
