"""Combine per-segment result blocks into one per-server block.

Parity: pinot-core/.../operator/CombineOperator.java (selection/agg merge via
CombineService) and CombineGroupByOperator.java:107-156 (concurrent group map
merge) + AggregationGroupByTrimmingService.java:44 (trim to
max(5·topN, 5000) when the merged map passes 4× that size).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from pinot_tpu.common.request import BrokerRequest, SelectionSort
from pinot_tpu.query.aggregation import AggregationFunction, make_functions
from pinot_tpu.query.blocks import IntermediateResultsBlock


def trim_size_for(top_n: int) -> int:
    return max(5 * top_n, 5000)


def combine_blocks(request: BrokerRequest,
                   blocks: List[IntermediateResultsBlock]
                   ) -> IntermediateResultsBlock:
    if not blocks:
        return IntermediateResultsBlock()
    out = blocks[0]
    functions = make_functions(request.aggregations) \
        if request.is_aggregation else []
    for blk in blocks[1:]:
        _merge_into(request, functions, out, blk)
        out.stats.merge(blk.stats)
        out.exceptions.extend(blk.exceptions)
    if request.is_group_by and out.group_map is not None:
        t = trim_size_for(request.group_by.top_n)
        if len(out.group_map) > 4 * t:
            out.group_map = trim_group_map(out.group_map, functions, t)
    if request.is_selection and out.selection_rows is not None:
        _trim_selection(request, out)
    return out


def _merge_into(request: BrokerRequest,
                functions: List[AggregationFunction],
                a: IntermediateResultsBlock,
                b: IntermediateResultsBlock) -> None:
    if request.is_group_by:
        if a.group_map is None:
            a.group_map = b.group_map or {}
        elif b.group_map:
            for key, inters in b.group_map.items():
                mine = a.group_map.get(key)
                if mine is None:
                    a.group_map[key] = inters
                else:
                    a.group_map[key] = [f.merge(x, y) for f, x, y in
                                        zip(functions, mine, inters)]
    elif request.is_aggregation:
        if a.agg_intermediates is None:
            a.agg_intermediates = b.agg_intermediates
        elif b.agg_intermediates is not None:
            a.agg_intermediates = [
                f.merge(x, y) for f, x, y in
                zip(functions, a.agg_intermediates, b.agg_intermediates)]
    if request.is_selection:
        if a.selection_rows is None:
            a.selection_rows = b.selection_rows
            a.selection_columns = b.selection_columns
            a.selection_display_cols = b.selection_display_cols
        elif b.selection_rows:
            a.selection_rows = merge_selection_rows(
                request, a.selection_columns, a.selection_rows,
                b.selection_rows)


def vector_order_key(columns: List[str]):
    """Merge order for vector-similarity rows: score desc, then
    (segment, docId) asc — total and deterministic, so every merge
    topology (frozen+tail pair, per-server combine, broker reduce)
    produces the same top-k as one global pass."""
    si = columns.index("$score")
    ni = columns.index("$segmentName")
    di = columns.index("$docId")

    def key(row: tuple):
        return (-row[si], row[ni], row[di])

    return key


def merge_selection_rows(request: BrokerRequest, columns: List[str],
                         rows_a: List[tuple], rows_b: List[tuple]
                         ) -> List[tuple]:
    sel = request.selection
    limit = sel.offset + sel.size
    merged = list(rows_a) + list(rows_b)
    if request.vector is not None:
        merged.sort(key=vector_order_key(columns))
    elif sel.order_by:
        merged.sort(key=_order_key(sel.order_by, columns))
    return merged[:limit]


def _order_key(order_by: List[SelectionSort], columns: List[str]):
    idx = {c: i for i, c in enumerate(columns)}

    def key(row: tuple):
        parts = []
        for ob in order_by:
            v = row[idx[ob.column]]
            parts.append(_Rev(v) if not ob.ascending else v)
        return tuple(parts)

    return key


class _Rev:
    """Reverse-order wrapper for mixed-type sort keys."""
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


def trim_group_map(group_map: Dict[Tuple, List],
                   functions: List[AggregationFunction],
                   trim_size: int) -> Dict[Tuple, List]:
    """Keep the union of per-function top-`trim_size` groups (value desc).

    Parity: AggregationGroupByTrimmingService sorts per function and keeps
    the heads, so a group surviving under ANY function survives the trim.
    """
    keep = set()
    keys = list(group_map.keys())
    for fi, f in enumerate(functions):
        scored = sorted(
            keys, key=lambda k: f.sortable_final(group_map[k][fi]),
            reverse=True)
        keep.update(scored[:trim_size])
    return {k: group_map[k] for k in keep}


def _trim_selection(request: BrokerRequest,
                    out: IntermediateResultsBlock) -> None:
    sel = request.selection
    limit = sel.offset + sel.size
    rows = out.selection_rows
    if not rows:
        out.selection_rows = []
        return
    if request.vector is not None:
        rows = sorted(rows, key=vector_order_key(out.selection_columns))
    elif sel.order_by:
        rows = sorted(rows, key=_order_key(sel.order_by,
                                           out.selection_columns))
    out.selection_rows = rows[:limit]
