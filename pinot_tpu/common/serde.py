"""Wire serde: request JSON tree + typed binary object serde.

Parity: pinot-common's Thrift request serialization (request.thrift via
TCompactProtocol, ScheduledRequestHandler.java:63) and the typed object
serde registry (core/common/ObjectSerDeUtils.java:55-83 — AvgPair,
MinMaxRangePair, HLL, percentile maps...). We use JSON for the request tree
(control-plane friendly, schema evolvable) and a compact tagged binary
format for result objects (sets/maps/pairs cross the server→broker wire in
DataTable cells).
"""
from __future__ import annotations

import json
import struct
from typing import Any, List, Optional

from pinot_tpu.common.request import (AggregationInfo, BrokerRequest,
                                      FilterOperator, FilterQueryTree,
                                      GroupBy, HavingNode, InstanceRequest,
                                      JoinSpec, QueryOptions, Selection,
                                      SelectionSort, VectorSimilarity,
                                      WindowSpec)
from pinot_tpu.common.sketches import HyperLogLog, TDigest

# ---------------------------------------------------------------------------
# Request JSON
# ---------------------------------------------------------------------------


def filter_to_json(n: Optional[FilterQueryTree]) -> Optional[dict]:
    if n is None:
        return None
    return {
        "op": n.operator.value, "col": n.column, "vals": n.values,
        "children": [filter_to_json(c) for c in n.children],
        "lo": n.lower, "hi": n.upper,
        "loInc": n.lower_inclusive, "hiInc": n.upper_inclusive,
    }


def filter_from_json(d: Optional[dict]) -> Optional[FilterQueryTree]:
    if d is None:
        return None
    return FilterQueryTree(
        operator=FilterOperator(d["op"]), column=d.get("col"),
        values=d.get("vals") or [],
        children=[filter_from_json(c) for c in d.get("children") or []],
        lower=d.get("lo"), upper=d.get("hi"),
        lower_inclusive=d.get("loInc", True),
        upper_inclusive=d.get("hiInc", True))


def _having_to_json(n: Optional[HavingNode]) -> Optional[dict]:
    if n is None:
        return None
    return {
        "op": n.operator.value,
        "agg": None if n.agg is None else
        {"fn": n.agg.function_name, "col": n.agg.column},
        "vals": n.values,
        "children": [_having_to_json(c) for c in n.children],
        "lo": n.lower, "hi": n.upper,
        "loInc": n.lower_inclusive, "hiInc": n.upper_inclusive,
    }


def _having_from_json(d: Optional[dict]) -> Optional[HavingNode]:
    if d is None:
        return None
    agg = d.get("agg")
    return HavingNode(
        operator=FilterOperator(d["op"]),
        agg=None if agg is None else AggregationInfo(agg["fn"], agg["col"]),
        values=d.get("vals") or [],
        children=[_having_from_json(c) for c in d.get("children") or []],
        lower=d.get("lo"), upper=d.get("hi"),
        lower_inclusive=d.get("loInc", True),
        upper_inclusive=d.get("hiInc", True))


def request_to_json(r: BrokerRequest) -> dict:
    return {
        "table": r.table_name,
        "filter": filter_to_json(r.filter),
        "aggregations": [{"fn": a.function_name, "col": a.column}
                         for a in r.aggregations],
        "groupBy": None if r.group_by is None else
        {"columns": r.group_by.columns, "topN": r.group_by.top_n},
        "selection": None if r.selection is None else {
            "columns": r.selection.columns,
            "orderBy": [{"col": s.column, "asc": s.ascending}
                        for s in r.selection.order_by],
            "offset": r.selection.offset, "size": r.selection.size},
        # optional vector-similarity clause (absent pre-vector payloads
        # parse unchanged; older peers ignore the extra key)
        "vector": None if r.vector is None else {
            "col": r.vector.column,
            "q": [float(x) for x in r.vector.query],
            "k": r.vector.k, "metric": r.vector.metric,
            "nprobe": r.vector.nprobe},
        # optional multi-stage clauses (same version-skew contract)
        "join": None if r.join is None else {
            "dimTable": r.join.dim_table,
            "factKey": r.join.fact_key, "dimKey": r.join.dim_key,
            "dimFilter": filter_to_json(r.join.dim_filter),
            "dimColumns": list(r.join.dim_columns)},
        "windows": [{
            "fn": w.function, "col": w.column,
            "partitionBy": list(w.partition_by),
            "orderBy": [{"col": s.column, "asc": s.ascending}
                        for s in w.order_by]} for w in r.windows],
        "having": _having_to_json(r.having),
        "options": {"trace": r.query_options.trace,
                    "timeoutMs": r.query_options.timeout_ms,
                    "debug": r.query_options.debug_options,
                    "options": r.query_options.options},
        "limit": r.limit,
    }


def request_from_json(d: dict) -> BrokerRequest:
    sel = d.get("selection")
    gb = d.get("groupBy")
    vec = d.get("vector")
    jn = d.get("join")
    opts = d.get("options") or {}
    return BrokerRequest(
        table_name=d["table"],
        filter=filter_from_json(d.get("filter")),
        aggregations=[AggregationInfo(a["fn"], a["col"])
                      for a in d.get("aggregations") or []],
        group_by=None if gb is None else GroupBy(gb["columns"], gb["topN"]),
        selection=None if sel is None else Selection(
            columns=sel["columns"],
            order_by=[SelectionSort(s["col"], s["asc"])
                      for s in sel.get("orderBy") or []],
            offset=sel.get("offset", 0), size=sel.get("size", 10)),
        vector=None if vec is None else VectorSimilarity(
            column=vec["col"], query=list(vec["q"]),
            k=vec.get("k", 10), metric=vec.get("metric", "COSINE"),
            nprobe=int(vec.get("nprobe", 0))),
        join=None if jn is None else JoinSpec(
            dim_table=jn["dimTable"], fact_key=jn["factKey"],
            dim_key=jn["dimKey"],
            dim_filter=filter_from_json(jn.get("dimFilter")),
            dim_columns=list(jn.get("dimColumns") or [])),
        windows=[WindowSpec(
            function=w["fn"], column=w.get("col"),
            partition_by=list(w.get("partitionBy") or []),
            order_by=[SelectionSort(s["col"], s["asc"])
                      for s in w.get("orderBy") or []])
            for w in d.get("windows") or []],
        having=_having_from_json(d.get("having")),
        query_options=QueryOptions(
            trace=opts.get("trace", False),
            timeout_ms=opts.get("timeoutMs"),
            debug_options=opts.get("debug") or {},
            options=opts.get("options") or {}),
        limit=d.get("limit", 10))


def instance_request_to_bytes(r: InstanceRequest) -> bytes:
    d = {
        "requestId": r.request_id,
        "query": request_to_json(r.query),
        "searchSegments": r.search_segments,
        "enableTrace": r.enable_trace,
        "brokerId": r.broker_id,
    }
    if r.deadline_budget_ms is not None:
        # optional key: payloads from older brokers stay parseable and
        # payloads to older servers are ignored, not rejected
        d["deadlineBudgetMs"] = r.deadline_budget_ms
    if r.trace_id is not None:
        # optional for the same version-skew reason: the tracing
        # context only travels when the query is traced
        d["traceId"] = r.trace_id
        d["parentSpanId"] = r.parent_span_id
    if r.workload is not None:
        # optional: a tenant tag from a newer broker is scheduling
        # advice an older server simply ignores
        d["workload"] = r.workload
    if r.hedge:
        d["hedge"] = True
    if r.publish_exchange is not None:
        # multi-stage exchange plane (optional keys, version-skew safe):
        # a stage-1 producer publishes its result under the exchange id;
        # a stage-2 consumer fetches the listed peer blocks first
        d["publishExchange"] = r.publish_exchange
    if r.exchange_sources is not None:
        d["exchangeSources"] = r.exchange_sources
    return json.dumps(d).encode("utf-8")


def instance_request_from_bytes(b: bytes) -> InstanceRequest:
    d = json.loads(b.decode("utf-8"))
    return InstanceRequest(
        request_id=d["requestId"],
        query=request_from_json(d["query"]),
        search_segments=d.get("searchSegments"),
        enable_trace=d.get("enableTrace", False),
        broker_id=d.get("brokerId", ""),
        deadline_budget_ms=d.get("deadlineBudgetMs"),
        trace_id=d.get("traceId"),
        parent_span_id=d.get("parentSpanId"),
        workload=d.get("workload"),
        hedge=d.get("hedge", False),
        publish_exchange=d.get("publishExchange"),
        exchange_sources=d.get("exchangeSources"))


# ---------------------------------------------------------------------------
# Typed binary object serde (DataTable cells / aggregation intermediates)
#
# Tags: N null, B bool, i int64, I bigint(str), d float64, s str, b bytes,
#       t tuple, l list, S set, D dict (sorted by key bytes for determinism),
#       H HyperLogLog, T TDigest (sketch custom objects —
#       ObjectSerDeUtils.ObjectType HyperLogLog/TDigest parity)
# ---------------------------------------------------------------------------

_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")


def obj_to_bytes(v: Any) -> bytes:
    out = bytearray()
    _write_obj(out, v)
    return bytes(out)


def obj_from_bytes(b) -> Any:
    """`b`: any buffer (bytes / memoryview) — the zero-copy DataTable
    decode path hands frame memoryviews straight in."""
    v, off = _read_obj(b, 0)
    return v


def _write_obj(out: bytearray, v: Any) -> None:
    import numpy as np
    if isinstance(v, np.generic):
        v = v.item()
    if v is None:
        out += b"N"
    elif isinstance(v, bool):
        out += b"B"
        out += b"\x01" if v else b"\x00"
    elif isinstance(v, int):
        if -(2**63) <= v < 2**63:
            out += b"i"
            out += _I64.pack(v)
        else:
            s = str(v).encode()
            out += b"I"
            out += _U32.pack(len(s))
            out += s
    elif isinstance(v, float):
        out += b"d"
        out += _F64.pack(v)
    elif isinstance(v, str):
        s = v.encode("utf-8")
        out += b"s"
        out += _U32.pack(len(s))
        out += s
    elif isinstance(v, bytes):
        out += b"b"
        out += _U32.pack(len(v))
        out += v
    elif isinstance(v, tuple):
        out += b"t"
        out += _U32.pack(len(v))
        for x in v:
            _write_obj(out, x)
    elif isinstance(v, list):
        out += b"l"
        out += _U32.pack(len(v))
        for x in v:
            _write_obj(out, x)
    elif isinstance(v, (set, frozenset)):
        items = [obj_to_bytes(x) for x in v]
        items.sort()
        out += b"S"
        out += _U32.pack(len(items))
        for ib in items:
            out += ib
    elif isinstance(v, dict):
        items = sorted((obj_to_bytes(k), obj_to_bytes(x))
                       for k, x in v.items())
        out += b"D"
        out += _U32.pack(len(items))
        for kb, vb in items:
            out += kb
            out += vb
    elif isinstance(v, HyperLogLog):
        payload = v.to_bytes()
        out += b"H"
        out += _U32.pack(len(payload))
        out += payload
    elif isinstance(v, TDigest):
        payload = v.to_bytes()
        out += b"T"
        out += _U32.pack(len(payload))
        out += payload
    else:
        raise TypeError(f"unserializable object type {type(v)}")


def _read_obj(b, off: int):
    # str(buf, "utf-8") decodes bytes AND memoryview slices — .decode()
    # exists only on bytes, and the zero-copy frame path passes views
    tag = b[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"B":
        return b[off] != 0, off + 1
    if tag == b"i":
        return _I64.unpack_from(b, off)[0], off + 8
    if tag == b"I":
        n = _U32.unpack_from(b, off)[0]
        off += 4
        return int(str(b[off:off + n], "ascii")), off + n
    if tag == b"d":
        return _F64.unpack_from(b, off)[0], off + 8
    if tag == b"s":
        n = _U32.unpack_from(b, off)[0]
        off += 4
        return str(b[off:off + n], "utf-8"), off + n
    if tag == b"b":
        n = _U32.unpack_from(b, off)[0]
        off += 4
        return bytes(b[off:off + n]), off + n
    if tag in (b"t", b"l"):
        n = _U32.unpack_from(b, off)[0]
        off += 4
        items: List[Any] = []
        for _ in range(n):
            v, off = _read_obj(b, off)
            items.append(v)
        return (tuple(items) if tag == b"t" else items), off
    if tag == b"S":
        n = _U32.unpack_from(b, off)[0]
        off += 4
        out = set()
        for _ in range(n):
            v, off = _read_obj(b, off)
            out.add(v)
        return out, off
    if tag == b"D":
        n = _U32.unpack_from(b, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _read_obj(b, off)
            v, off = _read_obj(b, off)
            d[k] = v
        return d, off
    if tag in (b"H", b"T"):
        n = _U32.unpack_from(b, off)[0]
        off += 4
        cls = HyperLogLog if tag == b"H" else TDigest
        return cls.from_bytes(bytes(b[off:off + n])), off + n
    raise ValueError(f"bad object tag {tag!r} at {off - 1}")
