"""Record transformer chain: raw reader rows → schema-conformant rows.

Parity: pinot-core/.../core/data/recordtransformer/ — CompoundTransformer
composing ExpressionTransformer (derived columns), TimeTransformer
(incoming → schema time granularity), DataTypeTransformer (type coercion,
SV/MV normalization), NullValueTransformer (default fill) and
SanitationTransformer (string cleanup), in that order.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.schema import FieldType, Schema, TimeUnit

MAX_STRING_LENGTH = 512          # parity: SanitationTransformer trim length


class RecordTransformer:
    def transform(self, row: dict) -> Optional[dict]:
        """Returns the transformed row, or None to drop the record."""
        raise NotImplementedError


class ExpressionTransformer(RecordTransformer):
    """Derive columns from transform expressions over other fields.

    Parity: ExpressionTransformer / FunctionExpressionEvaluator — the
    reference evaluates Groovy-ish expressions per record; here the shared
    transform-function registry (common/expression.py) is used.
    """

    def __init__(self, expressions: Dict[str, str]):
        self.expressions = {col: expr_mod.parse_expression(text)
                            for col, text in expressions.items()}

    def transform(self, row: dict) -> Optional[dict]:
        for out_col, expr in self.expressions.items():
            if row.get(out_col) is not None:
                continue        # already provided by the source
            try:
                val = expr_mod.evaluate(
                    expr, lambda c: np.asarray([row[c]]))
                if isinstance(val, np.ndarray):
                    val = val.ravel()[0]
                row[out_col] = val.item() if hasattr(val, "item") else val
            except (KeyError, TypeError, ValueError):
                row[out_col] = None
        return row


class TimeTransformer(RecordTransformer):
    """Convert the incoming time value to the schema's time unit."""

    def __init__(self, schema: Schema,
                 incoming_unit: Optional[TimeUnit] = None):
        tc = schema.time_column
        self.column = tc.name if tc else None
        self.out_ms = (tc.time_unit.value * max(tc.time_unit_size, 1)
                       ) if tc and tc.time_unit else None
        self.in_unit = incoming_unit

    def transform(self, row: dict) -> Optional[dict]:
        if self.column is None or self.in_unit is None or \
                self.out_ms is None or self.in_unit.value == self.out_ms:
            return row
        v = row.get(self.column)
        if v is None:
            return row
        ms = self.in_unit.to_millis(int(v))
        row[self.column] = int(ms // self.out_ms)
        return row


class DataTypeTransformer(RecordTransformer):
    """Coerce every schema column to its declared type; normalize SV/MV
    shapes (scalars wrapped into lists for MV fields, singleton lists
    unwrapped for SV fields)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def transform(self, row: dict) -> Optional[dict]:
        from pinot_tpu.common.datatype import DataType
        for f in self.schema.fields:
            v = row.get(f.name)
            if v is None:
                continue
            if f.data_type == DataType.VECTOR:
                # the list payload IS the embedding — never unwrap it
                # like an accidentally-listed scalar
                row[f.name] = f.convert(v)
            elif f.single_value:
                if isinstance(v, (list, tuple)):
                    v = v[0] if v else None
                row[f.name] = None if v is None else f.convert(v)
            else:
                vs = v if isinstance(v, (list, tuple)) else [v]
                row[f.name] = [f.convert(x) for x in vs if x is not None]
        return row


class NullValueTransformer(RecordTransformer):
    """Fill missing/None values with the field's default null value."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def transform(self, row: dict) -> Optional[dict]:
        for f in self.schema.fields:
            v = row.get(f.name)
            if f.single_value:
                if v is None:
                    row[f.name] = f.default_null_value
            else:
                if not v:
                    row[f.name] = [f.default_null_value]
        return row


class SanitationTransformer(RecordTransformer):
    """Clean string values: strip NUL characters, clamp length."""

    def __init__(self, schema: Schema,
                 max_length: int = MAX_STRING_LENGTH):
        self.schema = schema
        self.max_length = max_length

    def _clean(self, v):
        if isinstance(v, str):
            if "\x00" in v:
                v = v.replace("\x00", "")
            if len(v) > self.max_length:
                v = v[: self.max_length]
        return v

    def transform(self, row: dict) -> Optional[dict]:
        for f in self.schema.fields:
            v = row.get(f.name)
            if isinstance(v, list):
                row[f.name] = [self._clean(x) for x in v]
            else:
                row[f.name] = self._clean(v)
        return row


class CompoundTransformer(RecordTransformer):
    """The standard chain, in the reference's order."""

    def __init__(self, schema: Schema,
                 expressions: Optional[Dict[str, str]] = None,
                 incoming_time_unit: Optional[TimeUnit] = None):
        self.chain: List[RecordTransformer] = []
        if expressions:
            self.chain.append(ExpressionTransformer(expressions))
        self.chain.append(TimeTransformer(schema, incoming_time_unit))
        self.chain.append(DataTypeTransformer(schema))
        self.chain.append(NullValueTransformer(schema))
        self.chain.append(SanitationTransformer(schema))

    def transform(self, row: dict) -> Optional[dict]:
        for t in self.chain:
            row = t.transform(row)
            if row is None:
                return None
        return row
