"""Segment completion protocol: consuming-server ↔ controller messages.

Parity: pinot-common/.../protocols/SegmentCompletionProtocol.java:50-117 —
message types segmentConsumed / segmentCommitStart / segmentCommitEnd and
response statuses HOLD / CATCHUP / DISCARD / KEEP / COMMIT /
COMMIT_SUCCESS / COMMIT_CONTINUE / FAILED. Servers report their stream
offset when a consuming segment hits its end criteria; the controller's
completion FSM elects a committer and steers every replica.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

# response statuses (SegmentCompletionProtocol.ControllerResponseStatus)
HOLD = "HOLD"                       # keep the built rows, re-poll soon
CATCHUP = "CATCHUP"                 # consume up to `offset`, then re-poll
DISCARD = "DISCARD"                 # drop local rows; committed copy will
#                                     arrive via the ONLINE transition
KEEP = "KEEP"                       # local rows match the committed end
COMMIT = "COMMIT"                   # you are the committer: build + upload
COMMIT_SUCCESS = "COMMIT_SUCCESS"
COMMIT_CONTINUE = "COMMIT_CONTINUE"
PROCESSED = "PROCESSED"             # extendBuildTime granted
FAILED = "FAILED"


@dataclasses.dataclass
class CompletionResponse:
    status: str
    offset: Optional[int] = None    # CATCHUP target / committed end offset

    def to_json(self) -> dict:
        return {"status": self.status, "offset": self.offset}

    @classmethod
    def from_json(cls, d: dict) -> "CompletionResponse":
        return cls(d["status"], d.get("offset"))
