"""Rule modules — importing this package registers every rule."""
from pinot_tpu.analysis.rules import (api_compat, async_safety,
                                      concurrency, deep, dtype_drift,
                                      host_sync, lock_order, retrace)

__all__ = ["api_compat", "async_safety", "concurrency", "deep",
           "dtype_drift", "host_sync", "lock_order", "retrace"]
