"""Interprocedural scaffolding for the deep analysis tier.

Everything here is ONE-LEVEL interprocedural and file-local by design:
rules follow `self.method()` calls (and bare module-function calls) one
hop from the body being analyzed, which is the deepest reasoning an AST
linter can do without whole-program import resolution — and, measured
against this codebase, exactly the depth at which the real hazards live
(a consume loop calling its own `_flush`, a scatter path calling its own
`_call_once`).

Three capabilities, shared by the lock-order, async-safety and upgraded
concurrency rules:

- **Method/function index** per class and per module, with a shallow
  call-edge map (`self.x()` → method, `f()` → module function).
- **Thread-entry-point map**: which methods run on which kind of thread.
  Detected syntactically: `threading.Thread(target=self.m)` and
  `threading.Timer`, `<pool>.submit(self.m)`, `loop.run_in_executor(_,
  self.m)`, `loop.call_soon*(self.m)`, `fut.add_done_callback(self.m)`
  mark `m` as a SPAWNED root; `async def` methods are LOOP roots; every
  other non-underscore method is an EXTERNAL root (callable from any
  caller thread — scheduler pools, HTTP handler threads). Private
  methods inherit the roots of their callers (fixpoint).
- **Lock tracking**: which `self.<attr>` / module-global names hold
  `threading.Lock/RLock/Condition` objects, and which lock set is held
  at any statement (with-statements plus explicit `.acquire()` /
  `.release()`, scanned in statement order).
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pinot_tpu.analysis import astutil

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}

#: attribute names whose callable argument runs on another THREAD:
#: (attr name → index of the callable argument)
_THREAD_SPAWN_ATTRS = {
    "submit": 0,              # Executor.submit(fn, ...)
    "run_in_executor": 1,     # loop.run_in_executor(executor, fn, ...)
}

#: attribute names whose callable argument runs as an EVENT-LOOP
#: callback (on the loop thread — create_task is legal inside these)
_LOOP_CALLBACK_ATTRS = {
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "call_later": 1,          # loop.call_later(delay, fn)
    "add_done_callback": 0,
}

_SPAWN_ATTRS = {**_THREAD_SPAWN_ATTRS, **_LOOP_CALLBACK_ATTRS}

#: resolved dotted ctors whose keyword/positional arg is a thread target
_THREAD_CTORS = {"threading.Thread", "threading.Timer"}


def _callable_ref(node: ast.AST) -> Optional[str]:
    """`self.m` → 'm'; bare `f` → 'f'; anything else → None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _spawned_via(tree: ast.AST, aliases: Dict[str, str],
                 attrs: Dict[str, int], thread_ctors: bool) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = astutil.resolve(node.func, aliases)
        if thread_ctors and callee in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = _callable_ref(kw.value)
                    if ref:
                        out.add(ref)
            # Timer(interval, fn) positional
            if callee == "threading.Timer" and len(node.args) >= 2:
                ref = _callable_ref(node.args[1])
                if ref:
                    out.add(ref)
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in attrs:
            idx = attrs[node.func.attr]
            if len(node.args) > idx:
                ref = _callable_ref(node.args[idx])
                if ref:
                    out.add(ref)
    return out


def spawned_callables(tree: ast.AST, aliases: Dict[str, str]) -> Set[str]:
    """Names of methods/functions handed to a thread/loop-callback API
    anywhere under `tree`."""
    return _spawned_via(tree, aliases, _SPAWN_ATTRS, thread_ctors=True)


def thread_spawned_callables(tree: ast.AST,
                             aliases: Dict[str, str]) -> Set[str]:
    """Names handed to a genuinely-other-THREAD API (Thread/Timer
    targets, Executor.submit, run_in_executor) — excludes loop-callback
    registration, which runs on the event-loop thread."""
    return _spawned_via(tree, aliases, _THREAD_SPAWN_ATTRS,
                        thread_ctors=True)


def loop_callback_callables(tree: ast.AST,
                            aliases: Dict[str, str]) -> Set[str]:
    """Names handed to a LOOP-scheduling API (call_soon*, call_later,
    add_done_callback) — these run on the event-loop thread, so
    create_task/ensure_future are legal inside them."""
    return _spawned_via(tree, aliases, _LOOP_CALLBACK_ATTRS,
                        thread_ctors=False)


def lock_attrs_of(cls: ast.ClassDef, aliases: Dict[str, str]) -> Set[str]:
    """self.X assigned a Lock/RLock/Condition anywhere in the class.
    `threading.Condition(self._lock)` aliases the SAME underlying lock;
    both names count as declared locks (holding either is holding)."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                astutil.resolve(node.value.func, aliases) in LOCK_CTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    locks.add(tgt.attr)
    return locks


def module_locks(tree: ast.Module, aliases: Dict[str, str]) -> Set[str]:
    """Module-global names bound to a Lock/RLock/Condition at top level."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Call) and \
                astutil.resolve(stmt.value.func, aliases) in LOCK_CTORS:
            out.update(t.id for t in stmt.targets
                       if isinstance(t, ast.Name))
    return out


def lock_of_expr(node: ast.AST, self_locks: Set[str],
                 global_locks: Set[str]) -> Optional[str]:
    """Lock identifier for an expression, or None.

    `self.X` (declared) → 'self.X'; bare global lock name → 'G'.
    """
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self" \
            and node.attr in self_locks:
        return f"self.{node.attr}"
    if isinstance(node, ast.Name) and node.id in global_locks:
        return node.id
    return None


@dataclasses.dataclass
class ClassModel:
    """Per-class view: methods, locks, thread roots, call edges."""

    node: ast.ClassDef
    methods: Dict[str, ast.AST]
    lock_attrs: Set[str]
    #: method → roots it can run under. Root spellings:
    #:   "spawn:<m>"  — m is a detected thread/callback target
    #:   "loop"       — async method (event-loop context)
    #:   "ext:<m>"    — public method m, callable from any thread
    roots: Dict[str, Set[str]]
    #: method → self-methods it calls (shallow, own body only)
    calls: Dict[str, Set[str]]

    def resolve_call(self, call: ast.Call) -> Optional[ast.AST]:
        """The method body a `self.m(...)` call lands in, if local."""
        ref = None
        if isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Name) and \
                call.func.value.id == "self":
            ref = call.func.attr
        return self.methods.get(ref) if ref else None


#: construction-time methods (happens-before publish) — the single
#: source of truth; rules import this instead of re-declaring it
INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__",
                          "__init_subclass__", "__set_name__"})
_INIT_METHODS = INIT_METHODS


def build_class_model(cls: ast.ClassDef, aliases: Dict[str, str]
                      ) -> ClassModel:
    methods: Dict[str, ast.AST] = {
        m.name: m for m in cls.body
        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    spawned = thread_spawned_callables(cls, aliases) & set(methods)
    loop_cbs = loop_callback_callables(cls, aliases) & set(methods)
    calls: Dict[str, Set[str]] = {}
    for name, m in methods.items():
        edges: Set[str] = set()
        for node in astutil.walk_shallow(m):
            if isinstance(node, ast.Call):
                ref = _callable_ref(node.func)
                if ref in methods:
                    edges.add(ref)
        calls[name] = edges

    roots: Dict[str, Set[str]] = {name: set() for name in methods}
    for name, m in methods.items():
        if name in _INIT_METHODS:
            # construction happens-before publish: the "init" root
            # propagates to helpers called only from __init__ so they
            # are recognizable as construction-time (never invented as
            # external thread paths), then discounted by the rules
            roots[name].add("init")
            continue
        # the categories are NOT exclusive: a public method that is
        # also a Thread target runs on both the spawned thread and any
        # caller thread — it carries both roots, which is exactly what
        # makes a single-method two-thread race detectable
        if name in spawned:
            roots[name].add(f"spawn:{name}")
        if isinstance(m, ast.AsyncFunctionDef) or name in loop_cbs:
            # loop-callback targets (call_soon*, add_done_callback) run
            # ON the event-loop thread — same context as async methods,
            # never a separate thread root
            roots[name].add("loop")
        if not name.startswith("_") and not \
                isinstance(m, ast.AsyncFunctionDef):
            roots[name].add(f"ext:{name}")
        # properties named like attributes are public too (no underscore
        # check already covers them); underscore methods start rootless
        # and inherit below.
    # propagate roots caller → callee to fixpoint (graphs are tiny)
    changed = True
    while changed:
        changed = False
        for caller, callees in calls.items():
            for callee in callees:
                if callee in _INIT_METHODS:
                    continue
                add = roots[caller] - roots[callee]
                if add:
                    roots[callee] |= add
                    changed = True
    return ClassModel(node=cls, methods=methods, lock_attrs=lock_attrs_of(
        cls, aliases), roots=roots, calls=calls)


def iter_class_models(tree: ast.Module, aliases: Dict[str, str]
                      ) -> Iterator[ClassModel]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield build_class_model(node, aliases)


# ---------------------------------------------------------------------------
# Lock-held statement walk
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Site:
    """One interesting statement with the lock set held when it runs."""

    node: ast.AST
    held: Tuple[str, ...]          # sorted lock ids held at this point
    acquires: Optional[str] = None  # lock id this site acquires, if any


def walk_with_locks(fn: ast.AST, self_locks: Set[str],
                    global_locks: Set[str]) -> List[Site]:
    """Every shallow node of `fn` paired with the locks held at it.

    Handles nested `with` (incl. multi-item) and explicit
    `.acquire()`/`.release()` in statement order. Not a CFG — a release
    inside one branch is treated as releasing for the rest of the
    body, which under-reports at worst (a linter must not over-hold).
    """
    out: List[Site] = []
    held: List[str] = []

    def lock_of(expr: ast.AST) -> Optional[str]:
        return lock_of_expr(expr, self_locks, global_locks)

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return                  # nested defs judged in their own scope
        if isinstance(node, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in node.items:
                lk = lock_of(item.context_expr)
                if lk is not None:
                    out.append(Site(item.context_expr,
                                    tuple(sorted(held)), acquires=lk))
                    held.append(lk)
                    entered.append(lk)
                else:
                    visit(item.context_expr)
            for stmt in node.body:
                visit(stmt)
            for lk in reversed(entered):
                # the body may have explicitly release()d the with'd
                # lock (temporary-release pattern) — already gone then
                if lk in held:
                    held.remove(lk)
            return
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            lk = lock_of(node.func.value)
            if lk is not None and node.func.attr == "acquire":
                out.append(Site(node, tuple(sorted(held)), acquires=lk))
                held.append(lk)
                return
            if lk is not None and node.func.attr == "release":
                if lk in held:
                    held.remove(lk)
                return
        out.append(Site(node, tuple(sorted(held))))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in ast.iter_child_nodes(fn):
        visit(stmt)
    return out


# ---------------------------------------------------------------------------
# Blocking-call classification (shared by lock-blocking / async-blocking)
# ---------------------------------------------------------------------------

#: resolved dotted calls that block the calling thread
BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "subprocess.Popen": "subprocess.Popen",
    "socket.create_connection": "socket.create_connection",
    "socket.getaddrinfo": "socket.getaddrinfo",
    "urllib.request.urlopen": "urllib.request.urlopen",
    "requests.get": "requests.get",
    "requests.post": "requests.post",
    "requests.request": "requests.request",
    "os.system": "os.system",
    "os.fsync": "os.fsync",
    "jax.device_get": "jax.device_get",
}


def blocking_kind(node: ast.AST, aliases: Dict[str, str]
                  ) -> Optional[str]:
    """A short description when `node` is a blocking call, else None.

    Awaitables are the caller's business (`ast.Await` is matched by the
    rules directly — an await under a threading lock parks the lock for
    a whole scheduling round-trip; an await NOT under a lock is normal
    asyncio).
    """
    if not isinstance(node, ast.Call):
        return None
    callee = astutil.resolve(node.func, aliases)
    if callee in BLOCKING_CALLS:
        return BLOCKING_CALLS[callee]
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        return "open() file IO"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr == "result":
            return "Future.result()"
        if attr in ("recv", "sendall", "makefile") and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in ("sock", "s", "conn"):
            # conventional socket variable names; receiver types are
            # invisible to an AST linter, so this is deliberately narrow
            return f"socket.{attr}()"
    return None
