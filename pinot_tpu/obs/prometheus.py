"""Prometheus text exposition for a MetricsRegistry.

Parity: the reference exports its Yammer registry through
JmxReporterMetricsRegistryRegistrationListener (operators scrape JMX →
Prometheus); PAPERS.md's Monarch/Prometheus lineage is the pull model
this module implements directly — every component (broker, server,
controller) serves `GET /metrics` in the text exposition format
(version 0.0.4).

Naming: ``pinot_<component>_<snake_case_metric>`` with the registry's
table/server suffix emitted as a ``table`` label (the reference's
addMeteredTableValue table-suffix convention becomes a proper label).
Meters render as counters (``_total``), gauges as gauges, timers as
histograms over the registry's bounded log-scale millisecond buckets
plus ``_sum``/``_count``.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from pinot_tpu.common.metrics import MetricsRegistry, Timer

_INVALID = re.compile(r"[^a-zA-Z0-9_]")
_CAMEL = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _snake(name: str) -> str:
    return _INVALID.sub("_", _CAMEL.sub("_", name)).lower()


def _split_key(key: str) -> Tuple[Optional[str], str]:
    """Registry keys are ``<table>.<metric>`` or bare ``<metric>``
    (MetricsRegistry._get); metric names never contain dots."""
    if "." in key:
        table, name = key.rsplit(".", 1)
        return table, name
    return None, key


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_pairs(table: Optional[str]) -> List[str]:
    """Label assignments for a registry table suffix. A plain suffix is
    the reference's table-level convention (→ ``table`` label); a
    ``<table>|<kind>`` suffix (the residency gauges) splits into
    ``table`` + ``kind`` labels, empty parts omitted. A ``tier:<tier>``
    kind part (the residency manager's per-tier twins) renders as a
    ``tier`` label instead of a kind."""
    if table is None:
        return []
    if "|" in table:
        tbl, kind = table.split("|", 1)
        pairs = []
        if tbl:
            pairs.append(f'table="{_escape_label(tbl)}"')
        if kind.startswith("tier:"):
            pairs.append(f'tier="{_escape_label(kind[5:])}"')
        elif kind:
            pairs.append(f'kind="{_escape_label(kind)}"')
        return pairs
    return [f'table="{_escape_label(table)}"']


def _labels(table: Optional[str]) -> str:
    pairs = _label_pairs(table)
    return "{%s}" % ",".join(pairs) if pairs else ""


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: MetricsRegistry,
                      namespace: str = "pinot") -> str:
    """The full registry as Prometheus text exposition."""
    component = _snake(registry.component or "component")
    prefix = f"{namespace}_{component}"
    meters, gauges, timers = registry.metric_maps()

    # group series sharing a metric name under ONE # TYPE header
    by_name: Dict[str, dict] = {}

    def series(name: str, mtype: str):
        e = by_name.get(name)
        if e is None:
            e = by_name[name] = {"type": mtype, "lines": []}
        return e["lines"]

    for key, m in sorted(meters.items()):
        table, name = _split_key(key)
        full = f"{prefix}_{_snake(name)}_total"
        series(full, "counter").append(
            f"{full}{_labels(table)} {m.count}")
    for key, g in sorted(gauges.items()):
        table, name = _split_key(key)
        full = f"{prefix}_{_snake(name)}"
        series(full, "gauge").append(
            f"{full}{_labels(table)} {_fmt(float(g.value))}")
    for key, t in sorted(timers.items()):
        table, name = _split_key(key)
        full = f"{prefix}_{_snake(name)}_ms"
        lines = series(full, "histogram")
        pairs = _label_pairs(table)
        tl = "".join(p + "," for p in pairs)
        cumulative = 0
        counts = t.bucket_counts()          # len(BOUNDS) + 1 (overflow)
        bounds = [_fmt(b) for b in Timer.BUCKET_BOUNDS_MS] + ["+Inf"]
        for le, n in zip(bounds, counts):
            cumulative += n
            lines.append(f'{full}_bucket{{{tl}le="{le}"}} {cumulative}')
        suffix = _labels(table)
        lines.append(f"{full}_sum{suffix} {_fmt(round(t.total_ms, 3))}")
        lines.append(f"{full}_count{suffix} {t.count}")

    out: List[str] = []
    for name, entry in by_name.items():
        out.append(f"# TYPE {name} {entry['type']}")
        out.extend(entry["lines"])
    return "\n".join(out) + ("\n" if out else "")


#: the content type Prometheus scrapers expect for 0.0.4 exposition
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
